"""State-sync reactor: four channels, server + client plumbing.

Channel layout from the reference (internal/statesync/reactor.go:36-45):
Snapshot(0x60) discovery/offers, Chunk(0x61) chunk fetch,
LightBlock(0x62) header+valset serving for the state provider and
backfill, Params(0x63) consensus params at height. The server side
answers every request from the local app/stores; the client side routes
responses into the syncer's queues (syncer.py owns the sync logic).

Wire format: 1 tag byte + struct-packed fields + proto payloads.
"""

from __future__ import annotations

import struct
import threading
from typing import Callable, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.p2p.router import Channel, Envelope, Router
from tendermint_tpu.types.light import LightBlock, SignedHeader
from tendermint_tpu.types.params import (
    consensus_params_from_proto_bytes,
    consensus_params_to_proto_bytes,
)

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61
LIGHT_BLOCK_CHANNEL = 0x62
PARAMS_CHANNEL = 0x63

TAG_SNAPSHOTS_REQUEST = 1
TAG_SNAPSHOTS_RESPONSE = 2
TAG_CHUNK_REQUEST = 3
TAG_CHUNK_RESPONSE = 4
TAG_LIGHT_BLOCK_REQUEST = 5
TAG_LIGHT_BLOCK_RESPONSE = 6
TAG_PARAMS_REQUEST = 7
TAG_PARAMS_RESPONSE = 8

# Cap served snapshots per request (reference recentSnapshots = 10).
RECENT_SNAPSHOTS = 10
# Cap chunk size accepted from the wire (16 MB, reference chunk limits).
MAX_CHUNK_BYTES = 16 << 20


def encode_snapshots_response(s: abci.Snapshot) -> bytes:
    return (
        bytes([TAG_SNAPSHOTS_RESPONSE])
        + struct.pack(">qiiB", s.height, s.format, s.chunks, len(s.hash))
        + s.hash
        + s.metadata
    )


def decode_snapshots_response(payload: bytes) -> abci.Snapshot:
    height, format_, chunks, hlen = struct.unpack_from(">qiiB", payload)
    off = struct.calcsize(">qiiB")
    return abci.Snapshot(
        height=height,
        format=format_,
        chunks=chunks,
        hash=payload[off : off + hlen],
        metadata=payload[off + hlen :],
    )


class StateSyncReactor:
    def __init__(
        self,
        router: Router,
        app_client,
        block_store=None,
        state_store=None,
    ):
        self.app = app_client
        self.block_store = block_store
        self.state_store = state_store
        self.snapshot_ch = router.open_channel(SNAPSHOT_CHANNEL)
        self.chunk_ch = router.open_channel(CHUNK_CHANNEL)
        self.light_ch = router.open_channel(LIGHT_BLOCK_CHANNEL)
        self.params_ch = router.open_channel(PARAMS_CHANNEL)
        self._stop_flag = threading.Event()
        self._threads = []
        # Client-side sinks, installed by the syncer while it runs.
        self.on_snapshot: Optional[Callable] = None  # (peer, Snapshot)
        self.on_chunk: Optional[Callable] = None  # (peer, h, fmt, idx, bytes)
        self.on_light_block: Optional[Callable] = None  # (peer, h, LightBlock|None)
        self.on_params: Optional[Callable] = None  # (peer, h, ConsensusParams)

    def start(self) -> None:
        self._stop_flag.clear()
        for ch, handler in (
            (self.snapshot_ch, self._handle_snapshot),
            (self.chunk_ch, self._handle_chunk),
            (self.light_ch, self._handle_light),
            (self.params_ch, self._handle_params),
        ):
            t = threading.Thread(
                target=self._recv_loop, args=(ch, handler), daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop_flag.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()

    # --- client-side requests -------------------------------------------------

    def request_snapshots(self) -> None:
        self.snapshot_ch.broadcast(bytes([TAG_SNAPSHOTS_REQUEST]))

    def request_chunk(self, peer: str, height: int, format_: int, index: int) -> None:
        self.chunk_ch.send(
            Envelope(
                CHUNK_CHANNEL,
                bytes([TAG_CHUNK_REQUEST]) + struct.pack(">qii", height, format_, index),
                to_peer=peer,
            )
        )

    def request_light_block(self, peer: str, height: int) -> None:
        self.light_ch.send(
            Envelope(
                LIGHT_BLOCK_CHANNEL,
                bytes([TAG_LIGHT_BLOCK_REQUEST]) + struct.pack(">q", height),
                to_peer=peer,
            )
        )

    def request_params(self, peer: str, height: int) -> None:
        self.params_ch.send(
            Envelope(
                PARAMS_CHANNEL,
                bytes([TAG_PARAMS_REQUEST]) + struct.pack(">q", height),
                to_peer=peer,
            )
        )

    # --- inbound --------------------------------------------------------------

    def _recv_loop(self, ch: Channel, handler) -> None:
        while not self._stop_flag.is_set():
            env = ch.receive(timeout=0.2)
            if env is None:
                continue
            try:
                handler(env)
            except Exception:
                pass

    def _handle_snapshot(self, env: Envelope) -> None:
        tag = env.message[0] if env.message else 0
        if tag == TAG_SNAPSHOTS_REQUEST:
            res = self.app.list_snapshots(abci.RequestListSnapshots())
            recent = sorted(res.snapshots, key=lambda s: -s.height)[:RECENT_SNAPSHOTS]
            for s in recent:
                self.snapshot_ch.send(
                    Envelope(
                        SNAPSHOT_CHANNEL,
                        encode_snapshots_response(s),
                        to_peer=env.from_peer,
                    )
                )
        elif tag == TAG_SNAPSHOTS_RESPONSE and self.on_snapshot is not None:
            self.on_snapshot(env.from_peer, decode_snapshots_response(env.message[1:]))

    def _handle_chunk(self, env: Envelope) -> None:
        tag = env.message[0] if env.message else 0
        if tag == TAG_CHUNK_REQUEST:
            height, format_, index = struct.unpack_from(">qii", env.message, 1)
            res = self.app.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(height=height, format=format_, chunk=index)
            )
            missing = 0 if res.chunk else 1
            self.chunk_ch.send(
                Envelope(
                    CHUNK_CHANNEL,
                    bytes([TAG_CHUNK_RESPONSE])
                    + struct.pack(">qiiB", height, format_, index, missing)
                    + res.chunk,
                    to_peer=env.from_peer,
                )
            )
        elif tag == TAG_CHUNK_RESPONSE and self.on_chunk is not None:
            height, format_, index, missing = struct.unpack_from(">qiiB", env.message, 1)
            body = env.message[1 + struct.calcsize(">qiiB") :]
            if len(body) > MAX_CHUNK_BYTES:
                return
            self.on_chunk(
                env.from_peer, height, format_, index, None if missing else body
            )

    def _serve_light_block(self, height: int) -> Optional[LightBlock]:
        if self.block_store is None or self.state_store is None:
            return None
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            seen = self.block_store.load_seen_commit()
            if seen is not None and seen.height == height:
                commit = seen
        if meta is None or commit is None:
            return None
        try:
            vals = self.state_store.load_validators(height)
        except LookupError:
            return None
        return LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validator_set=vals,
        )

    def _handle_light(self, env: Envelope) -> None:
        tag = env.message[0] if env.message else 0
        if tag == TAG_LIGHT_BLOCK_REQUEST:
            (height,) = struct.unpack_from(">q", env.message, 1)
            lb = self._serve_light_block(height)
            body = lb.to_proto_bytes() if lb is not None else b""
            self.light_ch.send(
                Envelope(
                    LIGHT_BLOCK_CHANNEL,
                    bytes([TAG_LIGHT_BLOCK_RESPONSE]) + struct.pack(">q", height) + body,
                    to_peer=env.from_peer,
                )
            )
        elif tag == TAG_LIGHT_BLOCK_RESPONSE and self.on_light_block is not None:
            (height,) = struct.unpack_from(">q", env.message, 1)
            body = env.message[1 + 8 :]
            lb = LightBlock.from_proto_bytes(body) if body else None
            self.on_light_block(env.from_peer, height, lb)

    def _handle_params(self, env: Envelope) -> None:
        tag = env.message[0] if env.message else 0
        if tag == TAG_PARAMS_REQUEST:
            (height,) = struct.unpack_from(">q", env.message, 1)
            if self.state_store is None:
                return
            try:
                params = self.state_store.load_consensus_params(height)
            except LookupError:
                return
            self.params_ch.send(
                Envelope(
                    PARAMS_CHANNEL,
                    bytes([TAG_PARAMS_RESPONSE])
                    + struct.pack(">q", height)
                    + consensus_params_to_proto_bytes(params),
                    to_peer=env.from_peer,
                )
            )
        elif tag == TAG_PARAMS_RESPONSE and self.on_params is not None:
            (height,) = struct.unpack_from(">q", env.message, 1)
            params = consensus_params_from_proto_bytes(env.message[1 + 8 :])
            self.on_params(env.from_peer, height, params)
