"""Sign-bytes golden vectors from the reference (types/vote_test.go:81-150)
plus protobuf wire codec round-trips."""

from tendermint_tpu.encoding import canonical
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Timestamp,
)
from tendermint_tpu.encoding.proto import Reader, encode_varint

# Go's zero time.Time as a protobuf Timestamp.
GO_ZERO_TIME = Timestamp(-62135596800, 0)


def sign_bytes(chain_id, msg_type, height, round_):
    return canonical.vote_sign_bytes(
        chain_id, msg_type, height, round_, b"", 0, b"", GO_ZERO_TIME
    )


def test_vote_sign_bytes_golden_vectors():
    # types/vote_test.go:88-150
    assert sign_bytes("", 0, 0, 0) == bytes(
        [0xD, 0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
    )
    precommit = bytes(
        [0x21, 0x8, 0x2, 0x11, 1, 0, 0, 0, 0, 0, 0, 0, 0x19, 1, 0, 0, 0, 0, 0, 0, 0,
         0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
    )
    assert sign_bytes("", SIGNED_MSG_TYPE_PRECOMMIT, 1, 1) == precommit
    prevote = bytes(
        [0x21, 0x8, 0x1, 0x11, 1, 0, 0, 0, 0, 0, 0, 0, 0x19, 1, 0, 0, 0, 0, 0, 0, 0,
         0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
    )
    assert sign_bytes("", SIGNED_MSG_TYPE_PREVOTE, 1, 1) == prevote
    no_type = bytes(
        [0x1F, 0x11, 1, 0, 0, 0, 0, 0, 0, 0, 0x19, 1, 0, 0, 0, 0, 0, 0, 0,
         0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
    )
    assert sign_bytes("", 0, 1, 1) == no_type
    with_chain = bytes(
        [0x2E, 0x11, 1, 0, 0, 0, 0, 0, 0, 0, 0x19, 1, 0, 0, 0, 0, 0, 0, 0,
         0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1,
         0x32, 0xD] + list(b"test_chain_id")
    )
    assert sign_bytes("test_chain_id", 0, 1, 1) == with_chain


def test_vote_extension_sign_bytes():
    # extension field does not affect vote sign bytes; it has its own
    # canonical struct (types/vote_test.go:152-170 case 5 matches case 4).
    got = canonical.vote_extension_sign_bytes("test_chain_id", b"extension", 1, 1)
    r = Reader(got)
    total = r.read_varint()
    assert total == len(got) - 1
    fields = {}
    for field, wire in r.fields():
        if wire == 2:
            fields[field] = r.read_bytes()
        elif wire == 1:
            fields[field] = r.read_sfixed64()
        else:
            r.skip(wire)
    assert fields == {1: b"extension", 2: 1, 3: 1, 4: b"test_chain_id"}


def test_varint_negative_is_ten_bytes():
    assert len(encode_varint(-1)) == 10
    r = Reader(encode_varint(-62135596800))
    assert r.read_svarint() == -62135596800


def test_timestamp_roundtrip():
    ts = Timestamp.from_unix_ns(1700000000_000000123)
    assert ts == Timestamp(1700000000, 123)
    enc = ts.encode()
    assert enc == bytes([0x08, 0x80, 0xE2, 0xCF, 0xAA, 0x06, 0x10, 0x7B])


def test_proposal_sign_bytes_parses():
    got = canonical.proposal_sign_bytes(
        "chain", 5, 2, -1, b"\xaa" * 32, 3, b"\xbb" * 32, Timestamp(100, 5)
    )
    r = Reader(got)
    r.read_varint()
    fields = {}
    for field, wire in r.fields():
        if wire == 2:
            fields[field] = r.read_bytes()
        elif wire == 1:
            fields[field] = r.read_sfixed64()
        else:
            fields[field] = r.read_svarint()
    assert fields[1] == 32  # SIGNED_MSG_TYPE_PROPOSAL
    assert fields[2] == 5 and fields[3] == 2
    assert fields[4] == -1  # pol_round, varint-encoded
    assert fields[7] == b"chain"
