#!/usr/bin/env python
"""Headline benchmark: batched Ed25519 ZIP-215 verification throughput.

Mirrors the reference's BenchmarkVerifyBatch (crypto/ed25519/bench_test.go:31-67)
at large batch, which is the hot path of VerifyCommit / blocksync / light
client (types/validation.go:154). Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "sigs/s", "vs_baseline": N}

vs_baseline divides by the reference's Go batch-verify throughput class.
No Go toolchain exists in this image to measure it directly; the
denominator is the curve25519-voi batched verify figure of ~33 us/sig on
a modern x86 core => 30,000 sigs/s (see BASELINE.md: the Go bench "run on
the build machine is the denominator").
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

GO_CPU_BATCH_SIGS_PER_SEC = 30_000.0  # curve25519-voi batch verify, 1 core

BATCH = int(os.environ.get("BENCH_BATCH", "8192"))
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "5"))


def main() -> None:
    import numpy as np

    from tendermint_tpu.crypto.keys import Ed25519PrivKey
    from tendermint_tpu.ops import ed25519_batch

    rng = np.random.default_rng(1234)
    n_keys = 256  # distinct signers, cycled (commit-like workload)
    privs = [Ed25519PrivKey.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8))) for _ in range(n_keys)]
    pubs = [p.pub_key().bytes() for p in privs]
    msgs = [bytes(rng.integers(0, 256, 120, dtype=np.uint8)) for _ in range(BATCH)]
    pks = [pubs[i % n_keys] for i in range(BATCH)]
    sigs = [privs[i % n_keys].sign(msgs[i]) for i in range(BATCH)]

    # Warmup: compile + first run.
    oks = ed25519_batch.verify_batch(pks, msgs, sigs)
    assert all(oks), "benchmark signatures must verify"

    best = 0.0
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        ed25519_batch.verify_batch(pks, msgs, sigs)
        dt = time.perf_counter() - t0
        best = max(best, BATCH / dt)

    print(
        json.dumps(
            {
                "metric": f"ed25519_batch_verify_throughput_b{BATCH}",
                "value": round(best, 1),
                "unit": "sigs/s",
                "vs_baseline": round(best / GO_CPU_BATCH_SIGS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
