"""Light-block providers (light/provider/provider.go).

A provider serves LightBlocks by height and accepts evidence of
misbehavior. MemoryProvider is the in-process test double (the mock/http
split of the reference); an RPC-backed provider plugs in the same ABC.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from tendermint_tpu.types.evidence import Evidence
from tendermint_tpu.types.light import LightBlock


class ProviderError(Exception):
    pass


class LightBlockNotFoundError(ProviderError):
    """provider.ErrLightBlockNotFound."""


class HeightTooHighError(ProviderError):
    """provider.ErrHeightTooHigh: the provider chain is shorter."""


class Provider:
    def chain_id(self) -> str:
        raise NotImplementedError

    def light_block(self, height: int) -> LightBlock:
        """Returns the LightBlock at height (0 = latest); raises
        LightBlockNotFoundError / HeightTooHighError."""
        raise NotImplementedError

    def report_evidence(self, evidence: Evidence) -> None:
        raise NotImplementedError


class MemoryProvider(Provider):
    def __init__(self, chain_id: str, blocks: Optional[List[LightBlock]] = None):
        self._chain_id = chain_id
        self._blocks: Dict[int, LightBlock] = {}
        self.evidence: List[Evidence] = []
        self._lock = threading.Lock()
        for lb in blocks or []:
            self._blocks[lb.height] = lb

    def chain_id(self) -> str:
        return self._chain_id

    def add(self, lb: LightBlock) -> None:
        with self._lock:
            self._blocks[lb.height] = lb

    def latest_height(self) -> int:
        with self._lock:
            return max(self._blocks) if self._blocks else 0

    def light_block(self, height: int) -> LightBlock:
        with self._lock:
            if not self._blocks:
                raise LightBlockNotFoundError(f"no blocks (chain {self._chain_id})")
            latest = max(self._blocks)
            if height == 0:
                return self._blocks[latest]
            if height > latest:
                raise HeightTooHighError(f"height {height} > latest {latest}")
            if height not in self._blocks:
                raise LightBlockNotFoundError(f"no light block at height {height}")
            return self._blocks[height]

    def report_evidence(self, evidence: Evidence) -> None:
        with self._lock:
            self.evidence.append(evidence)


class ProviderBudgetExhaustedError(ProviderError):
    """The wrapped provider burned its failure budget; fail fast until
    the rolling window slides past the old failures."""


class RetryingProvider(Provider):
    """Transient-failure armor for any Provider (lightd serving tier).

    Retries ONLY transient ``ProviderError``s (network flaps, bad
    responses) with exponential backoff. Definitive answers —
    ``LightBlockNotFoundError`` and ``HeightTooHighError`` — are part of
    the protocol and propagate immediately; retrying them would only
    stall bisection.

    A rolling per-provider failure budget turns a persistently sick
    provider into an immediate ``ProviderBudgetExhaustedError`` instead
    of a retry storm: once `failure_budget` transient failures land
    within `budget_window` seconds, calls fail fast until the window
    slides. `sleep` and `clock` are injectable so tests run in zero
    wall-clock time.
    """

    def __init__(self, inner: Provider, retries: int = 3,
                 base_delay: float = 0.05, max_delay: float = 2.0,
                 failure_budget: int = 8, budget_window: float = 60.0,
                 sleep=time.sleep, clock=time.monotonic):
        if retries < 0:
            raise ValueError("retries must be >= 0")
        self.inner = inner
        self.retries = retries
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.failure_budget = failure_budget
        self.budget_window = budget_window
        self._sleep = sleep
        self._clock = clock
        self._mtx = threading.Lock()
        # Timestamps (clock()) of recent transient failures.
        self._failures: deque = deque()  # guarded-by: _mtx
        self.retries_total = 0  # guarded-by: _mtx
        self.fast_fails_total = 0  # guarded-by: _mtx

    def chain_id(self) -> str:
        return self.inner.chain_id()

    def _budget_left_locked(self) -> int:
        horizon = self._clock() - self.budget_window
        while self._failures and self._failures[0] < horizon:
            self._failures.popleft()
        return self.failure_budget - len(self._failures)

    def _note_failure(self) -> None:
        with self._mtx:
            self._failures.append(self._clock())

    def _check_budget(self) -> None:
        with self._mtx:
            if self._budget_left_locked() <= 0:
                self.fast_fails_total += 1
                raise ProviderBudgetExhaustedError(
                    f"provider failure budget exhausted "
                    f"({self.failure_budget} transient failures in "
                    f"{self.budget_window:g}s)"
                )

    def light_block(self, height: int) -> LightBlock:
        self._check_budget()
        delay = self.base_delay
        last: Optional[ProviderError] = None
        for attempt in range(self.retries + 1):
            try:
                return self.inner.light_block(height)
            except (LightBlockNotFoundError, HeightTooHighError):
                raise  # definitive protocol answers, never transient
            except ProviderError as e:
                self._note_failure()
                last = e
                with self._mtx:
                    out_of_budget = self._budget_left_locked() <= 0
                if out_of_budget or attempt == self.retries:
                    break
                with self._mtx:
                    self.retries_total += 1
                self._sleep(delay)
                delay = min(delay * 2.0, self.max_delay)
        assert last is not None
        raise last

    def report_evidence(self, evidence: Evidence) -> None:
        # Evidence broadcast is best-effort fire-and-forget upstream;
        # no retry loop (HTTPProvider already swallows failures).
        self.inner.report_evidence(evidence)


class HTTPProvider(Provider):
    """RPC-backed provider (light/provider/http/http.go): builds
    LightBlocks from /commit + /validators against a full node."""

    def __init__(self, chain_id: str, url: str, timeout: float = 10.0):
        from tendermint_tpu.rpc.client import HTTPClient

        self._chain_id = chain_id
        self.client = HTTPClient(url, timeout=timeout)

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        from tendermint_tpu.rpc import encoding as enc
        from tendermint_tpu.rpc.client import RPCClientError
        from tendermint_tpu.types.light import SignedHeader
        from tendermint_tpu.types.validator_set import ValidatorSet

        try:
            c = self.client.commit(height if height > 0 else None)
            h = int(c["signed_header"]["header"]["height"])
            v = self.client.validators(h, per_page=100)
            vals = [enc.validator_from_json(d) for d in v["validators"]]
            total = int(v["total"])
            page = 2
            while len(vals) < total:
                more = self.client.validators(h, page=page, per_page=100)
                got = [enc.validator_from_json(d) for d in more["validators"]]
                if not got:
                    break
                vals.extend(got)
                page += 1
        except RPCClientError as e:
            msg = e.message + " " + e.data
            if "no block" in msg or "no commit" in msg:
                raise HeightTooHighError(msg)
            raise LightBlockNotFoundError(msg)
        except OSError as e:
            raise ProviderError(str(e))
        # Preserve the priorities the full node reported: populate the set
        # directly instead of via ValidatorSet(vals), which would re-run the
        # change-set algorithm and re-increment priorities. The proposer is
        # derived lazily from the reported priorities (get_proposer).
        vset = ValidatorSet()
        vset.validators = vals
        # Derive the proposer from the REPORTED priorities now:
        # validate_basic (LightClient init) requires a non-nil proposer
        # and must not trip on a lazily-populated set. A defective node
        # (empty valset) must surface as a ProviderError so the caller
        # drops the WITNESS, not the whole verification.
        try:
            vset.get_proposer()
        except ValueError as e:
            raise ProviderError(f"bad validator set from node: {e}")
        return LightBlock(
            signed_header=SignedHeader(
                header=enc.header_from_json(c["signed_header"]["header"]),
                commit=enc.commit_from_json(c["signed_header"]["commit"]),
            ),
            validator_set=vset,
        )

    def report_evidence(self, evidence: Evidence) -> None:
        try:
            self.client.call(
                "broadcast_evidence",
                {"evidence": "0x" + evidence.to_proto_bytes().hex()},
            )
        except Exception:
            pass
