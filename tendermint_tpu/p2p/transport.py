"""Transports: connection establishment + channel-tagged messaging.

Mirrors internal/p2p/transport.go's split: a ``Transport`` accepts/dials
``Connection``s; each connection does a node-info handshake then carries
(channel-id, payload) messages. Two implementations, as in the reference:
TCP with SecretConnection encryption (transport_mconn.go) and an
in-memory pair for tests (transport_memory.go).
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Tuple

from tendermint_tpu.p2p.key import NodeID, NodeKey, node_id_from_pubkey
from tendermint_tpu.p2p.secret_connection import SecretConnection


@dataclass
class NodeInfo:
    """types/node_info.go subset: identity + capabilities."""

    node_id: NodeID
    network: str  # chain id
    moniker: str = ""
    channels: List[int] = dc_field(default_factory=list)
    listen_addr: str = ""
    version: str = "0.1.0"

    def to_json_bytes(self) -> bytes:
        return json.dumps(
            {
                "node_id": self.node_id,
                "network": self.network,
                "moniker": self.moniker,
                "channels": self.channels,
                "listen_addr": self.listen_addr,
                "version": self.version,
            }
        ).encode()

    @classmethod
    def from_json_bytes(cls, raw: bytes) -> "NodeInfo":
        doc = json.loads(raw.decode())
        return cls(
            node_id=doc["node_id"],
            network=doc["network"],
            moniker=doc.get("moniker", ""),
            channels=list(doc.get("channels", [])),
            listen_addr=doc.get("listen_addr", ""),
            version=doc.get("version", ""),
        )

    def compatible_with(self, other: "NodeInfo") -> None:
        if self.network != other.network:
            raise ValueError(
                f"peer is on network {other.network!r}, not {self.network!r}"
            )


class Connection:
    def handshake(self, local_info: NodeInfo) -> NodeInfo:
        raise NotImplementedError

    def send(self, channel_id: int, msg: bytes) -> None:
        raise NotImplementedError

    def receive(self) -> Tuple[int, bytes]:
        """Blocks; raises ConnectionClosed on EOF/close."""
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class ConnectionClosed(Exception):
    pass


class Transport:
    def listen(self, addr: str) -> None:
        raise NotImplementedError

    def accept(self, timeout: Optional[float] = None) -> Connection:
        raise NotImplementedError

    def dial(self, addr: str) -> Connection:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


# --- memory transport (internal/p2p/transport_memory.go) --------------------


class _MemoryConn(Connection):
    def __init__(self, out_q: "queue.Queue", in_q: "queue.Queue"):
        self._out = out_q
        self._in = in_q
        self._closed = threading.Event()

    def handshake(self, local_info: NodeInfo) -> NodeInfo:
        self._out.put(("__handshake__", local_info.to_json_bytes()))
        kind, raw = self._in.get(timeout=5)
        if kind != "__handshake__":
            raise ConnectionClosed("bad handshake")
        return NodeInfo.from_json_bytes(raw)

    def send(self, channel_id: int, msg: bytes) -> None:
        if self._closed.is_set():
            raise ConnectionClosed("connection closed")
        self._out.put((channel_id, msg))

    def receive(self) -> Tuple[int, bytes]:
        while True:
            if self._closed.is_set():
                raise ConnectionClosed("connection closed")
            try:
                item = self._in.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                self._closed.set()
                raise ConnectionClosed("peer closed")
            return item

    def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            try:
                self._out.put_nowait(None)
            except queue.Full:
                pass


class MemoryNetwork:
    """A registry of in-process 'listeners' addressable by name."""

    def __init__(self):
        self._listeners: Dict[str, "queue.Queue"] = {}
        self._lock = threading.Lock()

    def transport(self, addr: str) -> "MemoryTransport":
        return MemoryTransport(self, addr)


class MemoryTransport(Transport):
    def __init__(self, network: MemoryNetwork, addr: str):
        self._network = network
        self.addr = addr
        self._accept_q: "queue.Queue" = queue.Queue()
        with network._lock:
            network._listeners[addr] = self._accept_q

    def listen(self, addr: str) -> None:
        pass  # registered at construction

    def accept(self, timeout: Optional[float] = None) -> Connection:
        conn = self._accept_q.get(timeout=timeout)
        return conn

    def dial(self, addr: str) -> Connection:
        with self._network._lock:
            listener = self._network._listeners.get(addr)
        if listener is None:
            raise ConnectionRefusedError(f"no memory listener at {addr}")
        a_to_b: "queue.Queue" = queue.Queue(maxsize=4096)
        b_to_a: "queue.Queue" = queue.Queue(maxsize=4096)
        local = _MemoryConn(a_to_b, b_to_a)
        remote = _MemoryConn(b_to_a, a_to_b)
        listener.put(remote)
        return local

    def close(self) -> None:
        with self._network._lock:
            self._network._listeners.pop(self.addr, None)


# --- TCP transport with SecretConnection ------------------------------------


class _SocketStream:
    def __init__(self, sock: socket.socket):
        self._sock = sock

    def sendall(self, data: bytes) -> None:
        self._sock.sendall(data)

    def recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionClosed("EOF")
            buf += chunk
        return buf


class _DeadlineStream(_SocketStream):
    """Stream with an ABSOLUTE deadline: every operation shrinks the
    socket timeout to the remaining budget, so a peer trickling one byte
    per timeout window cannot hold the handshake (and its per-IP slot)
    open indefinitely (transport_mconn.go SetDeadline semantics).
    ``disarm()`` turns it into a plain stream once the handshake is done."""

    def __init__(self, sock: socket.socket, deadline: float):
        super().__init__(sock)
        self._deadline: Optional[float] = deadline

    def disarm(self) -> None:
        self._deadline = None

    def _arm(self) -> None:
        if self._deadline is None:
            return
        remaining = self._deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("handshake deadline exceeded")
        self._sock.settimeout(remaining)

    def sendall(self, data: bytes) -> None:
        self._arm()
        super().sendall(data)

    def recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            self._arm()
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionClosed("EOF")
            buf += chunk
        return buf


class _TCPConn(Connection):
    """Encrypted TCP connection with MConnection multiplexing on top.

    SecretConnection authenticates and frames the stream; after the
    NodeInfo handshake the MConnection layer takes over every frame,
    adding per-channel priority scheduling, ~1400B packetization,
    send/recv rate limiting, and ping/pong keepalive
    (transport_mconn.go + conn/connection.go).
    """

    HANDSHAKE_TIMEOUT = 10.0  # transport_mconn.go handshake deadline

    def __init__(
        self,
        sock: socket.socket,
        node_key: NodeKey,
        mconn_config=None,
    ):
        # NO crypto here: __init__ runs on the accept/dial loop thread,
        # which must stay responsive. The SecretConnection key exchange
        # happens in handshake(), on the router's per-peer handshake
        # thread, under a socket deadline — a client that connects and
        # sends nothing cannot wedge the accept loop or force key
        # exchanges past the per-IP limit.
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._node_key = node_key
        self._secret = None
        self._send_lock = threading.Lock()
        self._mconn_config = mconn_config
        self._mconn = None
        self._recv_q: "queue.Queue" = queue.Queue(maxsize=8192)
        self._closed_ev = threading.Event()
        try:
            self.remote_ip = sock.getpeername()[0]
        except OSError:
            self.remote_ip = None
        self.remote_node_id = None  # known after handshake()

    def handshake(self, local_info: NodeInfo) -> NodeInfo:
        deadline_stream = _DeadlineStream(
            self._sock, time.monotonic() + self.HANDSHAKE_TIMEOUT
        )
        try:
            self._secret = SecretConnection(
                deadline_stream, self._node_key.priv_key
            )
            self.remote_node_id = node_id_from_pubkey(
                self._secret.remote_pubkey
            )
            with self._send_lock:
                self._secret.send_msg(local_info.to_json_bytes())
            info = NodeInfo.from_json_bytes(self._secret.recv_msg())
        finally:
            self._sock.settimeout(None)
        # handshake done: the deadline no longer applies to steady state
        deadline_stream.disarm()
        # The authenticated transport key must match the claimed node id
        # (transport_mconn.go handshake validation).
        if info.node_id != self.remote_node_id:
            raise ValueError(
                f"peer claimed node id {info.node_id} but transport "
                f"authenticated {self.remote_node_id}"
            )
        # Handshake done: the multiplexer owns the stream from here.
        from tendermint_tpu.p2p.mconn import MConnection

        self._mconn = MConnection(
            send_frame=self._secret.send_msg,
            recv_frame=self._secret.recv_msg,
            on_receive=self._deliver,
            on_error=self._conn_error,
            config=self._mconn_config,
        )
        self._mconn.start()
        return info

    def _deliver(self, channel_id: int, msg: bytes) -> None:
        if self._closed_ev.is_set():
            return
        try:
            self._recv_q.put((channel_id, msg), timeout=5)
        except queue.Full:
            pass  # backpressure: drop (router-side queues do the same)

    def _conn_error(self, e: Exception) -> None:
        # event, not an in-queue sentinel: a full queue can never lose it
        self._closed_ev.set()

    def send(self, channel_id: int, msg: bytes) -> None:
        mconn = self._mconn
        if mconn is None:
            raise ConnectionClosed("send before handshake")
        if self._closed_ev.is_set() or mconn.errored or mconn.stopped:
            # dead connection must surface so the router evicts the peer
            raise ConnectionClosed("mconn errored or closed")
        # full channel queue -> drop, matching the reference's
        # non-blocking Send-returns-false contract (connection.go Send);
        # gossip routines re-offer what a peer still lacks.
        mconn.send(channel_id, msg)

    def receive(self) -> Tuple[int, bytes]:
        # drain anything already delivered, then surface the close
        while True:
            try:
                return self._recv_q.get(timeout=0.2)
            except queue.Empty:
                if self._closed_ev.is_set():
                    raise ConnectionClosed("mconn errored or closed") from None

    def close(self) -> None:
        if self._mconn is not None:
            self._mconn.stop()
        self._closed_ev.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class TCPTransport(Transport):
    def __init__(self, node_key: NodeKey, mconn_config=None):
        self.node_key = node_key
        self.mconn_config = mconn_config
        self._listener: Optional[socket.socket] = None
        self.listen_addr = ""

    def listen(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host or "127.0.0.1", int(port)))
        s.listen(64)
        self._listener = s
        self.listen_addr = f"{host or '127.0.0.1'}:{s.getsockname()[1]}"

    def accept(self, timeout: Optional[float] = None) -> Connection:
        if self._listener is None:
            raise RuntimeError("not listening")
        self._listener.settimeout(timeout)
        sock, _ = self._listener.accept()
        return _TCPConn(sock, self.node_key, mconn_config=self.mconn_config)

    def dial(self, addr: str) -> Connection:
        host, _, port = addr.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=5)
        sock.settimeout(None)
        return _TCPConn(sock, self.node_key, mconn_config=self.mconn_config)

    def close(self) -> None:
        if self._listener is not None:
            self._listener.close()
            self._listener = None
