"""TimeoutTicker (internal/consensus/ticker.go): one timer, HRS-monotonic.

ScheduleTimeout replaces any pending timer; a fire enqueues the
TimeoutInfo onto the state machine's timeout queue. Stale timeouts (for
an older height/round/step) are filtered by the receiver, as in the
reference (ticker.go:18-50 + state.go handleTimeout guard).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from tendermint_tpu.consensus.wal import TimeoutInfo


class TimeoutTicker:
    def __init__(self, on_timeout: Callable[[TimeoutInfo], None]):
        self._on_timeout = on_timeout
        self._timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()
        self._stopped = False

    def schedule_timeout(
        self, duration: float, height: int, round_: int, step: int
    ) -> None:
        ti = TimeoutInfo(duration, height, round_, step)
        with self._lock:
            if self._stopped:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(max(0.0, duration), self._fire, args=(ti,))
            self._timer.daemon = True
            self._timer.start()

    def _fire(self, ti: TimeoutInfo) -> None:
        with self._lock:
            if self._stopped:
                return
        self._on_timeout(ti)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
