"""Accumulate-with-deadline batch verification scheduler.

The latency/throughput duality (SURVEY §7 "Hard parts"): consensus votes
arrive one at a time and need ~100µs-class answers, while the device
verifier only pays off in batches. This scheduler is the seam between
them: concurrent callers submit single (pubkey, msg, sig) verifies and
block on a future; an accumulator thread flushes the pending set to ONE
batch verification when either

- the batch reaches ``max_batch`` entries (throughput bound), or
- the OLDEST pending entry has waited ``max_delay`` (latency bound) —
  the deadline is per-entry, so a lone vote is answered within
  ``max_delay`` even when nothing else arrives.

Per-entry verdicts come from the batch verifier's attribution (the
reference's BatchVerifier.Verify bool slice, crypto/crypto.go:58-76), so
one bad signature fails only its own future.

Wiring: callers that ingest signatures from many concurrent sources
(per-peer vote floods, RPC broadcast storms) submit here instead of
calling ``pub_key.verify_signature`` inline; the single-threaded
consensus loop keeps its inline host verify, which is already
latency-optimal for one caller.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from tendermint_tpu.libs import tracing

DEFAULT_MAX_BATCH = 256
DEFAULT_MAX_DELAY = 0.002  # 2ms: well under a vote round-trip


@dataclass
class _Pending:
    pubkey: bytes
    msg: bytes
    sig: bytes
    submitted: float
    done: threading.Event = field(default_factory=threading.Event)
    ok: bool = False


class VerifyScheduler:
    """Batches concurrent single-signature verifies onto one verifier call.

    ``verify_fn(pks, msgs, sigs) -> List[bool]`` is the flush target —
    ``ops.verify_batch`` on a device backend, or any host batch verifier.

    ``fallback_fn`` (optional, same signature) is tried when
    ``verify_fn`` raises — the seam that keeps the scheduler draining
    under device degradation instead of failing whole flushes closed.
    Without a fallback, a raising flush still fails closed.
    """

    def __init__(
        self,
        verify_fn: Callable[
            [Sequence[bytes], Sequence[bytes], Sequence[bytes]], List[bool]
        ],
        max_batch: int = DEFAULT_MAX_BATCH,
        max_delay: float = DEFAULT_MAX_DELAY,
        fallback_fn: Optional[
            Callable[
                [Sequence[bytes], Sequence[bytes], Sequence[bytes]], List[bool]
            ]
        ] = None,
    ):
        self._verify_fn = verify_fn
        self._fallback_fn = fallback_fn
        self.max_batch = max_batch
        self.max_delay = max_delay
        self._pending: List[_Pending] = []
        self._mtx = threading.Lock()
        self._wake = threading.Condition(self._mtx)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # observability
        self.flushes = 0
        self.entries_verified = 0
        self.entries_coalesced = 0  # duplicate submissions answered by one lane
        self.flush_errors = 0  # primary verify_fn raised
        self.fallback_flushes = 0  # fallback_fn answered a failed flush

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._mtx:
            if self._thread is not None:
                return
            self._stop = False
            # assign under the lock: a concurrent start() must see it
            self._thread = threading.Thread(
                target=self._run, name="verify-scheduler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # fail any stragglers closed rather than hanging their callers
        with self._mtx:
            leftovers, self._pending = self._pending, []
        for p in leftovers:
            p.ok = False
            p.done.set()

    # --- submission ----------------------------------------------------------

    def submit(self, pubkey: bytes, msg: bytes, sig: bytes) -> _Pending:
        """Enqueue one signature; returns a handle for ``wait``. Callers
        with several signatures submit all first so one flush covers
        them, instead of paying the deadline once per signature."""
        entry = _Pending(pubkey, msg, sig, time.monotonic())
        with self._wake:
            if self._stop or self._thread is None:
                raise RuntimeError("scheduler not running")
            self._pending.append(entry)
            self._wake.notify_all()
        return entry

    def wait(self, entry: _Pending, timeout: float = 10.0) -> bool:
        """Block until the entry's batch flushed; False on timeout (fail
        closed: an unverified signature is an invalid signature)."""
        if not entry.done.wait(timeout=timeout):
            return False
        return entry.ok

    def verify(
        self, pubkey: bytes, msg: bytes, sig: bytes, timeout: float = 10.0
    ) -> bool:
        """Submit one signature and block until its batch flushes."""
        return self.wait(self.submit(pubkey, msg, sig), timeout=timeout)

    # --- accumulator ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._wake:
                while not self._stop:
                    if len(self._pending) >= self.max_batch:
                        break
                    if self._pending:
                        oldest = self._pending[0].submitted
                        wait = self.max_delay - (time.monotonic() - oldest)
                        if wait <= 0:
                            break
                        self._wake.wait(timeout=wait)
                    else:
                        self._wake.wait(timeout=0.1)
                if self._stop:
                    return
                batch, self._pending = (
                    self._pending[: self.max_batch],
                    self._pending[self.max_batch :],
                )
            if not batch:
                continue
            # Coalesce duplicate (pubkey, msg, sig) submissions: a vote
            # gossiped by k peers lands k times inside one deadline
            # window but costs one verifier lane; the verdict fans out
            # to every waiting future.
            pks: List[bytes] = []
            msgs: List[bytes] = []
            sigs: List[bytes] = []
            index: dict = {}
            slots: List[int] = []
            with tracing.span("sched_assemble", lanes=len(batch)) as asp:
                for p in batch:
                    key = (p.pubkey, p.msg, p.sig)
                    idx = index.get(key)
                    if idx is None:
                        idx = index[key] = len(pks)
                        pks.append(p.pubkey)
                        msgs.append(p.msg)
                        sigs.append(p.sig)
                    slots.append(idx)
                asp.set(unique=len(pks), coalesced=len(batch) - len(pks))
            self.entries_coalesced += len(batch) - len(pks)
            with tracing.span("sched_flush", lanes=len(pks)):
                try:
                    oks = self._verify_fn(pks, msgs, sigs)
                except Exception:
                    self.flush_errors += 1
                    oks = None
                    if self._fallback_fn is not None:
                        try:
                            oks = self._fallback_fn(pks, msgs, sigs)
                            self.fallback_flushes += 1
                        except Exception:
                            oks = None
                    if oks is None:
                        # fail closed, never hang callers
                        oks = [False] * len(pks)
            if len(oks) != len(pks):  # misbehaving verifier: fail closed
                oks = [False] * len(pks)
            self.flushes += 1
            self.entries_verified += len(batch)
            for p, idx in zip(batch, slots):
                p.ok = bool(oks[idx])
                p.done.set()
