"""P2P stack tests: secret connection, transports, router, peer manager
(internal/p2p tests analog, memory transport substituting for sockets
where possible per SURVEY.md §4)."""

import queue
import socket
import threading
import time

import pytest

from tendermint_tpu.crypto.keys import Ed25519PrivKey
from tendermint_tpu.p2p.key import NodeKey, node_id_from_pubkey, validate_node_id
from tendermint_tpu.p2p.peermanager import PeerAddress, PeerManager, PeerUpdate
from tendermint_tpu.p2p.router import Envelope, Router
from tendermint_tpu.p2p.secret_connection import SecretConnection, SecretConnectionError
from tendermint_tpu.p2p.transport import (
    MemoryNetwork,
    NodeInfo,
    TCPTransport,
)

CHAIN = "p2p-chain"


class _PipeStream:
    """Stream over a socketpair end."""

    def __init__(self, sock):
        self.sock = sock

    def sendall(self, data):
        self.sock.sendall(data)

    def recv_exact(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("EOF")
            buf += chunk
        return buf


class TestSecretConnection:
    def _pair(self):
        a, b = socket.socketpair()
        ka = Ed25519PrivKey.from_seed(b"\x01" * 32)
        kb = Ed25519PrivKey.from_seed(b"\x02" * 32)
        out = {}

        def responder():
            out["b"] = SecretConnection(_PipeStream(b), kb)

        t = threading.Thread(target=responder)
        t.start()
        sca = SecretConnection(_PipeStream(a), ka)
        t.join(timeout=5)
        return sca, out["b"], ka, kb

    def test_handshake_authenticates_keys(self):
        sca, scb, ka, kb = self._pair()
        assert sca.remote_pubkey.bytes() == kb.pub_key().bytes()
        assert scb.remote_pubkey.bytes() == ka.pub_key().bytes()

    def test_bidirectional_messages(self):
        sca, scb, _, _ = self._pair()
        sca.send_msg(b"hello from a")
        scb.send_msg(b"hello from b" * 500)  # multi-frame
        assert scb.recv_msg() == b"hello from a"
        assert sca.recv_msg() == b"hello from b" * 500

    def test_tampered_ciphertext_rejected(self):
        a, b = socket.socketpair()
        ka = Ed25519PrivKey.from_seed(b"\x01" * 32)
        kb = Ed25519PrivKey.from_seed(b"\x02" * 32)
        out = {}
        t = threading.Thread(
            target=lambda: out.update(b_conn=SecretConnection(_PipeStream(b), kb))
        )
        t.start()
        sca = SecretConnection(_PipeStream(a), ka)
        t.join(timeout=5)
        scb = out["b_conn"]
        # Write a corrupted sealed frame directly into the socket.
        from tendermint_tpu.p2p.secret_connection import SEALED_FRAME_SIZE

        a.sendall(b"\x00" * SEALED_FRAME_SIZE)
        with pytest.raises(SecretConnectionError):
            scb.recv()


class TestNodeKey:
    def test_node_id_format(self, tmp_path):
        nk = NodeKey.load_or_gen(str(tmp_path / "nk.json"))
        validate_node_id(nk.node_id)
        nk2 = NodeKey.load_or_gen(str(tmp_path / "nk.json"))
        assert nk.node_id == nk2.node_id


class TestPeerManager:
    def test_address_book_and_dialing(self):
        pm = PeerManager("a" * 40)
        addr = PeerAddress("b" * 40, "127.0.0.1:1234")
        assert pm.add_address(addr)
        assert not pm.add_address(addr)  # no new info
        cand = pm.dial_next()
        assert cand is not None and cand.node_id == "b" * 40
        assert pm.dial_next() is None  # already dialing
        pm.dialed(cand)
        assert pm.connected_peers() == ["b" * 40]

    def test_dial_failure_backoff(self):
        t = {"now": 0.0}
        pm = PeerManager("a" * 40, now=lambda: t["now"])
        pm.add_address(PeerAddress("b" * 40, "127.0.0.1:1"))
        cand = pm.dial_next()
        pm.dial_failed(cand)
        assert pm.dial_next() is None  # in backoff
        t["now"] = 100.0
        assert pm.dial_next() is not None

    def test_accepted_capacity(self):
        pm = PeerManager("a" * 40, max_connected=1)
        pm.accepted("b" * 40)
        with pytest.raises(ValueError, match="maximum"):
            pm.accepted("c" * 40)
        with pytest.raises(ValueError, match="already"):
            pm.accepted("b" * 40)

    def test_self_rejected(self):
        pm = PeerManager("a" * 40)
        assert not pm.add_address(PeerAddress("a" * 40, "127.0.0.1:1"))
        with pytest.raises(ValueError, match="self"):
            pm.accepted("a" * 40)

    def test_subscriptions(self):
        pm = PeerManager("a" * 40)
        updates = []
        pm.subscribe(updates.append)
        pm.accepted("b" * 40)
        pm.ready("b" * 40)
        pm.disconnected("b" * 40)
        assert [(u.node_id, u.status) for u in updates] == [
            ("b" * 40, "up"),
            ("b" * 40, "down"),
        ]

    def test_persistence(self):
        from tendermint_tpu.storage import MemDB

        db = MemDB()
        pm = PeerManager("a" * 40, db=db)
        pm.add_address(PeerAddress("b" * 40, "1.2.3.4:5"), persistent=True)
        pm2 = PeerManager("a" * 40, db=db)
        assert pm2.addresses("b" * 40) == ["1.2.3.4:5"]


def make_router(network, name, chain=CHAIN):
    nk = NodeKey.generate()
    info = NodeInfo(node_id=nk.node_id, network=chain, listen_addr=name)
    pm = PeerManager(nk.node_id)
    transport = network.transport(name)
    router = Router(info, pm, transport)
    return router, nk, pm


class TestRouterMemory:
    def test_two_nodes_exchange(self):
        net = MemoryNetwork()
        r1, nk1, pm1 = make_router(net, "n1")
        r2, nk2, pm2 = make_router(net, "n2")
        ch1 = r1.open_channel(0x7F)
        ch2 = r2.open_channel(0x7F)
        r1.start()
        r2.start()
        try:
            pm1.add_address(PeerAddress(nk2.node_id, "n2"))
            deadline = time.monotonic() + 5
            while not r1.connected_peers() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert r1.connected_peers() == [nk2.node_id]
            ch1.broadcast(b"ping")
            env = ch2.receive(timeout=5)
            assert env is not None and env.message == b"ping"
            assert env.from_peer == nk1.node_id
            ch2.send(Envelope(0x7F, b"pong", to_peer=nk1.node_id))
            env = ch1.receive(timeout=5)
            assert env is not None and env.message == b"pong"
        finally:
            r1.stop()
            r2.stop()

    def test_network_mismatch_rejected(self):
        net = MemoryNetwork()
        r1, nk1, pm1 = make_router(net, "n1", chain="chain-A")
        r2, nk2, pm2 = make_router(net, "n2", chain="chain-B")
        r1.start()
        r2.start()
        try:
            pm1.add_address(PeerAddress(nk2.node_id, "n2"))
            time.sleep(0.5)
            assert r1.connected_peers() == []
        finally:
            r1.stop()
            r2.stop()


class TestRouterTCP:
    def test_encrypted_tcp_exchange(self):
        nk1, nk2 = NodeKey.generate(), NodeKey.generate()
        t1, t2 = TCPTransport(nk1), TCPTransport(nk2)
        t2.listen("127.0.0.1:0")
        info1 = NodeInfo(node_id=nk1.node_id, network=CHAIN)
        info2 = NodeInfo(node_id=nk2.node_id, network=CHAIN)
        pm1, pm2 = PeerManager(nk1.node_id), PeerManager(nk2.node_id)
        r1 = Router(info1, pm1, t1)
        r2 = Router(info2, pm2, t2)
        ch1 = r1.open_channel(0x42)
        ch2 = r2.open_channel(0x42)
        r1.start()
        r2.start()
        try:
            pm1.add_address(PeerAddress(nk2.node_id, t2.listen_addr))
            deadline = time.monotonic() + 5
            while not r1.connected_peers() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert nk2.node_id in r1.connected_peers()
            ch1.broadcast(b"secret ping over tcp")
            env = ch2.receive(timeout=5)
            assert env is not None and env.message == b"secret ping over tcp"
        finally:
            r1.stop()
            r2.stop()


class TestHandshakeBinding:
    """VERDICT missing #9: the handshake challenge must bind BOTH
    ephemerals and the session — a signature produced for one session
    must be unusable in any other (splice/MITM resistance), and role
    separation must come from the direction-split keys."""

    def test_challenge_binds_both_ephemerals(self):
        """Changing either ephemeral (or their order) changes the
        derived challenge: a MITM cannot keep a victim's challenge
        while substituting its own ephemeral."""
        from tendermint_tpu.p2p.secret_connection import _hkdf

        e1, e2, e3 = b"\x01" * 32, b"\x02" * 32, b"\x03" * 32
        shared = b"\x42" * 32
        label = b"TENDERMINT_TPU_SECRET_CONNECTION"

        def challenge(a, b):
            lo, hi = sorted([a, b])
            return _hkdf(shared, label + lo + hi, 96)[64:96]

        c12 = challenge(e1, e2)
        assert challenge(e2, e1) == c12  # symmetric: both sides agree
        assert challenge(e1, e3) != c12  # responder ephemeral bound
        assert challenge(e3, e2) != c12  # initiator ephemeral bound
        # and the DH secret itself is bound
        lo, hi = sorted([e1, e2])
        assert _hkdf(b"\x43" * 32, label + lo + hi, 96)[64:96] != c12

    def test_auth_from_another_session_rejected(self):
        """Splice attack: replaying the (pubkey, signature) auth message
        captured in session 1 into session 2 must fail — the signature
        covers session-specific material."""
        import socket as socketlib
        import threading as th

        from tendermint_tpu.crypto.keys import Ed25519PrivKey
        from tendermint_tpu.p2p.secret_connection import (
            SecretConnection,
            SecretConnectionError,
        )

        ka = Ed25519PrivKey.from_seed(b"\x0a" * 32)
        kb = Ed25519PrivKey.from_seed(b"\x0b" * 32)

        # A signature kb made over some OTHER session's challenge (any
        # bytes that are not THIS session's challenge model it exactly).
        sig_session1 = kb.sign(b"\x99" * 32)

        a2, b2 = socketlib.socketpair()
        err = {}

        def victim():
            try:
                SecretConnection(_PipeStream(b2), kb)
            except SecretConnectionError as e:
                err["e"] = e

        t = th.Thread(target=victim)
        t.start()
        # manual initiator: do the ephemeral exchange, derive keys, but
        # send kb's STALE signature instead of a fresh one over this
        # session's challenge

        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PrivateKey,
            X25519PublicKey,
        )
        from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        from tendermint_tpu.p2p.secret_connection import _hkdf

        s = _PipeStream(a2)
        eph = X25519PrivateKey.generate()
        eph_pub = eph.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        s.sendall(eph_pub)
        remote_eph = s.recv_exact(32)
        shared = eph.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        lo, hi = sorted([eph_pub, remote_eph])
        material = _hkdf(
            shared, b"TENDERMINT_TPU_SECRET_CONNECTION" + lo + hi, 96
        )
        key1, key2 = material[:32], material[32:64]
        send_key = key1 if eph_pub == lo else key2
        cipher = ChaCha20Poly1305(send_key)
        # frame the stale auth exactly like SecretConnection.send would
        import struct as _struct

        payload = kb.pub_key().bytes() + sig_session1
        frame = _struct.pack("<I", len(payload)) + payload
        frame += b"\x00" * (1028 - len(frame))
        nonce = b"\x00" * 4 + _struct.pack("<Q", 0)
        s.sendall(cipher.encrypt(nonce, frame, None))
        t.join(timeout=5)
        assert "e" in err, "stale-signature auth must be rejected"
        assert "challenge" in str(err["e"])

    def test_direction_keys_differ(self):
        """Role separation: each direction uses a distinct key, so a
        reflected ciphertext cannot be decrypted as inbound traffic."""
        sca, scb, _, _ = self._pair_keys()
        assert sca._send_cipher is not sca._recv_cipher
        # a's send key must equal b's recv key and differ from a's recv
        probe = b"direction probe"
        sca.send_msg(probe)
        assert scb.recv_msg() == probe

    def _pair_keys(self):
        a, b = socket.socketpair()
        ka = Ed25519PrivKey.from_seed(b"\x11" * 32)
        kb = Ed25519PrivKey.from_seed(b"\x12" * 32)
        out = {}
        t = threading.Thread(
            target=lambda: out.update(b=SecretConnection(_PipeStream(b), kb))
        )
        t.start()
        sca = SecretConnection(_PipeStream(a), ka)
        t.join(timeout=5)
        return sca, out["b"], ka, kb
