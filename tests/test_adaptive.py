"""ISSUE 17: SLO-driven adaptive serving — the dyn-batch controller
(synthetic-clock hysteresis, cost-model convergence, off-parity with
the static scheduler) and the per-tenant SLO budget machinery
(breach -> tenant-scoped shed -> recovery)."""

import threading
import time

import pytest

from tendermint_tpu.crypto import adaptive
from tendermint_tpu.crypto.adaptive import (
    DYN_BATCH_ENV,
    BatchCostModel,
    DynBatchController,
    dyn_batch_default,
)
from tendermint_tpu.crypto.scheduler import (
    DEFAULT_MAX_BATCH,
    VerifyScheduler,
)
from tendermint_tpu.verifyd import server as server_mod
from tendermint_tpu.verifyd.client import VerifydClient, VerifydRejectedError
from tendermint_tpu.verifyd.server import VerifydServer


def ok_verify(pks, msgs, sigs):
    return [True] * len(pks)


class FakeClock:
    """Injectable monotonic clock: hysteresis without sleeping."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def warm_controller(ctl, lanes=8, device_s=0.001):
    """Feed MIN_BUCKET_SAMPLES neutral-ish flushes so the cost model
    can produce predictions (votes before warmth are neutral)."""
    for _ in range(adaptive.MIN_BUCKET_SAMPLES):
        # tiny positive slack: marginal prediction (once warm) exceeds
        # half of it, so these observations cast NO vote either way
        ctl.observe_flush(lanes, 0.001, device_s, device_s * 0.1, 0.002)


# --- controller hysteresis (synthetic clock) -------------------------------


class TestControllerHysteresis:
    def grow(self, ctl, lanes=8):
        # huge slack: the warm model's marginal cost is trivially within
        # GROW_SLACK_FRACTION of it
        ctl.observe_flush(lanes, 0.001, 0.001, 1.0, 0.002)

    def shrink(self, ctl, lanes=8):
        # negative slack = the wire deadline was already blown at
        # dispatch: unconditional shrink vote
        ctl.observe_flush(lanes, 0.001, 0.001, -1.0, 0.002)

    def neutral(self, ctl, lanes=8):
        # slack too small for the marginal cost, no queue wait: no vote
        ctl.observe_flush(lanes, 0.001, 0.001, 1e-6, 0.002)

    def test_grow_needs_consecutive_votes(self):
        clock = FakeClock()
        ctl = DynBatchController(clock=clock)
        warm_controller(ctl)
        for _ in range(adaptive.VOTES_NEEDED - 1):
            self.grow(ctl)
        assert ctl.scale == 1.0
        self.grow(ctl)
        assert ctl.scale == pytest.approx(adaptive.GROW_STEP)
        assert ctl.snapshot()["steps_up"] == 1

    def test_dwell_gates_consecutive_steps(self):
        clock = FakeClock()
        ctl = DynBatchController(clock=clock)
        warm_controller(ctl)
        for _ in range(adaptive.VOTES_NEEDED):
            self.grow(ctl)
        assert ctl.scale == pytest.approx(adaptive.GROW_STEP)
        # votes keep landing inside the dwell window: no second step
        for _ in range(adaptive.VOTES_NEEDED * 3):
            self.grow(ctl)
        assert ctl.scale == pytest.approx(adaptive.GROW_STEP)
        clock.advance(adaptive.STEP_DWELL + 0.01)
        self.grow(ctl)
        assert ctl.scale == pytest.approx(adaptive.GROW_STEP**2)

    def test_shrink_on_blown_slack_with_hysteresis(self):
        clock = FakeClock()
        ctl = DynBatchController(clock=clock)
        for _ in range(adaptive.VOTES_NEEDED - 1):
            self.shrink(ctl)
        assert ctl.scale == 1.0
        self.shrink(ctl)
        assert ctl.scale == pytest.approx(adaptive.SHRINK_STEP)
        assert ctl.snapshot()["steps_down"] == 1

    def test_shrink_on_queue_wait_signal(self):
        clock = FakeClock()
        ctl = DynBatchController(clock=clock)
        # caller-observed queue wait far above half the resolved delay
        for _ in range(8):
            ctl.note_queue_wait(0.05)
        for _ in range(adaptive.VOTES_NEEDED):
            ctl.observe_flush(8, 0.001, 0.001, 1e-6, 0.002)
        assert ctl.scale == pytest.approx(adaptive.SHRINK_STEP)

    def test_neutral_vote_resets_both_streaks(self):
        clock = FakeClock()
        ctl = DynBatchController(clock=clock)
        for _ in range(adaptive.VOTES_NEEDED - 1):
            self.shrink(ctl)
        self.neutral(ctl)  # cold model + tiny slack: no vote
        for _ in range(adaptive.VOTES_NEEDED - 1):
            self.shrink(ctl)
        assert ctl.scale == 1.0  # streak restarted after the neutral
        self.shrink(ctl)
        assert ctl.scale == pytest.approx(adaptive.SHRINK_STEP)

    def test_scale_clamps_and_delay_cap(self):
        clock = FakeClock()
        ctl = DynBatchController(clock=clock)
        warm_controller(ctl)
        for _ in range(200):
            self.grow(ctl)
            clock.advance(adaptive.STEP_DWELL + 0.01)
        assert ctl.scale == adaptive.SCALE_MAX
        mb, md = ctl.limits(4, 0.002)
        assert mb == int(4 * adaptive.SCALE_MAX)
        # the delay knob is capped tighter than the batch knob
        assert md == pytest.approx(0.002 * adaptive.DELAY_SCALE_MAX)
        for _ in range(200):
            self.shrink(ctl)
            clock.advance(adaptive.STEP_DWELL + 0.01)
        assert ctl.scale == pytest.approx(adaptive.SCALE_MIN)
        mb, md = ctl.limits(4, 0.002)
        assert mb == max(1, int(4 * adaptive.SCALE_MIN))
        assert md >= 0.002 * adaptive.SCALE_MIN


# --- cost model ------------------------------------------------------------


class TestCostModel:
    def test_converges_on_fake_flush_stream(self):
        model = BatchCostModel()
        # a wild first sample, then a steady stream: the EWMA must
        # converge to the steady cost
        model.observe(8, 0.05, 0.05)
        for _ in range(60):
            model.observe(8, 0.002, 0.010)
        assert model.device_cost(8) == pytest.approx(0.010, abs=1e-4)
        assert model.residency_cost(8) == pytest.approx(0.002, abs=1e-4)

    def test_cold_buckets_give_no_predictions(self):
        model = BatchCostModel()
        assert model.device_cost(8) is None
        assert model.marginal_device_cost(8) is None
        model.observe(8, 0.001, 0.01)  # 1 sample < MIN_BUCKET_SAMPLES
        assert model.device_cost(8) is None

    def test_marginal_from_measured_adjacent_buckets(self):
        model = BatchCostModel()
        for _ in range(adaptive.MIN_BUCKET_SAMPLES):
            model.observe(8, 0.001, 0.010)
            model.observe(16, 0.001, 0.011)
        # both buckets warm: the marginal is the measured difference,
        # NOT the doubling guess
        assert model.marginal_device_cost(8) == pytest.approx(
            0.001, abs=1e-4
        )

    def test_extrapolation_is_conservative(self):
        model = BatchCostModel()
        for _ in range(adaptive.MIN_BUCKET_SAMPLES):
            model.observe(16, 0.001, 0.010)
        # cold upper bucket: linear per-lane scaling from the warm one
        assert model.device_cost(64) == pytest.approx(0.040, abs=1e-4)
        # cold upper bucket's marginal falls back to "doubling doubles"
        assert model.marginal_device_cost(16) == pytest.approx(
            0.010, abs=1e-4
        )


# --- env default and off-parity --------------------------------------------


class TestDynBatchOff:
    def test_env_default_resolution(self, monkeypatch):
        for off in ("off", "0", "false", "no"):
            monkeypatch.setenv(DYN_BATCH_ENV, off)
            assert dyn_batch_default() is False
        for on in ("on", "1", "true", "anything"):
            monkeypatch.setenv(DYN_BATCH_ENV, on)
            assert dyn_batch_default() is True
        monkeypatch.delenv(DYN_BATCH_ENV)
        assert dyn_batch_default() is True

    def test_bare_scheduler_defaults_static(self):
        s = VerifyScheduler(ok_verify, max_batch=8)
        assert s._dyn is None
        assert s.resolved_knobs()["dyn_batch"] is False
        assert "dyn" not in s.resolved_knobs()

    def test_server_honors_env_off(self, monkeypatch):
        monkeypatch.setenv(DYN_BATCH_ENV, "off")
        srv = VerifydServer(verify_fn=ok_verify)
        try:
            assert srv.dyn_batch is False
            assert srv.scheduler._dyn is None
        finally:
            srv.stop()

    @staticmethod
    def _flush_sizes(make_sched, n_entries):
        """Drive n_entries concurrent lanes through a scheduler with
        the deadline parked far away: only SIZE flushes can happen, so
        the flush-size sequence IS the flush-boundary behavior."""
        sizes = []
        mtx = threading.Lock()

        def counting(pks, msgs, sigs):
            with mtx:
                sizes.append(len(pks))
            return ok_verify(pks, msgs, sigs)

        sched = make_sched(counting)
        sched.start()
        try:
            threads = [
                threading.Thread(
                    target=sched.verify,
                    args=(b"\x01" * 32, b"m%d" % i, b"\x02" * 64),
                )
                for i in range(n_entries)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        finally:
            sched.stop()
        return sizes

    def test_off_parity_same_flush_boundaries_as_static(self):
        """TENDERMINT_TPU_DYN_BATCH=off must reproduce the static
        scheduler's flush boundaries exactly: same size-triggered
        batch sequence for the same offered load."""
        a = self._flush_sizes(
            lambda fn: VerifyScheduler(fn, max_batch=8, max_delay=30.0), 24
        )
        b = self._flush_sizes(
            lambda fn: VerifyScheduler(
                fn, max_batch=8, max_delay=30.0, dyn_batch=False
            ),
            24,
        )
        assert a == b == [8, 8, 8]
        static = VerifyScheduler(ok_verify, max_batch=8, max_delay=30.0)
        off = VerifyScheduler(
            ok_verify, max_batch=8, max_delay=30.0, dyn_batch=False
        )
        assert off._dyn is None  # no controller is constructed at all
        assert static.resolved_knobs() == off.resolved_knobs()


# --- mesh-aware max_batch staleness (ISSUE 17 satellite) --------------------


class TestMeshAwareMaxBatch:
    def test_max_batch_tracks_mesh_reconfigure(self, monkeypatch):
        """Regression: a scheduler built BEFORE MeshManager.configure()
        must not bake the pre-configuration device count into its
        default max_batch forever."""
        from tendermint_tpu.parallel import mesh

        monkeypatch.setenv(mesh.MESH_ENV, "1")
        mesh.manager.reset()
        try:
            s = VerifyScheduler(ok_verify)
            assert s.max_batch == DEFAULT_MAX_BATCH
            mesh.manager.configure(8)  # the real topology lands late
            assert s.max_batch == DEFAULT_MAX_BATCH * 8
        finally:
            mesh.manager.reset()

    def test_explicit_max_batch_wins_over_mesh(self, monkeypatch):
        from tendermint_tpu.parallel import mesh

        s = VerifyScheduler(ok_verify, max_batch=17)
        mesh.manager.reset()
        try:
            assert s.max_batch == 17
            s.max_batch = 5  # operator override sticks too
            assert s.max_batch == 5
        finally:
            mesh.manager.reset()

    def test_config_gen_bumps_on_configure_and_reset(self):
        from tendermint_tpu.parallel import mesh

        g0 = mesh.manager.config_gen()
        mesh.manager.configure(1)
        g1 = mesh.manager.config_gen()
        assert g1 > g0
        mesh.manager.reset()
        assert mesh.manager.config_gen() > g1


# --- per-tenant SLO budgets -------------------------------------------------


class TestTenantSlo:
    def test_breach_shed_recovery_synthetic_clock(self):
        srv = VerifydServer(verify_fn=ok_verify)
        try:
            hot = srv._tenant_for("hot")
            cold = srv._tenant_for("cold")
            srv._tenant_declare_slo(hot, 10)  # 10ms p99 target
            now = 100.0
            # a cold sketch casts no verdicts
            for _ in range(server_mod._SLO_MIN_SAMPLES):
                srv._tenant_observe_latency(hot, 0.05, now)
            assert srv.tenant_stats()["hot"]["slo_shedding"] is False
            # sustained breach past the hysteresis window trips the gate
            srv._tenant_observe_latency(
                hot, 0.05, now + srv.slo_breach_after + 0.01
            )
            ten = srv.tenant_stats()["hot"]
            assert ten["slo_shedding"] is True
            t_shed = now + srv.slo_breach_after + 0.01
            assert srv._tenant_slo_gate(hot, t_shed + 0.01) is True
            # tenant-SCOPED: the other tenant is untouched
            assert srv._tenant_slo_gate(cold, t_shed + 0.01) is False
            assert srv.tenant_stats()["hot"]["slo_sheds"] == 1
            # release after the recovery clock, with a fresh sketch
            t_rec = t_shed + srv.slo_recover_after + 0.01
            assert srv._tenant_slo_gate(hot, t_rec) is False
            ten = srv.tenant_stats()["hot"]
            assert ten["slo_shedding"] is False
            assert ten["p99_ms"] == 0.0  # ring reset: fresh evidence only
        finally:
            srv.stop()

    def test_wire_declaration_tightest_wins_operator_pins(self):
        srv = VerifydServer(
            verify_fn=ok_verify, tenant_slos={"pinned": 30}
        )
        try:
            free = srv._tenant_for("free")
            srv._tenant_declare_slo(free, 50)
            assert srv.tenant_stats()["free"]["slo_ms"] == 50
            srv._tenant_declare_slo(free, 20)  # tighter: adopted
            assert srv.tenant_stats()["free"]["slo_ms"] == 20
            srv._tenant_declare_slo(free, 90)  # laxer: ignored
            assert srv.tenant_stats()["free"]["slo_ms"] == 20
            pinned = srv._tenant_for("pinned")
            srv._tenant_declare_slo(pinned, 1)  # operator pin wins
            assert srv.tenant_stats()["pinned"]["slo_ms"] == 30
        finally:
            srv.stop()

    def test_slo_shed_scoped_end_to_end(self):
        """Breach -> scoped shed -> exemption, through the real wire:
        the hot tenant's rpc is shed, its consensus is NOT, and the
        quiet tenant never notices."""

        def slow(pks, msgs, sigs):
            time.sleep(0.02)
            return [True] * len(pks)

        srv = VerifydServer(
            verify_fn=slow,
            max_batch=4,
            max_delay=0.001,
            tenant_slos={"hot": 2},  # 2ms target vs a 20ms device
            slo_breach_after=0.05,
            slo_recover_after=60.0,  # no release during the test
        )
        srv.start()
        try:
            addr = "%s:%d" % srv.address
            lanes = ([b"\x01" * 32], [b"slo"], [b"\x02" * 64])
            hot = VerifydClient(
                addr, tenant="hot", fallback=False, shed_retries=0
            )
            quiet = VerifydClient(
                addr, tenant="quiet", fallback=False, shed_retries=0
            )
            shed = False
            for _ in range(server_mod._SLO_MIN_SAMPLES + 40):
                try:
                    hot.verify(*lanes)  # rpc class by default
                except VerifydRejectedError:
                    shed = True
                    break
            assert shed, "hot tenant rpc was never SLO-shed"
            assert srv.tenant_stats()["hot"]["slo_sheds"] >= 1
            # consensus from the SAME tenant is exempt
            from tendermint_tpu.verifyd import protocol

            assert hot.verify(*lanes, klass=protocol.CLASS_CONSENSUS) == [
                True
            ]
            # the quiet tenant is untouched by hot's brownout
            assert quiet.verify(*lanes) == [True]
            assert srv.tenant_stats()["quiet"]["slo_sheds"] == 0
            hot.close()
            quiet.close()
        finally:
            srv.stop()

    def test_protocol_slo_field_roundtrip(self):
        from tendermint_tpu.verifyd import protocol

        req = protocol.VerifyRequest(
            pks=[b"\x01" * 32], msgs=[b"m"], sigs=[b"\x02" * 64], slo_ms=75
        )
        enc = protocol.encode_request(req)
        assert len(enc) == protocol.encoded_request_size(req)
        assert protocol.decode_request(enc).slo_ms == 75
        # zero is OMITTED on the wire and re-established on decode
        req.slo_ms = 0
        enc0 = protocol.encode_request(req)
        assert len(enc0) < len(enc)
        assert protocol.decode_request(enc0).slo_ms == 0
        # bound: a nonsense declaration is rejected at decode
        req.slo_ms = protocol.MAX_SLO_MS + 1
        with pytest.raises(ValueError):
            protocol.decode_request(protocol.encode_request(req))
