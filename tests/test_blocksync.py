"""Block sync tests: pool scheduling and the pipelined catch-up
(internal/blocksync/pool_test.go + reactor_test.go analog)."""

import pytest

from tendermint_tpu.blocksync import BlockPool, BlockSyncer
from tendermint_tpu.blocksync.syncer import PeerTransport
from tendermint_tpu.parallel.pipeline import CommitTask, verify_commits_pipelined
from tendermint_tpu.types import ExtendedCommit
from tests.helpers import CHAIN_ID, make_block_id, make_commit, make_validators
from tests.test_execution import advance_one_height, make_chain_env


def build_source_chain(n_heights, n_vals=4):
    """A fully-applied chain (executor harness) whose stores serve blocks."""
    executor, state, privs, vset, app = make_chain_env(n_vals)
    ec = ExtendedCommit()
    for h in range(1, n_heights + 1):
        txs = [b"h%d=v%d" % (h, h)]
        state, ec = advance_one_height(executor, state, privs, vset, txs, ec)
    return executor, state


class FakePeer(PeerTransport):
    """Serves blocks out of a source block store into the pool."""

    def __init__(self, pool, source_store, drop_heights=(), corrupt_heights=()):
        self.pool = pool
        self.store = source_store
        self.drop = set(drop_heights)
        self.corrupt = set(corrupt_heights)
        self.requests = []

    def request_block(self, peer_id, height):
        self.requests.append((peer_id, height))
        if height in self.drop:
            return
        block = self.store.load_block(height)
        if block is None:
            return
        if height in self.corrupt and block.last_commit.signatures:
            block.last_commit.signatures[0].signature = bytes(64)
            block.last_commit._hash = None
        self.pool.add_block(peer_id, block)


class TestBlockPool:
    def test_scheduling_and_delivery(self):
        pool = BlockPool(1)
        pool.set_peer_range("p1", 1, 5)
        reqs = pool.make_requests()
        assert [h for h, _ in reqs] == [1, 2, 3, 4, 5]
        assert pool.num_pending() == 5

    def test_per_peer_limit(self):
        pool = BlockPool(1)
        pool.set_peer_range("p1", 1, 100)
        reqs = pool.make_requests()
        assert len(reqs) == 20  # MAX_PENDING_REQUESTS_PER_PEER

    def test_add_block_only_from_assigned_peer(self, ):
        executor, _ = build_source_chain(2)
        block = executor.block_store.load_block(1)
        pool = BlockPool(1)
        pool.set_peer_range("p1", 1, 3)
        pool.make_requests()
        assert not pool.add_block("p2", block)  # wrong peer
        assert pool.add_block("p1", block)
        assert not pool.add_block("p1", block)  # duplicate

    def test_timeout_bans_peer(self):
        t = {"now": 0.0}
        pool = BlockPool(1, now=lambda: t["now"])
        pool.set_peer_range("p1", 1, 3)
        pool.make_requests()
        t["now"] = 100.0
        assert pool.check_timeouts() == ["p1"]
        assert pool.max_peer_height() == 0


class TestPipelinedVerification:
    def test_batch_of_commits(self):
        privs, vset = make_validators(4)
        tasks = []
        for h in range(1, 6):
            bid = make_block_id(b"blk%d" % h)
            commit = make_commit(bid, h, 0, vset, privs)
            tasks.append(CommitTask(CHAIN_ID, vset, bid, h, commit))
        verdicts = verify_commits_pipelined(tasks, use_device=False)
        assert all(v.ok for v in verdicts)

    def test_bad_commit_attributed_within_batch(self):
        privs, vset = make_validators(4)
        tasks = []
        for h in range(1, 6):
            bid = make_block_id(b"blk%d" % h)
            commit = make_commit(bid, h, 0, vset, privs)
            if h == 3:
                commit.signatures[1].signature = bytes(64)
            tasks.append(CommitTask(CHAIN_ID, vset, bid, h, commit))
        verdicts = verify_commits_pipelined(tasks, use_device=False)
        assert [v.ok for v in verdicts] == [True, True, False, True, True]
        assert "#1" in str(verdicts[2].error)

    def test_insufficient_power_detected(self):
        privs, vset = make_validators(4)
        bid = make_block_id()
        commit = make_commit(bid, 1, 0, vset, privs, absent={0, 1})
        verdicts = verify_commits_pipelined(
            [CommitTask(CHAIN_ID, vset, bid, 1, commit)], use_device=False
        )
        assert not verdicts[0].ok


class TestBlockSyncer:
    def _fresh_follower(self):
        from tests.test_execution import make_chain_env

        executor, state, privs, vset, app = make_chain_env(4)
        return executor, state

    def test_catch_up_pipelined(self):
        source_exec, source_state = build_source_chain(12)
        follower_exec, follower_state = self._fresh_follower()
        syncer = BlockSyncer(
            follower_state,
            follower_exec,
            follower_exec.block_store,
            transport=None,
            verify_window=8,
            use_device=False,
        )
        peer = FakePeer(syncer.pool, source_exec.block_store)
        syncer.transport = peer
        syncer.pool.set_peer_range("p1", 1, source_exec.block_store.height())
        applied_total = 0
        for _ in range(50):
            applied_total += syncer.step()
            # The syncer can apply at most height-1 (needs second block's
            # LastCommit for the last one).
            if syncer.state.last_block_height >= 11:
                break
        assert syncer.state.last_block_height >= 11
        # app state converged with the source at the synced height
        src = source_exec.state_store.load()
        dst = follower_exec.state_store.load()
        assert dst.last_block_height >= 11
        src_vals_h11 = source_exec.state_store.load_validators(11)
        dst_vals_h11 = follower_exec.state_store.load_validators(11)
        assert src_vals_h11.hash() == dst_vals_h11.hash()
        # identical block hashes along the chain
        for h in range(1, 12):
            assert (
                follower_exec.block_store.load_block_meta(h).block_id
                == source_exec.block_store.load_block_meta(h).block_id
            )

    def test_catch_up_through_sharded_mesh(self):
        """Blocksync ranges pipelined into device batches SHARDED over the
        8-mesh: the fetch window's commits verify in one sharded launch
        per pass and the follower converges on the source chain."""
        from tendermint_tpu.parallel import make_mesh

        source_exec, _ = build_source_chain(10)
        follower_exec, follower_state = self._fresh_follower()
        syncer = BlockSyncer(
            follower_state,
            follower_exec,
            follower_exec.block_store,
            transport=None,
            verify_window=8,
            mesh=make_mesh(8),
        )
        peer = FakePeer(syncer.pool, source_exec.block_store)
        syncer.transport = peer
        syncer.pool.set_peer_range("p1", 1, source_exec.block_store.height())
        for _ in range(50):
            syncer.step()
            if syncer.state.last_block_height >= 9:
                break
        assert syncer.state.last_block_height >= 9
        for h in range(1, 10):
            assert (
                follower_exec.block_store.load_block_meta(h).block_id
                == source_exec.block_store.load_block_meta(h).block_id
            )

    def test_corrupt_block_bans_peer_and_recovers(self):
        source_exec, _ = build_source_chain(8)
        follower_exec, follower_state = self._fresh_follower()
        syncer = BlockSyncer(
            follower_state,
            follower_exec,
            follower_exec.block_store,
            transport=None,
            verify_window=4,
            use_device=False,
        )
        bad_peer = FakePeer(syncer.pool, source_exec.block_store, corrupt_heights={4})
        good_peer = FakePeer(syncer.pool, source_exec.block_store)

        class Router(PeerTransport):
            def request_block(self, peer_id, height):
                (bad_peer if peer_id == "bad" else good_peer).request_block(
                    peer_id, height
                )

        syncer.transport = Router()
        syncer.pool.set_peer_range("bad", 1, 8)
        for _ in range(100):
            syncer.step()
            if syncer.state.last_block_height >= 2:
                break
            # after the ban, add the good peer (reactor would learn of it)
            if "bad" in syncer.pool._banned and "good" not in syncer.pool._peers:
                syncer.pool.set_peer_range("good", 1, 8)
        syncer.pool.set_peer_range("good", 1, 8)
        for _ in range(100):
            syncer.step()
            if syncer.state.last_block_height >= 7:
                break
        assert syncer.state.last_block_height >= 7
        assert "bad" in syncer.pool._banned
