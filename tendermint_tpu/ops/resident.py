"""Device-resident precompute table store for the verify hot path.

ops/precompute.py keeps the per-validator ``[1..8](-A)`` signed-window
tables on the *host*; until now every batch re-gathered the cached
columns and re-shipped a fresh ``(8, 4, 32, N)`` uint8 tensor to the
device — ~1 KiB per lane per call, even when the same 100-validator
committee signs every commit. This module closes that loop: the live
validator set's tables are uploaded **once** as a ``(8, 4, 32, K)``
device tensor, and steady-state batches ship only per-lane ``int32``
gather indices into it (ops/ed25519_batch.verify_kernel_resident does
the ``jnp.take`` on device). Rotation and LRU eviction invalidate the
device copy in lockstep with the host cache via the observer hook
(:func:`precompute.register_observer`) — a stale tensor can never
verify a rotated-out key because any change to the host entries drops
the device copy wholesale.

Sharding: when a mesh is planned the store is uploaded **replicated**
across the plan's devices (``P(None, None, None, None)``): the store
axis is *distinct keys*, not lanes, and a replicated store makes the
per-lane gather device-local, so the in-kernel gathered table tensor
comes out lane-sharded ``P(None, None, None, 'sig')`` with zero
collectives — same layout the sharded table kernel always used. A
committee's worth of tables is ~100 KiB; replication is cheaper than
one cross-device gather.

Column 0 is reserved for the pad-lane table so padded lanes index
something valid; real keys start at column 1.

Env knob / config::

    TENDERMINT_TPU_RESIDENT   auto (default: on for tpu/axon) | on | off
    [ops] resident_tables     same values, via node config -> configure()

This module fails safe everywhere: any trouble (no device, upload
failure, mesh mismatch) returns None from :func:`acquire` and the
caller keeps the per-batch gathered-table path.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.sanitizer import instrument_attrs

_ENV = "TENDERMINT_TPU_RESIDENT"

# Keys seen this many times via note_hot_keys get pinned in the host
# cache (verifyd traffic has no validator-set activation to ride).
_HOT_PIN_THRESHOLD = 2
_HOT_TRACK_CAP = 4096

# Host-staged footprint of one key's signed-window table: the
# ``(8, 4, 32)`` uint8 column that joins the resident upload. Pinned
# keys hold this much host memory whether or not a device copy exists,
# so the partitioned-fleet ledger can show per-shard table placement
# even on CPU (where the device upload never happens).
TABLE_BYTES_PER_KEY = 8 * 4 * 32


def _platform(backend: Optional[str]) -> str:
    try:
        import jax

        if backend:
            return jax.local_devices(backend=backend)[0].platform
        return jax.default_backend()
    except Exception:
        return "unknown"


@instrument_attrs
class ResidentTableStore:
    """Thread-safe device mirror of the host precompute cache."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._mode_override: Optional[str] = None  # guarded-by: _lock
        self._index: Dict[bytes, int] = {}  # guarded-by: _lock
        self._tab_dev = None  # guarded-by: _lock  (8,4,32,K) device uint8
        self._ok_host: Optional[np.ndarray] = None  # guarded-by: _lock
        self._mesh_key: Optional[tuple] = None  # guarded-by: _lock
        self._backend_key: Optional[str] = None  # guarded-by: _lock
        self._version = 0  # guarded-by: _lock
        self._metrics = None  # guarded-by: _lock
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.uploads = 0  # guarded-by: _lock
        self.h2d_bytes = 0  # guarded-by: _lock
        self.gathered_h2d_bytes = 0  # guarded-by: _lock
        self.invalidations = 0  # guarded-by: _lock
        self._hot_counts: Dict[bytes, int] = {}  # guarded-by: _lock
        self._tenant_pins: Dict[str, int] = {}  # guarded-by: _lock
        self.pin_quota_denials = 0  # guarded-by: _lock
        # keys THIS process pinned via note_hot_keys — the shard's
        # slice of the partitioned fleet. Mirrored to the introspect
        # ledger as host-staged bytes so `verifyd stats` shows table
        # placement per shard (disjoint across a federation) even on
        # CPU, where the device upload never happens.
        self._pinned: set = set()  # guarded-by: _lock

    # --- configuration ------------------------------------------------------

    def configure(self, mode: Optional[str]) -> None:
        """Config-file override of the env knob (``[ops] resident_tables``)."""
        with self._lock:
            self._mode_override = mode.lower() if mode else None

    def mode(self) -> str:
        with self._lock:
            override = self._mode_override
        if override:
            return override
        return os.environ.get(_ENV, "auto").lower()

    def enabled(self, backend: Optional[str] = None) -> bool:
        m = self.mode()
        if m in ("1", "on", "true", "yes", "all"):
            return True
        if m in ("0", "off", "none", "false"):
            return False
        # auto: accelerator backends only — CPU ships tables per batch
        # exactly as before, so tier-1 behavior is unchanged.
        return _platform(backend) in ("tpu", "axon")

    def bind_metrics(self, metrics) -> None:
        with self._lock:
            self._metrics = metrics

    # --- upload / invalidate ------------------------------------------------

    def _context_key(self, plan, backend: Optional[str]) -> Tuple[Optional[tuple], Optional[str]]:
        if plan is not None:
            return tuple(plan.device_ids), None
        return None, backend

    def refresh(self, plan=None, backend: Optional[str] = None) -> bool:
        """Upload the host cache's live-committee slice to the device.

        Builds the ``(8, 4, 32, K)`` tensor on host (column 0 = pad
        table), ships it once, and installs it unless an invalidation
        raced the upload (version check). Returns True when a usable
        device copy is installed.
        """
        from tendermint_tpu.ops import ed25519_batch, precompute

        snap = precompute.tables.snapshot_eligible()
        if not snap:
            return False
        mesh_key, backend_key = self._context_key(plan, backend)
        with self._lock:
            version = self._version
        cols = [ed25519_batch._pad_table()]
        oks = [True]
        index: Dict[bytes, int] = {}
        for pk, table, ok in snap:
            index[pk] = len(cols)
            cols.append(table)
            oks.append(ok)
        host_tab = np.ascontiguousarray(
            np.stack(cols).transpose(1, 2, 3, 0)
        )  # (8, 4, 32, K)
        nbytes = int(host_tab.nbytes)
        try:
            with tracing.span(
                "resident_upload",
                stage="resident_upload",
                engine="ed25519",
                keys=len(index),
                bytes=nbytes,
            ):
                tab_dev = self._device_put(host_tab, plan, backend)
        except Exception:  # upload is an optimization; fail safe to gather
            return False
        with self._lock:
            if self._version != version:
                # an invalidation raced the upload: the snapshot may be
                # stale, drop it and let the next batch retry
                return False
            self._index = index
            self._tab_dev = tab_dev
            self._ok_host = np.asarray(oks, dtype=np.uint8)
            self._mesh_key = mesh_key
            self._backend_key = backend_key
            self.uploads += 1
            self.h2d_bytes += nbytes
            metrics = self._metrics
            pins = dict(self._tenant_pins)
        if metrics is not None:
            metrics.table_h2d_bytes.inc(nbytes)
        # Device-tier ledger (ops/introspect.py): the installed tensor
        # is THE resident_tables allocation — absolute-set keeps the
        # ledger exact across rotation (drop zeroes it, the re-upload
        # sets the new size).
        from tendermint_tpu.ops import introspect

        introspect.set_bytes("resident_tables", nbytes)
        introspect.accountant.set_tenant_bytes(nbytes, pins)
        return True

    @staticmethod
    def _device_put(host_tab: np.ndarray, plan, backend: Optional[str]):
        import jax

        if plan is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(
                host_tab,
                NamedSharding(plan.mesh, PartitionSpec(None, None, None, None)),
            )
        dev = jax.local_devices(backend=backend)[0] if backend else None
        if dev is not None:
            return jax.device_put(host_tab, dev)
        return jax.device_put(host_tab)

    def invalidate(self, pubkeys: Iterable[bytes]) -> None:
        """Host cache dropped these keys: the device copy dies with them."""
        keys = [bytes(pk) for pk in pubkeys]
        with self._lock:
            # an evicted key leaves the shard's pinned slice whether or
            # not a device copy exists — the host column is gone
            if any(pk in self._pinned for pk in keys):
                self._pinned.difference_update(keys)
                self._account_host_locked()
            if self._tab_dev is None:
                return
            if not any(pk in self._index for pk in keys):
                return
            self._drop_locked()

    def clear(self) -> None:
        with self._lock:
            self._drop_locked()
            self._hot_counts.clear()
            self._pinned.clear()
            self._account_host_locked()

    def _drop_locked(self) -> None:
        if self._tab_dev is not None:
            self.invalidations += 1
        self._index = {}
        self._tab_dev = None
        self._ok_host = None
        self._mesh_key = None
        self._backend_key = None
        self._version += 1
        # the introspect ledger holds its own (leaf) lock, never ours
        from tendermint_tpu.ops import introspect

        introspect.set_bytes("resident_tables", 0)
        introspect.accountant.set_tenant_bytes(0, {})

    def _account_host_locked(self) -> None:
        """Mirror the pinned slice to the introspect ledger under its
        own owner label ("resident_tables_host"): host-staged bytes,
        distinct from the device tensor, so a federation's per-shard
        memstats show the PARTITIONED placement — each shard's entry is
        its slice, and the fleet aggregate grows linearly."""
        from tendermint_tpu.ops import introspect

        introspect.set_bytes(
            "resident_tables_host", len(self._pinned) * TABLE_BYTES_PER_KEY
        )

    # --- lookup -------------------------------------------------------------

    def acquire(
        self,
        pubkeys: Sequence[bytes],
        has_table: np.ndarray,
        plan=None,
        backend: Optional[str] = None,
    ):
        """Resident routing for one batch.

        For lanes with a host-cached table (``has_table``), answers
        which can ride the resident kernel: returns ``(res_mask, idx,
        ok, tab_dev, mesh_key)`` where ``res_mask`` is the (N,) bool
        lane partition, ``idx``/``ok`` are full-length per-lane arrays
        (garbage outside the mask), and ``tab_dev`` is the device
        tensor. Returns None when the resident path is off, empty, or
        uploaded for a different mesh/backend context.
        """
        if not self.enabled(backend):
            return None
        n = len(pubkeys)
        want_key = self._context_key(plan, backend)
        with self._lock:
            stale = self._tab_dev is None or (
                (self._mesh_key, self._backend_key) != want_key
            )
            if not stale:
                # committee growth: a host-cached key the store has not
                # seen yet means the upload predates it — refresh once
                # so new validators join the resident tensor
                index = self._index
                stale = any(
                    has_table[i] and bytes(pubkeys[i]) not in index
                    for i in range(n)
                )
        if stale:
            if not self.refresh(plan=plan, backend=backend):
                return None
        with self._lock:
            tab_dev = self._tab_dev
            ok_host = self._ok_host
            index = self._index
            if tab_dev is None or (
                (self._mesh_key, self._backend_key) != want_key
            ):
                return None
            idx = np.zeros(n, dtype=np.int32)
            res_mask = np.zeros(n, dtype=bool)
            hits = misses = 0
            for i in range(n):
                if not has_table[i]:
                    continue
                col = index.get(bytes(pubkeys[i]))
                if col is None:
                    misses += 1
                    continue
                idx[i] = col
                res_mask[i] = True
                hits += 1
            self.hits += hits
            self.misses += misses
            metrics = self._metrics
        if metrics is not None:
            if hits:
                metrics.table_resident_hits.inc(hits)
            if misses:
                metrics.table_resident_misses.inc(misses)
        if not res_mask.any():
            return None
        return res_mask, idx, ok_host, tab_dev, want_key[0]

    # --- verifyd / accounting hooks ----------------------------------------

    def note_hot_keys(
        self,
        pubkeys: Iterable[bytes],
        tenant: Optional[str] = None,
        quota: int = 0,
    ) -> None:
        """Count repeat signers from set-less traffic (verifyd): a key
        seen ``_HOT_PIN_THRESHOLD`` times gets pinned in the host cache
        so it joins the next resident upload.

        ``tenant``/``quota`` cap how many pins one namespace may hold
        (multi-tenant verifyd): past ``quota`` pins, a tenant's further
        hot keys are counted as ``pin_quota_denials`` instead of pinned,
        so one chain's validator universe can't monopolize the resident
        tensor. ``quota=0`` (or no tenant) keeps the unlimited behavior.
        """
        to_pin = []
        with self._lock:
            for pk in pubkeys:
                pk = bytes(pk)
                if len(pk) != 32:
                    continue
                c = self._hot_counts.get(pk, 0) + 1
                if c >= _HOT_PIN_THRESHOLD:
                    if tenant is not None and quota > 0:
                        used = self._tenant_pins.get(tenant, 0)
                        if used >= quota:
                            self.pin_quota_denials += 1
                            self._hot_counts.pop(pk, None)
                            continue
                        self._tenant_pins[tenant] = used + 1
                    self._hot_counts.pop(pk, None)
                    to_pin.append(pk)
                elif len(self._hot_counts) < _HOT_TRACK_CAP:
                    self._hot_counts[pk] = c
            if to_pin:
                self._pinned.update(to_pin)
                self._account_host_locked()
        if to_pin:
            from tendermint_tpu.ops import precompute

            precompute.pin_pubkeys(to_pin)

    def note_table_h2d(self, nbytes: int) -> None:
        """Account a gathered-table (non-resident) per-batch upload."""
        with self._lock:
            self.gathered_h2d_bytes += int(nbytes)
            metrics = self._metrics
        if metrics is not None:
            metrics.table_h2d_bytes.inc(int(nbytes))

    # --- introspection ------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "resident_keys": len(self._index),
                "hits": self.hits,
                "misses": self.misses,
                "uploads": self.uploads,
                "h2d_bytes": self.h2d_bytes,
                "gathered_h2d_bytes": self.gathered_h2d_bytes,
                "invalidations": self.invalidations,
                "pin_quota_denials": self.pin_quota_denials,
                "pinned_keys": len(self._pinned),
                "host_staged_bytes": len(self._pinned) * TABLE_BYTES_PER_KEY,
            }

    def pinned_keys(self) -> list:
        """Hex identities of this process's pinned slice (sorted). The
        verifyd_fleet bench compares these across shards to prove the
        federation PARTITIONS tables instead of replicating them."""
        with self._lock:
            return sorted(pk.hex() for pk in self._pinned)

    def tenant_pins(self) -> Dict[str, int]:
        """Pins held per tenant namespace (quota introspection)."""
        with self._lock:
            return dict(self._tenant_pins)

    def reset(self) -> None:
        with self._lock:
            self._drop_locked()
            self._hot_counts.clear()
            self._tenant_pins.clear()
            self._pinned.clear()
            self._account_host_locked()
            self.hits = self.misses = self.uploads = 0
            self.h2d_bytes = self.gathered_h2d_bytes = 0
            self.invalidations = 0
            self.pin_quota_denials = 0


# --- process-wide singleton --------------------------------------------------

store = ResidentTableStore()


def _on_cache_event(kind: str, payload: tuple) -> None:
    """precompute.py observer: host invalidation -> device invalidation."""
    if kind in ("rotation", "evict"):
        store.invalidate(payload)
    elif kind == "clear":
        store.clear()


def _install_observer() -> None:
    from tendermint_tpu.ops import precompute

    precompute.register_observer(_on_cache_event)


_install_observer()


def acquire(pubkeys, has_table, plan=None, backend=None):
    return store.acquire(pubkeys, has_table, plan=plan, backend=backend)


def enabled(backend: Optional[str] = None) -> bool:
    return store.enabled(backend)


def configure(mode: Optional[str]) -> None:
    store.configure(mode)


def bind_metrics(metrics) -> None:
    store.bind_metrics(metrics)


def note_hot_keys(
    pubkeys: Iterable[bytes],
    tenant: Optional[str] = None,
    quota: int = 0,
) -> None:
    store.note_hot_keys(pubkeys, tenant=tenant, quota=quota)


def note_table_h2d(nbytes: int) -> None:
    store.note_table_h2d(nbytes)


def note_validator_rotation() -> None:
    """Consensus noticed a validator-set change before the host cache
    did (crypto/batch.note_validator_set): drop the device copy now so
    the next batch re-uploads against the fresh committee."""
    store.clear()


def stats() -> Dict[str, float]:
    return store.stats()


def pinned_keys() -> list:
    return store.pinned_keys()


def reset() -> None:
    store.reset()
