"""CLI + TOML config tests (cmd/tendermint + config/toml.go analogs).

The flagship case mirrors the reference testnet flow: generate 4 home
dirs with `testnet`, start 4 separate OS processes with `start`, and
watch every node commit blocks over real TCP with filedb persistence.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from tendermint_tpu.config import Config
from tendermint_tpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port_block(n: int) -> int:
    """Find a base port with n*2 consecutive free ports (best effort)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    base = s.getsockname()[1]
    s.close()
    # steer clear of the ephemeral range edge
    return base if base + 2 * n < 65000 else base - 4 * n


def _rpc_height(port: int) -> int:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/status", timeout=2
    ) as resp:
        doc = json.load(resp)
    return int(doc["result"]["sync_info"]["latest_block_height"])


def _run(args) -> int:
    return cli_main(args)


class TestConfigToml:
    def test_roundtrip(self, tmp_path):
        cfg = Config(home=str(tmp_path))
        cfg.base.moniker = "alpha"
        cfg.base.proxy_app = "persistent_kvstore"
        cfg.p2p.laddr = "127.0.0.1:11111"
        cfg.p2p.persistent_peers = ["aa@1.2.3.4:5", "bb@6.7.8.9:10"]
        cfg.rpc.laddr = "127.0.0.1:22222"
        cfg.mempool.size = 77
        cfg.statesync.enabled = True
        cfg.statesync.trust_height = 42
        cfg.statesync.trust_hash = b"\xab\xcd"
        cfg.privval.laddr = "tcp://127.0.0.1:33333"
        cfg.save()

        loaded = Config.load(str(tmp_path))
        assert loaded.base.moniker == "alpha"
        assert loaded.base.proxy_app == "persistent_kvstore"
        assert loaded.p2p.persistent_peers == cfg.p2p.persistent_peers
        assert loaded.mempool.size == 77
        assert loaded.statesync.enabled is True
        assert loaded.statesync.trust_height == 42
        assert loaded.statesync.trust_hash == b"\xab\xcd"
        assert loaded.privval.laddr == "tcp://127.0.0.1:33333"

    def test_to_node_config(self, tmp_path):
        cfg = Config(home=str(tmp_path))
        cfg.statesync.enabled = False
        nc = cfg.to_node_config(chain_id="x")
        assert nc.chain_id == "x"
        assert nc.statesync is None  # disabled -> not wired
        cfg.statesync.enabled = True
        assert cfg.to_node_config().statesync is cfg.statesync

    def test_unknown_keys_ignored(self, tmp_path):
        text = '[base]\nmoniker = "m"\nfuture_knob = 3\n[bogus]\nx = 1\n'
        cfg = Config.from_toml(text)
        assert cfg.base.moniker == "m"


class TestInitAndKeys:
    def test_init_creates_layout(self, tmp_path):
        home = str(tmp_path / "h")
        assert _run(["--home", home, "init", "--chain-id", "c1"]) == 0
        cfg = Config(home=home)
        for path in (
            cfg.config_file(),
            cfg.genesis_file(),
            cfg.node_key_file(),
            cfg.privval_key_file(),
        ):
            assert os.path.exists(path), path
        # refuses to clobber without --force
        assert _run(["--home", home, "init"]) == 1
        assert _run(["--home", home, "init", "--force"]) == 0

    def test_show_commands(self, tmp_path, capsys):
        home = str(tmp_path / "h")
        _run(["--home", home, "init"])
        capsys.readouterr()  # drain init output
        assert _run(["--home", home, "show-node-id"]) == 0
        node_id = capsys.readouterr().out.strip()
        assert len(node_id) == 40
        assert _run(["--home", home, "show-validator"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["type"] == "ed25519" and doc["value"]

    def test_unsafe_reset_keeps_keys(self, tmp_path):
        home = str(tmp_path / "h")
        _run(["--home", home, "init"])
        cfg = Config(home=home)
        key_before = open(cfg.privval_key_file()).read()
        marker = os.path.join(cfg.data_dir(), "junk.db")
        open(marker, "w").write("x")
        assert _run(["--home", home, "unsafe-reset-all"]) == 0
        assert not os.path.exists(marker)
        assert open(cfg.privval_key_file()).read() == key_before

    def test_start_without_init_errors(self, tmp_path):
        assert _run(["--home", str(tmp_path / "nope"), "start"]) == 1


def _fast_genesis_overwrite(home: str) -> None:
    """Shrink consensus timeouts for test speed (operators tune these via
    genesis consensus_params; tests are just an aggressive operator)."""
    from tendermint_tpu.types.genesis import GenesisDoc
    from tendermint_tpu.types.params import TimeoutParams

    cfg = Config(home=home)
    doc = GenesisDoc.from_file(cfg.genesis_file())
    doc.consensus_params.timeout = TimeoutParams(
        propose=0.6, propose_delta=0.2, vote=0.3, vote_delta=0.1, commit=0.1
    )
    doc.save_as(cfg.genesis_file())


class TestNodeLifecycle:
    def _spawn(self, home: str):
        return subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu", "--home", home, "start"],
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )

    def _wait_height(self, port: int, target: int, timeout: float) -> int:
        deadline = time.monotonic() + timeout
        height = -1
        while time.monotonic() < deadline:
            try:
                height = _rpc_height(port)
                if height >= target:
                    return height
            except Exception:
                pass
            time.sleep(0.5)
        return height

    def test_single_node_commits_and_persists(self, tmp_path):
        home = str(tmp_path / "n0")
        _run(["--home", home, "init", "--chain-id", "cli-one"])
        _fast_genesis_overwrite(home)
        port = _free_port_block(1)
        cfg = Config.load(home)
        cfg.p2p.laddr = f"127.0.0.1:{port}"
        cfg.rpc.laddr = f"127.0.0.1:{port + 1}"
        cfg.save()

        proc = self._spawn(home)
        try:
            height = self._wait_height(port + 1, 3, timeout=60)
            assert height >= 3, f"node never reached height 3 (got {height})"
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)

        # stores survived shutdown: inspect sees the committed chain
        out = subprocess.run(
            [sys.executable, "-m", "tendermint_tpu", "--home", home, "inspect"],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=30,
        )
        doc = json.loads(out.stdout)
        assert doc["latest_block_height"] >= 3
        assert doc["chain_id"] == "cli-one"

    def test_four_process_testnet_commits(self, tmp_path):
        """VERDICT round-2 item 10 'Done =': a 4-process localhost testnet
        starts from generated configs and commits blocks."""
        out_dir = str(tmp_path / "tn")
        base = _free_port_block(4)
        assert (
            _run(
                [
                    "testnet",
                    "-v",
                    "4",
                    "-o",
                    out_dir,
                    "--chain-id",
                    "cli-tn",
                    "--starting-port",
                    str(base),
                ]
            )
            == 0
        )
        homes = [os.path.join(out_dir, f"node{i}") for i in range(4)]
        for home in homes:
            _fast_genesis_overwrite(home)
        procs = [self._spawn(h) for h in homes]
        try:
            heights = [
                self._wait_height(base + 2 * i + 1, 2, timeout=90)
                for i in range(4)
            ]
            assert all(h >= 2 for h in heights), f"heights: {heights}"
        finally:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestRollback:
    def test_rollback_then_restart(self, tmp_path):
        home = str(tmp_path / "n0")
        _run(["--home", home, "init", "--chain-id", "rb"])
        _fast_genesis_overwrite(home)
        port = _free_port_block(1)
        cfg = Config.load(home)
        cfg.p2p.laddr = f"127.0.0.1:{port}"
        cfg.rpc.laddr = f"127.0.0.1:{port + 1}"
        cfg.save()
        proc = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu", "--home", home, "start"],
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        try:
            deadline = time.monotonic() + 60
            height = -1
            while time.monotonic() < deadline and height < 3:
                try:
                    height = _rpc_height(port + 1)
                except Exception:
                    pass
                time.sleep(0.5)
            assert height >= 3
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)

        assert _run(["--home", home, "rollback"]) == 0
        # replay pushes the stored blocks back into a fresh app
        assert _run(["--home", home, "replay"]) == 0


class TestDebugTools:
    def test_wal2json(self, tmp_path, capsys):
        from tendermint_tpu.consensus.wal import WAL, EndHeightMessage, TimeoutInfo

        path = str(tmp_path / "cs.wal")
        w = WAL(path)
        w.start()
        w.write(TimeoutInfo(0.5, 3, 1, 2))
        w.write_sync(EndHeightMessage(3))
        w.stop()
        assert _run(["wal2json", path]) == 0
        lines = [json.loads(s) for s in capsys.readouterr().out.splitlines()]
        assert [d["type"] for d in lines] == ["TimeoutInfo", "EndHeightMessage"]
        assert lines[0]["height"] == 3 and lines[0]["round"] == 1
        assert lines[1]["height"] == 3

    def test_abci_cli_against_socket_app(self, capsys):
        import subprocess
        import socket as socketlib
        import time as timelib

        s = socketlib.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "tendermint_tpu.abci.socket_server",
                "--addr",
                f"127.0.0.1:{port}",
            ],
            cwd=REPO,
        )
        try:
            deadline = timelib.monotonic() + 15
            while timelib.monotonic() < deadline:
                try:
                    probe = socketlib.create_connection(("127.0.0.1", port), 1)
                    probe.close()
                    break
                except OSError:
                    timelib.sleep(0.2)
            else:
                pytest.fail("socket app never came up")
            addr = f"tcp://127.0.0.1:{port}"
            assert _run(["abci", "echo", "ping!", "--addr", addr]) == 0
            assert capsys.readouterr().out.strip() == "ping!"
            assert _run(["abci", "info", "--addr", addr]) == 0
            doc = json.loads(capsys.readouterr().out)
            assert doc["last_block_height"] == 0
            assert _run(["abci", "check-tx", "a=b", "--addr", addr]) == 0
            assert json.loads(capsys.readouterr().out)["code"] == 0
            assert _run(["abci", "query", "a", "--addr", addr]) == 0
            assert "log" in json.loads(capsys.readouterr().out)
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_compact_db(self, tmp_path):
        from tendermint_tpu.storage import open_db

        home = str(tmp_path / "h")
        data = os.path.join(home, "data")
        os.makedirs(data)
        os.makedirs(os.path.join(home, "config"))
        db = open_db("filedb", data, "bloat")
        for _ in range(300):
            db.set(b"k", b"v" * 100)  # 299 dead versions
        db.set(b"other", b"live")
        db.close()
        before = os.path.getsize(os.path.join(data, "bloat.fdb"))
        assert _run(["--home", home, "compact-db"]) == 0
        after = os.path.getsize(os.path.join(data, "bloat.fdb"))
        assert after < before / 10
        db = open_db("filedb", data, "bloat")
        assert db.get(b"k") == b"v" * 100
        assert db.get(b"other") == b"live"
        db.close()

    def test_inspect_serve(self, tmp_path):
        """inspect --serve: read-only RPC over a stopped node's stores
        (internal/inspect/inspect.go:31)."""
        home = str(tmp_path / "h")
        _run(["--home", home, "init", "--chain-id", "ins"])
        _fast_genesis_overwrite(home)
        port = _free_port_block(1)
        cfg = Config.load(home)
        cfg.p2p.laddr = f"127.0.0.1:{port}"
        cfg.rpc.laddr = f"127.0.0.1:{port + 1}"
        cfg.save()
        proc = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu", "--home", home, "start"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        try:
            deadline = time.monotonic() + 60
            h = -1
            while time.monotonic() < deadline and h < 3:
                try:
                    h = _rpc_height(port + 1)
                except Exception:
                    pass
                time.sleep(0.5)
            assert h >= 3
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)
        # node stopped: serve the stores read-only
        iport = _free_port_block(1)
        srv = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu", "--home", home,
             "inspect", "--serve", f"127.0.0.1:{iport}"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        try:
            deadline = time.monotonic() + 30
            doc = None
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{iport}/block?height=2", timeout=2
                    ) as resp:
                        doc = json.load(resp)
                    break
                except Exception:
                    time.sleep(0.5)
            assert doc and int(doc["result"]["block"]["header"]["height"]) == 2
            with urllib.request.urlopen(
                f"http://127.0.0.1:{iport}/validators?height=2", timeout=5
            ) as resp:
                vdoc = json.load(resp)
            assert vdoc["result"]["count"] == "1"
        finally:
            srv.send_signal(signal.SIGTERM)
            srv.wait(timeout=10)

    def test_reindex_event_rebuilds_lost_index(self, tmp_path):
        """commands/reindex_event.go: wipe the tx index of a stopped
        node, rebuild it from stored blocks + persisted FinalizeBlock
        responses, and find a committed tx again."""
        import base64
        import hashlib

        home = str(tmp_path / "h")
        _run(["--home", home, "init", "--chain-id", "reidx"])
        _fast_genesis_overwrite(home)
        port = _free_port_block(1)
        cfg = Config.load(home)
        cfg.p2p.laddr = f"127.0.0.1:{port}"
        cfg.rpc.laddr = f"127.0.0.1:{port + 1}"
        cfg.save()
        proc = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu", "--home", home, "start"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        tx = b"reidx=1"
        try:
            deadline = time.monotonic() + 60
            up = False
            while time.monotonic() < deadline and not up:
                try:
                    _rpc_height(port + 1)
                    up = True
                except Exception:
                    time.sleep(0.5)
            assert up
            body = json.dumps(
                {
                    "jsonrpc": "2.0", "id": 1, "method": "broadcast_tx_sync",
                    "params": {"tx": base64.b64encode(tx).decode()},
                }
            ).encode()
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port + 1}", body,
                    {"Content-Type": "application/json"},
                ),
                timeout=10,
            )
            h = hashlib.sha256(tx).hexdigest()
            committed = False
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not committed:
                q = json.dumps(
                    {"jsonrpc": "2.0", "id": 2, "method": "tx",
                     "params": {"hash": "0x" + h}}
                ).encode()
                try:
                    with urllib.request.urlopen(
                        urllib.request.Request(
                            f"http://127.0.0.1:{port + 1}", q,
                            {"Content-Type": "application/json"},
                        ),
                        timeout=3,
                    ) as resp:
                        committed = "result" in json.load(resp)
                except Exception:
                    pass
                if not committed:
                    time.sleep(0.5)
            assert committed, "tx never committed"
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)

        # lose the index, rebuild, and find the tx offline
        for f in os.listdir(os.path.join(home, "data")):
            if f.startswith("tx_index"):
                os.unlink(os.path.join(home, "data", f))
        assert _run(["--home", home, "reindex-event"]) == 0
        from tendermint_tpu.indexer import KVIndexer
        from tendermint_tpu.storage import open_db

        idx = KVIndexer(open_db("filedb", os.path.join(home, "data"), "tx_index"))
        tr = idx.get_tx(hashlib.sha256(tx).digest())
        assert tr is not None and tr.tx == tx

    def test_confix_migrates_schema(self, tmp_path, capsys):
        home = str(tmp_path / "h")
        _run(["--home", home, "init", "--chain-id", "cfx"])
        capsys.readouterr()
        path = Config(home=home).config_file()
        text = open(path).read()
        text = text.replace('log_level = "info"\n', "")  # missing new key
        text = text.replace(
            "[p2p]", "obsolete_flag = true\n\n[p2p]", 1
        )  # dead key
        open(path, "w").write(text)
        assert _run(["--home", home, "confix", "--dry-run"]) == 0
        out = capsys.readouterr().out
        assert "obsolete_flag" in out and "log_level" in out
        assert 'log_level = "info"' not in open(path).read()  # not rewritten
        assert _run(["--home", home, "confix"]) == 0
        capsys.readouterr()
        migrated = open(path).read()
        assert 'log_level = "info"' in migrated
        assert "obsolete_flag" not in migrated
        assert os.path.exists(path + ".bak")
        # idempotent
        assert _run(["--home", home, "confix"]) == 0
        assert "already matches" in capsys.readouterr().out
        # node still starts from the migrated config
        loaded = Config.load(home)
        assert loaded.base.log_level == "info"


def test_key_migrate_roundtrip(tmp_path, capsys):
    """key-migrate re-encodes every store into a fresh backend dir and
    the migrated stores contain identical data (scripts/keymigrate
    analog over this tree's backend seam)."""
    from tendermint_tpu.cli import main
    from tendermint_tpu.storage import open_db

    home = str(tmp_path / "mig")
    assert main(["--home", home, "init", "--chain-id", "mig-chain"]) == 0
    # put some data in a store the migrated dir must reproduce
    data_dir = os.path.join(home, "data")
    db = open_db("filedb", data_dir, "state")
    for i in range(100):
        db.set(b"k%03d" % i, b"v%d" % i)
    db.close()

    assert main(["--home", home, "key-migrate", "--to-backend", "filedb-py"]) == 0
    out_dir = data_dir + "-migrated"
    assert os.path.isdir(out_dir)
    src = open_db("filedb", data_dir, "state")
    dst = open_db("filedb-py", out_dir, "state")
    src_kv = list(src.iterator())
    dst_kv = list(dst.iterator())
    assert src_kv == dst_kv and len(dst_kv) >= 100
    src.close()
    dst.close()
