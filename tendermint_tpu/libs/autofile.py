"""Rotating append-only file group (internal/libs/autofile/group.go).

A Group is a logically-infinite append log physically split into chunks:
writes go to the *head* file; when the head passes ``head_size_limit``
it is sealed into a chunk named ``<head>.<base>`` (base = the chunk's
starting logical offset, zero-padded so lexicographic order is logical
order) and a fresh head opens. When the group's total size passes
``total_size_limit`` the oldest chunks are pruned (group.go's
checkTotalSizeLimit), which is safe for the consensus WAL: replay only
ever starts at the latest #ENDHEIGHT marker.

Readers address bytes by LOGICAL offset — stable across rotation and
pruning — which is what keeps the WAL's replay-offset contract intact.
"""

from __future__ import annotations

import os
import re
from typing import List, Optional, Tuple

DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024  # group.go defaultHeadSizeLimit
DEFAULT_TOTAL_SIZE_LIMIT = 1024 * 1024 * 1024  # defaultTotalSizeLimit (1GB)

_CHUNK_RE = re.compile(r"\.(\d{16})$")


class Group:
    def __init__(
        self,
        head_path: str,
        head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT,
        total_size_limit: int = DEFAULT_TOTAL_SIZE_LIMIT,
    ):
        self.head_path = head_path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        self._head = None
        self._head_base = 0  # logical offset where the head starts
        self._head_size = 0

    # --- chunk bookkeeping ---------------------------------------------------

    def _chunk_paths(self) -> List[Tuple[int, str]]:
        """Sealed chunks as (base_offset, path), oldest first."""
        directory = os.path.dirname(self.head_path) or "."
        prefix = os.path.basename(self.head_path) + "."
        chunks = []
        try:
            names = os.listdir(directory)
        except FileNotFoundError:
            return []
        for name in names:
            if not name.startswith(prefix):
                continue
            m = _CHUNK_RE.search(name)
            if m:
                chunks.append(
                    (int(m.group(1)), os.path.join(directory, name))
                )
        chunks.sort()
        return chunks

    def _derived_head_base(self) -> int:
        """The head's logical base derived from sealed chunks — correct
        whether or not the group is started (reads on an unstarted group
        must see the same offsets a started one would)."""
        chunks = self._chunk_paths()
        if chunks:
            last_base, last_path = chunks[-1]
            return last_base + os.path.getsize(last_path)
        return self._head_base

    def segments(self) -> List[Tuple[int, str]]:
        """All readable segments (base_offset, path), oldest first,
        head last."""
        segs = self._chunk_paths()
        head_base = self._head_base if self._head is not None else (
            self._derived_head_base()
        )
        if os.path.exists(self.head_path):
            segs.append((head_base, self.head_path))
        return segs

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        chunks = self._chunk_paths()
        if chunks:
            last_base, last_path = chunks[-1]
            self._head_base = last_base + os.path.getsize(last_path)
        else:
            self._head_base = 0
        self._head_size = (
            os.path.getsize(self.head_path)
            if os.path.exists(self.head_path)
            else 0
        )
        os.makedirs(os.path.dirname(self.head_path) or ".", exist_ok=True)
        self._head = open(self.head_path, "ab")

    def stop(self) -> None:
        if self._head is not None:
            self._head.flush()
            os.fsync(self._head.fileno())
            self._head.close()
            self._head = None

    # --- writing -------------------------------------------------------------

    def write(self, data: bytes) -> None:
        if self._head is None:
            raise RuntimeError("autofile group not started")
        self._head.write(data)
        self._head_size += len(data)

    def flush(self, sync: bool = False) -> None:
        if self._head is None:
            return
        self._head.flush()
        if sync:
            os.fsync(self._head.fileno())

    def end_offset(self) -> int:
        return self._head_base + self._head_size

    def maybe_rotate(self) -> bool:
        """Seal the head into a chunk once past the size limit; callers
        invoke this at record boundaries so records never span chunks."""
        if self._head is None or self._head_size < self.head_size_limit:
            return False
        self._head.flush()
        os.fsync(self._head.fileno())
        self._head.close()
        chunk_path = f"{self.head_path}.{self._head_base:016d}"
        os.replace(self.head_path, chunk_path)
        self._head_base += self._head_size
        self._head_size = 0
        self._head = open(self.head_path, "ab")
        self._prune()
        return True

    def _prune(self) -> None:
        chunks = self._chunk_paths()
        total = sum(os.path.getsize(p) for _, p in chunks) + self._head_size
        # never prune the newest sealed chunk: its filename anchors the
        # head's logical base across restarts, keeping offsets stable
        # even when the size limit would otherwise clear every chunk
        for _, path in chunks[:-1]:
            if total <= self.total_size_limit:
                break
            size = os.path.getsize(path)
            os.unlink(path)
            total -= size

    # --- reading -------------------------------------------------------------

    def first_offset(self) -> int:
        segs = self.segments()
        return segs[0][0] if segs else 0

    def read_from(self, logical_offset: int) -> bytes:
        """All bytes from logical_offset to the end (across segments).
        Prefer iter_segments_from for large logs — this materializes
        everything at once."""
        return b"".join(
            data for _, data in self.iter_segments_from(logical_offset)
        )

    def iter_segments_from(self, logical_offset: int):
        """Yield (segment_base_of_slice, bytes) per segment from
        logical_offset — peak memory one segment, not the whole log."""
        for base, path in self.segments():
            size = os.path.getsize(path)
            if base + size <= logical_offset:
                continue
            with open(path, "rb") as fh:
                if logical_offset > base:
                    fh.seek(logical_offset - base)
                    yield logical_offset, fh.read()
                else:
                    yield base, fh.read()
            logical_offset = base + size

    def truncate_head_tail(self, keep_bytes: int) -> None:
        """Truncate the HEAD file to keep_bytes (crash-torn-tail repair;
        sealed chunks are immutable)."""
        was_open = self._head is not None
        if was_open:
            self._head.close()
            self._head = None
        with open(self.head_path, "r+b") as fh:
            fh.truncate(keep_bytes)
        self._head_size = keep_bytes
        if was_open:
            self._head = open(self.head_path, "ab")

    def head_size(self) -> int:
        return self._head_size
