"""Consensus round state types (internal/consensus/types/).

RoundStep state enum, HeightVoteSet (one prevote + precommit VoteSet per
round with peer catch-up round limits), and RoundState — the snapshot the
state machine logs and gossips.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Timestamp,
)
from tendermint_tpu.types import BlockID, Block, ValidatorSet
from tendermint_tpu.types.block import GO_ZERO_TIME, Proposal
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.vote_set import VoteSet


class RoundStep(enum.IntEnum):
    """internal/consensus/types/round_state.go:12-24."""

    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


class GotVoteFromUnwantedRoundError(Exception):
    """height_vote_set.go:21-23: peer exceeded its 2 catch-up rounds."""


@dataclass
class RoundVoteSet:
    prevotes: VoteSet
    precommits: VoteSet


class HeightVoteSet:
    """internal/consensus/types/height_vote_set.go:40-220."""

    def __init__(
        self,
        chain_id: str,
        height: int,
        val_set: ValidatorSet,
        extensions_enabled: bool = False,
    ):
        self.chain_id = chain_id
        self.extensions_enabled = extensions_enabled
        self._mtx = threading.Lock()
        self.reset(height, val_set)

    @classmethod
    def extended(
        cls, chain_id: str, height: int, val_set: ValidatorSet
    ) -> "HeightVoteSet":
        return cls(chain_id, height, val_set, extensions_enabled=True)

    def reset(self, height: int, val_set: ValidatorSet) -> None:
        self.height = height
        self.val_set = val_set
        self.round_vote_sets: Dict[int, RoundVoteSet] = {}
        self.peer_catchup_rounds: Dict[str, List[int]] = {}
        self._add_round(0)
        self.round = 0

    def set_round(self, round_: int) -> None:
        """Create vote sets up to round_ (height_vote_set.go:97-113)."""
        with self._mtx:
            new_round = self.round - 1
            if self.round != 0 and round_ < new_round:
                raise ValueError("set_round() must increment the round")
            for r in range(max(new_round, 0), round_ + 1):
                if r in self.round_vote_sets:
                    continue  # already exists because of peer catch-up
                self._add_round(r)
            self.round = round_

    def _add_round(self, round_: int) -> None:
        if round_ in self.round_vote_sets:
            raise ValueError("add_round() for an existing round")
        prevotes = VoteSet(
            self.chain_id, self.height, round_, SIGNED_MSG_TYPE_PREVOTE, self.val_set
        )
        precommits = VoteSet(
            self.chain_id,
            self.height,
            round_,
            SIGNED_MSG_TYPE_PRECOMMIT,
            self.val_set,
            extensions_enabled=self.extensions_enabled,
        )
        self.round_vote_sets[round_] = RoundVoteSet(prevotes, precommits)

    def add_vote(self, vote, peer_id: str = "") -> bool:
        """Duplicate votes return False. peer_id "" means self
        (height_vote_set.go:136-155)."""
        with self._mtx:
            if vote.type not in (SIGNED_MSG_TYPE_PREVOTE, SIGNED_MSG_TYPE_PRECOMMIT):
                return False
            vote_set = self._get_vote_set(vote.round, vote.type)
            if vote_set is None:
                rndz = self.peer_catchup_rounds.get(peer_id, [])
                if len(rndz) < 2:
                    self._add_round(vote.round)
                    vote_set = self._get_vote_set(vote.round, vote.type)
                    self.peer_catchup_rounds[peer_id] = rndz + [vote.round]
                else:
                    raise GotVoteFromUnwantedRoundError(
                        "peer has sent a vote that does not match our round "
                        "for more than one round"
                    )
            return vote_set.add_vote(vote)

    def prevotes(self, round_: int) -> Optional[VoteSet]:
        with self._mtx:
            return self._get_vote_set(round_, SIGNED_MSG_TYPE_PREVOTE)

    def precommits(self, round_: int) -> Optional[VoteSet]:
        with self._mtx:
            return self._get_vote_set(round_, SIGNED_MSG_TYPE_PRECOMMIT)

    def pol_info(self) -> Tuple[int, BlockID]:
        """Last round with +2/3 prevotes for a block; (-1, nil) if none
        (height_vote_set.go:172-184)."""
        with self._mtx:
            for r in range(self.round, -1, -1):
                rvs = self._get_vote_set(r, SIGNED_MSG_TYPE_PREVOTE)
                if rvs is None:
                    continue
                block_id, ok = rvs.two_thirds_majority()
                if ok:
                    return r, block_id
            return -1, BlockID()

    def _get_vote_set(self, round_: int, vote_type: int) -> Optional[VoteSet]:
        rvs = self.round_vote_sets.get(round_)
        if rvs is None:
            return None
        if vote_type == SIGNED_MSG_TYPE_PREVOTE:
            return rvs.prevotes
        if vote_type == SIGNED_MSG_TYPE_PRECOMMIT:
            return rvs.precommits
        raise ValueError(f"unexpected vote type {vote_type}")

    def set_peer_maj23(
        self, round_: int, vote_type: int, peer_id: str, block_id: BlockID
    ) -> None:
        with self._mtx:
            if vote_type not in (SIGNED_MSG_TYPE_PREVOTE, SIGNED_MSG_TYPE_PRECOMMIT):
                raise ValueError(f"setPeerMaj23: invalid vote type {vote_type}")
            vote_set = self._get_vote_set(round_, vote_type)
            if vote_set is None:
                return  # a round we don't know about yet
            vote_set.set_peer_maj23(peer_id, block_id)


@dataclass
class RoundState:
    """internal/consensus/types/round_state.go:65-120: the state machine's
    mutable snapshot, logged to the WAL and gossiped to peers."""

    height: int = 0
    round: int = 0
    step: RoundStep = RoundStep.NEW_HEIGHT
    start_time: Timestamp = GO_ZERO_TIME
    commit_time: Timestamp = GO_ZERO_TIME
    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_receive_time: Timestamp = GO_ZERO_TIME
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[PartSet] = None
    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[PartSet] = None
    valid_round: int = -1
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[PartSet] = None
    votes: Optional[HeightVoteSet] = None
    commit_round: int = -1
    last_commit: Optional[VoteSet] = None
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False

    def height_round_step(self) -> str:
        return f"{self.height}/{self.round}/{int(self.step)}"
