"""Event sinks (indexer/sink.py): null, SQL (psql schema), multi-sink
fan-out, and node config selection. Reference:
internal/state/indexer/sink/{null,psql}, indexer_service.go.
"""

import sqlite3

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.indexer.sink import (
    MultiSink,
    NullEventSink,
    SQLEventSink,
)


def _fres(n_txs=2):
    """A ResponseFinalizeBlock with block events and per-tx events."""
    return abci.ResponseFinalizeBlock(
        events=[
            abci.Event(
                type="block_meta",
                attributes=[abci.EventAttribute(key="round", value="0")],
            )
        ],
        tx_results=[
            abci.ExecTxResult(
                code=0,
                events=[
                    abci.Event(
                        type="transfer",
                        attributes=[
                            abci.EventAttribute(key="amount", value=str(i)),
                            abci.EventAttribute(key="to", value="addr%d" % i),
                        ],
                    )
                ],
            )
            for i in range(n_txs)
        ],
    )


def test_null_sink_discards():
    sink = NullEventSink()
    sink.index_finalized_block(1, [b"tx"], _fres(1))  # no error, no state


def test_sql_sink_psql_schema_roundtrip():
    conn = sqlite3.connect(":memory:")
    sink = SQLEventSink(conn, "sql-chain")
    txs = [b"tx-one=1", b"tx-two=2"]
    sink.index_finalized_block(5, txs, _fres(2))
    sink.index_finalized_block(6, [], _fres(0))

    cur = conn.cursor()
    cur.execute("SELECT height, chain_id FROM blocks ORDER BY height")
    assert cur.fetchall() == [(5, "sql-chain"), (6, "sql-chain")]
    cur.execute('SELECT "index", tx_hash FROM tx_results ORDER BY "index"')
    rows = cur.fetchall()
    assert [r[0] for r in rows] == [0, 1]
    import hashlib

    assert rows[0][1] == hashlib.sha256(txs[0]).hexdigest().upper()
    # the reference's joined views exist and answer queries
    cur.execute("SELECT type, key, value FROM block_events WHERE height = 5")
    assert ("block_meta", "round", "0") in cur.fetchall()
    cur.execute(
        "SELECT type, composite_key, value FROM tx_events "
        'WHERE height = 5 AND "index" = 1'
    )
    got = cur.fetchall()
    assert ("transfer", "transfer.amount", "1") in got
    assert ("transfer", "transfer.to", "addr1") in got


def test_sql_sink_tx_id_null_for_block_events():
    conn = sqlite3.connect(":memory:")
    sink = SQLEventSink(conn, "c")
    sink.index_finalized_block(1, [b"t"], _fres(1))
    cur = conn.cursor()
    cur.execute("SELECT COUNT(*) FROM events WHERE tx_id IS NULL")
    assert cur.fetchone()[0] == 1  # the block event
    cur.execute("SELECT COUNT(*) FROM events WHERE tx_id IS NOT NULL")
    assert cur.fetchone()[0] == 1  # the tx event


def test_multisink_fans_out():
    calls = []

    class Probe(NullEventSink):
        def __init__(self, name):
            self.name = name

        def index_finalized_block(self, height, txs, fres):
            calls.append((self.name, height))

    ms = MultiSink([Probe("a"), Probe("b")])
    ms.index_finalized_block(9, [], _fres(0))
    assert calls == [("a", 9), ("b", 9)]


def test_node_config_selects_sinks(tmp_path):
    """A node with sinks=["null","sql"] runs without a kv indexer and
    records blocks into the SQL schema."""
    import time

    from tests.test_node import fast_genesis, make_node
    from tendermint_tpu.privval import FilePV

    pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"))
    genesis = fast_genesis([pv])
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.node.node import Node, NodeConfig

    home = str(tmp_path / "home")
    import os

    os.makedirs(home, exist_ok=True)
    cfg = NodeConfig(
        home=home,
        chain_id=genesis.chain_id,
        listen_addr="127.0.0.1:0",
        wal_enabled=False,
        moniker="sink-node",
        tx_index_sinks=["null", "sql"],
    )
    node = Node(cfg, genesis, LocalClient(KVStoreApplication()),
                priv_validator=pv)
    assert node.indexer is None  # no kv sink configured
    node.start()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and node.height < 2:
            time.sleep(0.05)
        assert node.height >= 2
    finally:
        node.stop()
    conn = sqlite3.connect(os.path.join(home, "data", "tx_events.sqlite"))
    cur = conn.cursor()
    cur.execute("SELECT COUNT(*) FROM blocks")
    assert cur.fetchone()[0] >= 2
    conn.close()


def test_node_rejects_unknown_sink(tmp_path):
    from tests.test_node import fast_genesis
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.node.node import Node, NodeConfig
    from tendermint_tpu.privval import FilePV

    pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"))
    genesis = fast_genesis([pv])
    cfg = NodeConfig(
        chain_id=genesis.chain_id, listen_addr="127.0.0.1:0",
        wal_enabled=False, tx_index_sinks=["elastic"],
    )
    with pytest.raises(ValueError, match="unknown indexer sink"):
        Node(cfg, genesis, LocalClient(KVStoreApplication()), priv_validator=pv)
