"""Typed node configuration with TOML persistence.

The operator-facing analog of the reference's single Config struct tree
(config/config.go:62-1182) and its TOML template (config/toml.go):
``Config.load``/``save`` round-trip ``<home>/config/config.toml``, and
``to_node_config()`` produces the runtime NodeConfig the node assembly
consumes. Reading uses the stdlib ``tomllib``; writing uses a small
emitter covering the value types the config needs (str/bool/int/float/
str-list).

Sections mirror the reference file: [base] (top-level keys), [p2p],
[rpc], [mempool], [statesync], [privval]. Consensus timeouts are NOT
here — they live on-chain in ConsensusParams (types/params.go:91), which
genesis carries.
"""

from __future__ import annotations

import os
import tomllib
from dataclasses import dataclass, field as dc_field, fields
from typing import List, Optional

from tendermint_tpu.mempool.mempool import MempoolConfig
from tendermint_tpu.node.node import NodeConfig
from tendermint_tpu.statesync.syncer import StateSyncConfig

DEFAULT_CONFIG_DIR = "config"
DEFAULT_DATA_DIR = "data"
DEFAULT_CONFIG_FILE = "config.toml"
DEFAULT_GENESIS_FILE = "genesis.json"
DEFAULT_NODE_KEY_FILE = "node_key.json"
DEFAULT_PRIVVAL_KEY_FILE = "priv_validator_key.json"
DEFAULT_PRIVVAL_STATE_FILE = "priv_validator_state.json"


@dataclass
class BaseConfig:
    """config/config.go BaseConfig (condensed)."""

    moniker: str = "tpu-node"
    log_level: str = "info"  # debug/info/warn/error/none
    # "full" runs the complete node; "seed" runs PEX-only address gossip
    # (node/seed.go; reference config Mode).
    mode: str = "full"
    # ABCI application: "kvstore" (in-process), "persistent_kvstore"
    # (filedb-backed, in-process), or "tcp://host:port" for an
    # out-of-process socket app (config.go ProxyApp).
    proxy_app: str = "kvstore"
    db_backend: str = "filedb"
    blocksync: bool = True
    wal_enabled: bool = True
    # Snapshot cadence of the BUILT-IN kvstore apps (state-sync
    # providers); out-of-process apps configure their own.
    app_snapshot_interval: int = 0
    # Verify-pipeline span tracing (libs/tracing): "" inherits the
    # TENDERMINT_TPU_TRACE env var (default off), "ring" keeps a bounded
    # in-memory ring served at GET /debug/traces, any other value is a
    # Chrome-trace JSON path flushed at process exit.
    trace: str = ""


@dataclass
class P2PConfig:
    """config/config.go P2PConfig (condensed)."""

    laddr: str = "127.0.0.1:26656"
    persistent_peers: List[str] = dc_field(default_factory=list)
    max_connections: int = 16
    send_rate: int = 5120000  # bytes/sec per peer (config.go SendRate)
    recv_rate: int = 5120000
    # Per-peer send-queue discipline: fifo | priority | simple-priority
    # (router.go:216-238 QueueType).
    queue_type: str = "fifo"


@dataclass
class RPCConfig:
    """config/config.go RPCConfig (condensed)."""

    laddr: str = "127.0.0.1:26657"
    # Register unsafe operator routes (config.go Unsafe; routes.go
    # AddUnsafeRoutes): disconnect etc. Off by default.
    unsafe: bool = False


@dataclass
class PrivValidatorConfig:
    """config/config.go PrivValidatorConfig: empty laddr = local FilePV."""

    laddr: str = ""
    connect_timeout: float = 60.0  # wait for the signer to dial in


@dataclass
class ConsensusConfig:
    """config/config.go ConsensusConfig (condensed — timeouts live
    on-chain in ConsensusParams; this holds node-local knobs)."""

    # Refuse to join consensus if our key signed a commit within the
    # last N blocks (config.go:961 DoubleSignCheckHeight; 0 = off).
    double_sign_check_height: int = 0


@dataclass
class OpsConfig:
    """Accelerator operations knobs (no reference analog — the
    reference has no device boundary)."""

    # "host:port" of a verifyd verification daemon: device-worthy
    # signature batches are verified over the wire instead of on a
    # local accelerator. Empty = local verification. The
    # TENDERMINT_TPU_VERIFY_REMOTE env var applies when this is empty.
    verify_remote: str = ""
    # Tenant/chain namespace this node's remote verification traffic
    # rides under (multi-tenant verifyd: per-tenant admission budgets,
    # resident-table quotas, metrics). Empty = the default tenant.
    verify_tenant: str = ""
    # Devices the sharded verify engine may span (parallel/mesh.py).
    # 0 = all available devices; 1 disables sharding. The
    # TENDERMINT_TPU_MESH env var applies when this is 0.
    mesh_devices: int = 0
    # Device-resident precompute table store (ops/resident.py):
    # "auto" (on for tpu/axon backends), "on", or "off". Empty defers
    # to the TENDERMINT_TPU_RESIDENT env var.
    resident_tables: str = ""
    # Shared-memory slab-ring transport to a co-located verifyd
    # (verifyd/shm.py): "auto" (negotiate when server and node share a
    # host), "on", or "off" (pure TCP). Empty defers to the
    # TENDERMINT_TPU_SHM env var.
    verify_shm: str = ""


@dataclass
class IndexerConfig:
    enabled: bool = True
    # Event sinks: kv | null | sql (reference indexer sink list,
    # config.go TxIndexConfig.Indexer; "sql" is the psql schema over
    # sqlite3 — see indexer/sink.py).
    sinks: List[str] = dc_field(default_factory=lambda: ["kv"])


@dataclass
class Config:
    home: str = ""
    base: BaseConfig = dc_field(default_factory=BaseConfig)
    p2p: P2PConfig = dc_field(default_factory=P2PConfig)
    rpc: RPCConfig = dc_field(default_factory=RPCConfig)
    mempool: MempoolConfig = dc_field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = dc_field(default_factory=StateSyncConfig)
    privval: PrivValidatorConfig = dc_field(
        default_factory=PrivValidatorConfig
    )
    consensus: ConsensusConfig = dc_field(default_factory=ConsensusConfig)
    indexer: IndexerConfig = dc_field(default_factory=IndexerConfig)
    ops: OpsConfig = dc_field(default_factory=OpsConfig)

    # --- derived paths ------------------------------------------------------

    def config_dir(self) -> str:
        return os.path.join(self.home, DEFAULT_CONFIG_DIR)

    def data_dir(self) -> str:
        return os.path.join(self.home, DEFAULT_DATA_DIR)

    def config_file(self) -> str:
        return os.path.join(self.config_dir(), DEFAULT_CONFIG_FILE)

    def genesis_file(self) -> str:
        return os.path.join(self.config_dir(), DEFAULT_GENESIS_FILE)

    def node_key_file(self) -> str:
        return os.path.join(self.config_dir(), DEFAULT_NODE_KEY_FILE)

    def privval_key_file(self) -> str:
        return os.path.join(self.config_dir(), DEFAULT_PRIVVAL_KEY_FILE)

    def privval_state_file(self) -> str:
        return os.path.join(self.data_dir(), DEFAULT_PRIVVAL_STATE_FILE)

    # --- conversion ---------------------------------------------------------

    def to_node_config(self, chain_id: str = "") -> NodeConfig:
        return NodeConfig(
            home=self.home,
            chain_id=chain_id,
            listen_addr=self.p2p.laddr,
            persistent_peers=list(self.p2p.persistent_peers),
            mempool=self.mempool,
            blocksync=self.base.blocksync,
            wal_enabled=self.base.wal_enabled,
            max_connections=self.p2p.max_connections,
            moniker=self.base.moniker,
            rpc_laddr=self.rpc.laddr,
            rpc_unsafe=self.rpc.unsafe,
            tx_index=self.indexer.enabled,
            tx_index_sinks=list(self.indexer.sinks),
            db_backend=self.base.db_backend,
            statesync=self.statesync if self.statesync.enabled else None,
            priv_validator_laddr=self.privval.laddr,
            signer_connect_timeout=self.privval.connect_timeout,
            log_level=self.base.log_level,
            p2p_send_rate=self.p2p.send_rate,
            p2p_recv_rate=self.p2p.recv_rate,
            p2p_queue_type=self.p2p.queue_type,
            double_sign_check_height=self.consensus.double_sign_check_height,
            trace=self.base.trace,
            verify_remote=self.ops.verify_remote,
            verify_tenant=self.ops.verify_tenant,
            mesh_devices=self.ops.mesh_devices,
            resident_tables=self.ops.resident_tables,
            verify_shm=self.ops.verify_shm,
        )

    # --- TOML ---------------------------------------------------------------

    _SECTIONS = (
        "base", "p2p", "rpc", "mempool", "statesync", "privval",
        "consensus", "indexer", "ops",
    )

    def to_toml(self) -> str:
        out = [
            "# tendermint_tpu node configuration",
            "# (config/toml.go analog; consensus timeouts live in genesis"
            " consensus_params)",
            "",
        ]
        for section in self._SECTIONS:
            obj = getattr(self, section)
            out.append(f"[{section}]")
            for f in fields(obj):
                out.append(f"{f.name} = {_emit(getattr(obj, f.name))}")
            out.append("")
        return "\n".join(out)

    @classmethod
    def from_toml(cls, text: str, home: str = "") -> "Config":
        doc = tomllib.loads(text)
        cfg = cls(home=home)
        for section in cls._SECTIONS:
            data = doc.get(section)
            if not isinstance(data, dict):
                continue
            obj = getattr(cfg, section)
            for f in fields(obj):
                if f.name in data:
                    value = data[f.name]
                    # bytes fields are emitted as hex (see _emit); key the
                    # reverse conversion on the field's current type, not
                    # its name, so every bytes field round-trips
                    if isinstance(getattr(obj, f.name), bytes) and isinstance(
                        value, str
                    ):
                        value = bytes.fromhex(value)
                    setattr(obj, f.name, value)
        return cfg

    def save(self) -> None:
        os.makedirs(self.config_dir(), exist_ok=True)
        with open(self.config_file(), "w") as fh:
            fh.write(self.to_toml())

    @classmethod
    def load(cls, home: str) -> "Config":
        path = os.path.join(home, DEFAULT_CONFIG_DIR, DEFAULT_CONFIG_FILE)
        with open(path, "rb") as fh:
            text = fh.read().decode()
        return cls.from_toml(text, home=home)


def _emit(value) -> str:
    """Emit one TOML value (the subset our config uses)."""
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, bytes):
        return f'"{value.hex()}"'
    if isinstance(value, str):
        escaped = value.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    if isinstance(value, (list, tuple)):
        return "[" + ", ".join(_emit(v) for v in value) + "]"
    raise TypeError(f"cannot emit TOML for {type(value).__name__}")
