"""BlockStore: blocks, parts, commits per height (internal/store/store.go).

Key layout mirrors the reference's orderedcode scheme (store.go:651-737)
with one prefix byte + big-endian heights so range scans iterate in
height order: block meta, parts, the canonical commit for height H-1,
the locally-seen commit, a hash->height index, and the extended commit
with vote extensions.
"""

from __future__ import annotations

import threading
from typing import Optional

from tendermint_tpu.storage.kv import KVStore, ordered_key, prefix_end
from tendermint_tpu.types.block import Block, BlockID, Commit, ExtendedCommit
from tendermint_tpu.types.block_meta import BlockMeta
from tendermint_tpu.types.part_set import Part, PartSet

PREFIX_BLOCK_META = 0
PREFIX_BLOCK_PART = 1
PREFIX_BLOCK_COMMIT = 2
PREFIX_SEEN_COMMIT = 3
PREFIX_BLOCK_HASH = 4
PREFIX_EXT_COMMIT = 13


def _meta_key(height: int) -> bytes:
    return ordered_key(PREFIX_BLOCK_META, height)


def _part_key(height: int, index: int) -> bytes:
    return ordered_key(PREFIX_BLOCK_PART, height, index)


def _commit_key(height: int) -> bytes:
    return ordered_key(PREFIX_BLOCK_COMMIT, height)


def _seen_commit_key() -> bytes:
    return bytes([PREFIX_SEEN_COMMIT])


def _ext_commit_key(height: int) -> bytes:
    return ordered_key(PREFIX_EXT_COMMIT, height)


def _hash_key(hash_: bytes) -> bytes:
    return bytes([PREFIX_BLOCK_HASH]) + hash_


class BlockStore:
    """internal/store/store.go:34-: base()..height() contiguous blocks."""

    def __init__(self, db: KVStore):
        self._db = db
        self._mtx = threading.RLock()
        self._base = 0
        self._height = 0
        # Recover base/height from a pre-existing db by scanning metas.
        for k, _ in db.iterator(
            ordered_key(PREFIX_BLOCK_META, 0), prefix_end(bytes([PREFIX_BLOCK_META]))
        ):
            h = int.from_bytes(k[1:9], "big")
            if self._base == 0:
                self._base = h
            self._height = max(self._height, h)

    def base(self) -> int:
        with self._mtx:
            return self._base

    def height(self) -> int:
        with self._mtx:
            return self._height

    def size(self) -> int:
        with self._mtx:
            return 0 if self._height == 0 else self._height - self._base + 1

    # --- save ---------------------------------------------------------------

    def save_block(
        self, block: Block, parts: PartSet, seen_commit: Commit
    ) -> None:
        """store.go SaveBlock: meta + every part + last_commit + seen
        commit, in ONE batch — a process kill between two separate
        batch writes let the restart handshake advance state past a
        commit that was never persisted (a torn state
        reconstructLastCommit cannot repair). One batch closes the
        process-kill window; FileDB frames batch records individually,
        so a torn-tail MEDIA crash can still drop the trailing records
        of a batch (power-loss atomicity would need a batch commit
        marker in the storage layer)."""
        if block is None:
            raise ValueError("BlockStore can only save a non-nil block")
        self._save_block_data(
            block, parts,
            extra=[(_seen_commit_key(), seen_commit.to_proto_bytes())],
        )

    def save_block_with_extended_commit(
        self, block: Block, parts: PartSet, seen_extended_commit: ExtendedCommit
    ) -> None:
        """store.go SaveBlockWithExtendedCommit: also persist extensions
        (same single-batch atomicity as save_block)."""
        seen_extended_commit.ensure_extensions()
        self._save_block_data(
            block, parts,
            extra=[
                (
                    _seen_commit_key(),
                    seen_extended_commit.to_commit().to_proto_bytes(),
                ),
                (
                    _ext_commit_key(block.header.height),
                    seen_extended_commit.to_proto_bytes(),
                ),
            ],
        )

    def _save_block_data(self, block: Block, parts: PartSet, extra=()) -> None:
        height = block.header.height
        with self._mtx:
            expected = self._height + 1 if self._height > 0 else height
            if self._height > 0 and height != expected:
                raise ValueError(
                    f"BlockStore can only save contiguous blocks. Wanted "
                    f"{expected}, got {height}"
                )
            if not parts.is_complete():
                raise ValueError("BlockStore can only save complete part sets")
            block_id = BlockID(block.hash(), parts.header())
            meta = BlockMeta.from_block(block, parts.byte_size, block_id)
            batch = self._db.new_batch()
            batch.set(_meta_key(height), meta.to_proto_bytes())
            batch.set(_hash_key(block.hash()), str(height).encode())
            for i in range(parts.total):
                batch.set(_part_key(height, i), parts.get_part(i).to_proto_bytes())
            if block.last_commit is not None:
                batch.set(
                    _commit_key(height - 1), block.last_commit.to_proto_bytes()
                )
            for k, v in extra:
                batch.set(k, v)
            batch.write()
            if self._base == 0:
                self._base = height
            self._height = max(self._height, height)

    # --- load ---------------------------------------------------------------

    def load_block_meta(self, height: int) -> Optional[BlockMeta]:
        raw = self._db.get(_meta_key(height))
        return BlockMeta.from_proto_bytes(raw) if raw is not None else None

    def load_block(self, height: int) -> Optional[Block]:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.part_set_header.total):
            part = self.load_block_part(height, i)
            if part is None:
                return None
            parts.append(part.bytes)
        return Block.from_proto_bytes(b"".join(parts))

    def load_block_by_hash(self, hash_: bytes) -> Optional[Block]:
        raw = self._db.get(_hash_key(hash_))
        if raw is None:
            return None
        return self.load_block(int(raw.decode()))

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        raw = self._db.get(_part_key(height, index))
        return Part.from_proto_bytes(raw) if raw is not None else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        """The canonical commit for `height` (stored with block height+1)."""
        raw = self._db.get(_commit_key(height))
        return Commit.from_proto_bytes(raw) if raw is not None else None

    def save_seen_commit(self, commit: Commit) -> None:
        """Store the commit for the current tip without a block — the
        statesync bootstrap path (store.go SaveSeenCommit), so consensus
        can reconstruct its last commit after the jump."""
        self._db.set(_seen_commit_key(), commit.to_proto_bytes())

    def load_seen_commit(self) -> Optional[Commit]:
        raw = self._db.get(_seen_commit_key())
        return Commit.from_proto_bytes(raw) if raw is not None else None

    def load_block_extended_commit(self, height: int) -> Optional[ExtendedCommit]:
        raw = self._db.get(_ext_commit_key(height))
        return ExtendedCommit.from_proto_bytes(raw) if raw is not None else None

    def delete_latest_block(self) -> None:
        """Remove the highest block (the rollback --hard path; pairs with
        internal/state/rollback.go so consensus re-commits the height)."""
        with self._mtx:
            if self._height == 0:
                raise ValueError("block store is empty")
            h = self._height
            meta = self.load_block_meta(h)
            batch = self._db.new_batch()
            if meta is not None:
                batch.delete(_meta_key(h))
                batch.delete(_hash_key(meta.header.hash()))
                for i in range(meta.block_id.part_set_header.total):
                    batch.delete(_part_key(h, i))
            batch.delete(_ext_commit_key(h))
            # The canonical commit for h-1 (arrived in block h's LastCommit)
            # becomes the seen commit of the new tip so consensus can
            # reconstruct its last commit after a rollback restart.
            prev_commit = self._db.get(_commit_key(h - 1))
            if prev_commit is not None:
                batch.set(_seen_commit_key(), prev_commit)
            batch.write()
            self._height = h - 1
            if self._height < self._base:
                self._base = 0
                self._height = 0

    # --- prune --------------------------------------------------------------

    def prune_blocks(self, retain_height: int) -> int:
        """store.go PruneBlocks: drop [base, retain_height); returns count."""
        with self._mtx:
            if retain_height <= 0:
                raise ValueError("height must be greater than 0")
            if retain_height > self._height:
                raise ValueError(
                    f"cannot prune beyond the latest height {self._height}"
                )
            pruned = 0
            batch = self._db.new_batch()
            for h in range(self._base, retain_height):
                meta = self.load_block_meta(h)
                if meta is None:
                    continue
                batch.delete(_meta_key(h))
                batch.delete(_hash_key(meta.header.hash()))
                batch.delete(_commit_key(h - 1))
                batch.delete(_ext_commit_key(h))
                for i in range(meta.block_id.part_set_header.total):
                    batch.delete(_part_key(h, i))
                pruned += 1
            batch.write()
            self._base = retain_height
            return pruned
