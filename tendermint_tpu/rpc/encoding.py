"""JSON encoding of core types for RPC responses.

Follows the reference's RPC JSON conventions (rpc/coretypes/responses.go
with proto-JSON encodings): hashes hex-encoded, tx/data bytes base64,
timestamps RFC3339, int64 fields as strings (Go's proto-JSON renders
64-bit ints as strings; clients depend on that).
"""

from __future__ import annotations

import base64
from typing import Any, Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.types.block import (
    Block,
    BlockID,
    Commit,
    CommitSig,
    Header,
    Vote,
)
from tendermint_tpu.types.validator import Validator


def hex_bytes(b: bytes) -> str:
    return b.hex().upper()


def b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def unb64(s: str) -> bytes:
    return base64.b64decode(s)


def rfc3339(ts: Timestamp) -> str:
    import datetime

    dt = datetime.datetime.fromtimestamp(ts.seconds, tz=datetime.timezone.utc)
    frac = f".{ts.nanos:09d}".rstrip("0").rstrip(".")
    return dt.strftime("%Y-%m-%dT%H:%M:%S") + frac + "Z"


def parse_rfc3339(s: str) -> Timestamp:
    import datetime

    if s.endswith("Z"):
        s = s[:-1]
    if "." in s:
        main, frac = s.split(".", 1)
        nanos = int(frac.ljust(9, "0")[:9])
    else:
        main, nanos = s, 0
    dt = datetime.datetime.strptime(main, "%Y-%m-%dT%H:%M:%S").replace(
        tzinfo=datetime.timezone.utc
    )
    return Timestamp(int(dt.timestamp()), nanos)


def block_id_json(bid: BlockID) -> Dict[str, Any]:
    return {
        "hash": hex_bytes(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": hex_bytes(bid.part_set_header.hash),
        },
    }


def header_json(h: Header) -> Dict[str, Any]:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": rfc3339(h.time),
        "last_block_id": block_id_json(h.last_block_id),
        "last_commit_hash": hex_bytes(h.last_commit_hash),
        "data_hash": hex_bytes(h.data_hash),
        "validators_hash": hex_bytes(h.validators_hash),
        "next_validators_hash": hex_bytes(h.next_validators_hash),
        "consensus_hash": hex_bytes(h.consensus_hash),
        "app_hash": hex_bytes(h.app_hash),
        "last_results_hash": hex_bytes(h.last_results_hash),
        "evidence_hash": hex_bytes(h.evidence_hash),
        "proposer_address": hex_bytes(h.proposer_address),
    }


def commit_sig_json(cs: CommitSig) -> Dict[str, Any]:
    return {
        "block_id_flag": cs.block_id_flag,
        "validator_address": hex_bytes(cs.validator_address),
        "timestamp": rfc3339(cs.timestamp),
        "signature": b64(cs.signature) if cs.signature else None,
    }


def commit_json(c: Commit) -> Dict[str, Any]:
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": block_id_json(c.block_id),
        "signatures": [commit_sig_json(s) for s in c.signatures],
    }


def block_json(b: Block) -> Dict[str, Any]:
    return {
        "header": header_json(b.header),
        "data": {"txs": [b64(tx) for tx in b.data.txs]},
        "evidence": {"evidence": []},
        "last_commit": commit_json(b.last_commit) if b.last_commit else None,
    }


def validator_json(v: Validator) -> Dict[str, Any]:
    return {
        "address": hex_bytes(v.address),
        "pub_key": {
            "type": v.pub_key.type,
            "value": b64(v.pub_key.bytes()),
        },
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


# --- decoders (client side: light provider, statesync state provider) ------


def block_id_from_json(d: Dict[str, Any]) -> BlockID:
    from tendermint_tpu.types.part_set import PartSetHeader

    return BlockID(
        hash=bytes.fromhex(d.get("hash", "")),
        part_set_header=PartSetHeader(
            total=int(d.get("parts", {}).get("total", 0)),
            hash=bytes.fromhex(d.get("parts", {}).get("hash", "")),
        ),
    )


def header_from_json(d: Dict[str, Any]) -> Header:
    from tendermint_tpu.types.block import Consensus

    return Header(
        version=Consensus(
            block=int(d["version"]["block"]), app=int(d["version"]["app"])
        ),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time=parse_rfc3339(d["time"]),
        last_block_id=block_id_from_json(d["last_block_id"]),
        last_commit_hash=bytes.fromhex(d["last_commit_hash"]),
        data_hash=bytes.fromhex(d["data_hash"]),
        validators_hash=bytes.fromhex(d["validators_hash"]),
        next_validators_hash=bytes.fromhex(d["next_validators_hash"]),
        consensus_hash=bytes.fromhex(d["consensus_hash"]),
        app_hash=bytes.fromhex(d["app_hash"]),
        last_results_hash=bytes.fromhex(d["last_results_hash"]),
        evidence_hash=bytes.fromhex(d["evidence_hash"]),
        proposer_address=bytes.fromhex(d["proposer_address"]),
    )


def commit_from_json(d: Dict[str, Any]) -> Commit:
    return Commit(
        height=int(d["height"]),
        round=int(d["round"]),
        block_id=block_id_from_json(d["block_id"]),
        signatures=[
            CommitSig(
                block_id_flag=int(s["block_id_flag"]),
                validator_address=bytes.fromhex(s["validator_address"]),
                timestamp=parse_rfc3339(s["timestamp"]),
                signature=base64.b64decode(s["signature"]) if s.get("signature") else b"",
            )
            for s in d["signatures"]
        ],
    )


def validator_from_json(d: Dict[str, Any]) -> Validator:
    from tendermint_tpu.crypto.keys import pubkey_from_type_and_bytes

    pub = pubkey_from_type_and_bytes(
        d["pub_key"]["type"], base64.b64decode(d["pub_key"]["value"])
    )
    return Validator(
        address=bytes.fromhex(d["address"]),
        pub_key=pub,
        voting_power=int(d["voting_power"]),
        proposer_priority=int(d.get("proposer_priority", 0)),
    )


def event_json(e: abci.Event) -> Dict[str, Any]:
    return {
        "type": e.type,
        "attributes": [
            {"key": a.key, "value": a.value, "index": a.index} for a in e.attributes
        ],
    }


def exec_tx_result_json(r: abci.ExecTxResult) -> Dict[str, Any]:
    return {
        "code": r.code,
        "data": b64(r.data),
        "log": r.log,
        "info": r.info,
        "gas_wanted": str(r.gas_wanted),
        "gas_used": str(r.gas_used),
        "events": [event_json(e) for e in (r.events or [])],
        "codespace": r.codespace,
    }
