"""Consensus reactor: targeted per-peer gossip of proposals, parts, votes.

Mirrors internal/consensus/reactor.go's channel layout — State(0x20),
Data(0x21), Vote(0x22), VoteSetBits(0x23) (reactor.go:78-81) — and its
gossip discipline: one gossip routine per peer consults that peer's
PeerState and sends only what the peer is missing (gossipDataRoutine
reactor.go:501, gossipVotesRoutine reactor.go:736), with block-part +
commit catch-up for peers on older heights (gossipDataForCatchup
reactor.go:437). Peers announce state via NewRoundStep, HasVote, and
periodic VoteSetBits; everything a peer sends also updates its
PeerState, so re-sends converge to zero once a peer is caught up.

Wire format per message: 1 tag byte + payload (struct-packed fields,
proto payloads for types).
"""

from __future__ import annotations

import hashlib
import queue
import struct
import threading
import time
from typing import Dict, Optional

from tendermint_tpu.consensus.peer_state import PeerState
from tendermint_tpu.consensus.state import Broadcaster, ConsensusState
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.p2p.router import Channel, Envelope, Router
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
)
from tendermint_tpu.types.block import Proposal, Vote
from tendermint_tpu.types.part_set import Part

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

TAG_NEW_ROUND_STEP = 1
TAG_PROPOSAL = 2
TAG_BLOCK_PART = 3
TAG_VOTE = 4
TAG_HAS_VOTE = 5
TAG_VOTE_SET_BITS = 6

# How long gossip routines sleep when a peer needs nothing
# (peerGossipSleepDuration reactor.go:119 is 100ms; smaller here because
# test networks run sub-second rounds).
GOSSIP_SLEEP = 0.02
# Votes sent per gossip iteration when a peer is behind on votes.
VOTES_PER_ITER = 8
# Interval between VoteSetBits announcements of our own vote bitmaps.
BITS_INTERVAL = 0.5
# Upper bound on wire-supplied validator indices / bit-array sizes; a
# peer claiming more validators than this is lying (the reference bounds
# set size via MaxTotalVotingPower, validator_set.go:18-25).
MAX_WIRE_VALIDATORS = 65536


def encode_new_round_step(
    height: int, round_: int, step: int, last_commit_round: int
) -> bytes:
    return bytes([TAG_NEW_ROUND_STEP]) + struct.pack(
        ">qiii", height, round_, step, last_commit_round
    )


def encode_proposal(p: Proposal) -> bytes:
    return bytes([TAG_PROPOSAL]) + p.to_proto_bytes()


def encode_block_part(height: int, round_: int, part: Part) -> bytes:
    return (
        bytes([TAG_BLOCK_PART])
        + struct.pack(">qi", height, round_)
        + part.to_proto_bytes()
    )


def encode_vote(v: Vote) -> bytes:
    return bytes([TAG_VOTE]) + v.to_proto_bytes()


def encode_has_vote(height: int, round_: int, type_: int, index: int) -> bytes:
    return bytes([TAG_HAS_VOTE]) + struct.pack(">qibi", height, round_, type_, index)


def encode_vote_set_bits(
    height: int, round_: int, type_: int, bits: BitArray
) -> bytes:
    return (
        bytes([TAG_VOTE_SET_BITS])
        + struct.pack(">qibi", height, round_, type_, bits.size())
        + bytes(bits._elems)
    )


def decode_vote_set_bits(payload: bytes):
    """Returns (height, round, type, bits) or None for malformed/hostile
    input (oversized nbits would allocate unboundedly; a short payload
    would leave the BitArray's backing storage inconsistent)."""
    height, round_, type_, nbits = struct.unpack_from(">qibi", payload)
    if nbits < 0 or nbits > MAX_WIRE_VALIDATORS:
        return None
    ba = BitArray(nbits)
    body = payload[struct.calcsize(">qibi") :]
    if len(body) != len(ba._elems):
        return None
    ba._elems[:] = body
    return height, round_, type_, ba


class VotePreverifier:
    """Scheduler-batched signature pre-verification for the vote channel.

    Peer votes arrive on the reactor's vote-channel thread while the
    single-threaded state loop consumes them one at a time; verifying
    inline there serializes every signature onto the host. This stage
    instead submits each vote's signature(s) to the shared
    accumulate-with-deadline scheduler (crypto/scheduler.py -> device
    batch verify) and forwards the vote to the state machine once its
    batch flushed, marked pre-verified so VoteSet.add_vote (and the
    extension check in addVote) skip the redundant inline verify.
    Reference seam: types/vote_set.go:211-222, types/validation.go:12-16.

    Strictly an optimization, never a gate: a vote whose validator can't
    be resolved (height transition race, catch-up vote), whose key type
    isn't batchable, or whose batch verdict is negative is forwarded
    UNMARKED and re-verified inline by the state loop — fail-open, so a
    racy validator-set read can never drop a valid vote. The single
    forwarder thread preserves order among batched votes (passthrough
    votes may overtake queued ones; consensus tolerates reordering).
    """

    QUEUE_MAX = 4096
    # Per-vote verdict deadline, anchored at ENQUEUE time: when a flush
    # wedges (device hang), every queued vote fails open ~together after
    # one deadline, instead of serializing a full wait per vote.
    WAIT_DEADLINE = 5.0

    def __init__(self, cs: ConsensusState):
        self.cs = cs
        self._q: "queue.Queue" = queue.Queue(maxsize=self.QUEUE_MAX)
        self._stop_flag = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Until the batch engine is warm (first kernel compile can take
        # tens of seconds), votes pass straight through to the inline
        # path — pre-verification is an optimization, and a cold cache
        # must never add latency to consensus.
        self._warm = threading.Event()
        self._rewarming = threading.Lock()
        self._deadline_misses = 0  # consecutive; device likely wedged
        # observability (tested): how many votes went through the batch
        # path vs fell through to inline.
        self.batched = 0
        self.passthrough = 0

    def start(self) -> None:
        self._stop_flag.clear()
        self._thread = threading.Thread(
            target=self._forward_loop, name="vote-preverify", daemon=True
        )
        self._thread.start()
        threading.Thread(
            target=self._warmup, name="vote-preverify-warmup", daemon=True
        ).start()

    def _warmup(self) -> None:
        """Compile/warm the batch engine off the hot path; flip _warm
        only once a known-good verify round-trips. Also the re-warm
        probe after a cold flip: only one attempt runs at a time.

        The probe must take the same path a real flood takes: the
        scheduler's flush routes small batches (< DEVICE_THRESHOLD) to
        the host, so a single-entry probe would "warm" without ever
        compiling the device kernel. Probe at the threshold size so the
        device kernel is genuinely compiled before _warm flips."""
        from tendermint_tpu.crypto.batch import DEVICE_THRESHOLD, get_shared_scheduler
        from tendermint_tpu.ops.ed25519_batch import _PAD_MSG, _PAD_PK, _PAD_SIG

        if not self._rewarming.acquire(blocking=False):
            return
        try:
            sched = get_shared_scheduler()
            handles = [
                sched.submit(_PAD_PK, _PAD_MSG, _PAD_SIG)
                for _ in range(DEVICE_THRESHOLD)
            ]
            if all(sched.wait(h, timeout=120.0) for h in handles):
                self._deadline_misses = 0
                self._warm.set()
        except Exception:
            pass  # engine unusable: stay cold, inline path serves forever
        finally:
            self._rewarming.release()

    # Consecutive verdict-deadline misses before the preverifier goes
    # cold again (stops feeding a wedged device so the scheduler's
    # pending list cannot grow without bound) and re-probes.
    MISS_LIMIT = 4

    def stop(self) -> None:
        self._stop_flag.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        # Discard stragglers: the state loop is already stopped at node
        # shutdown (its queue may be full — forwarding would block
        # forever), and undelivered votes are simply re-gossiped.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def _resolve_pub_key(self, vote: Vote):
        """Expected signer for this vote, or None when not resolvable
        without the state lock's guarantees (then the state loop's
        inline verify — which holds the lock — decides)."""
        rs = self.cs.rs
        if vote.height != rs.height or rs.validators is None:
            return None
        val = rs.validators.get_by_index(vote.validator_index)
        if val is None or val.pub_key.address() != vote.validator_address:
            return None
        return val.pub_key

    def submit(self, vote: Vote, peer_id: str) -> None:
        from tendermint_tpu.crypto.batch import get_shared_scheduler
        from tendermint_tpu.crypto.keys import ED25519_KEY_TYPE

        pub_key = self._resolve_pub_key(vote)
        if (
            not self._warm.is_set()
            or pub_key is None
            or pub_key.type != ED25519_KEY_TYPE
        ):
            self.passthrough += 1
            self.cs.add_vote_from_peer(vote, peer_id)
            return
        chain_id = self.cs.state.chain_id
        if self._q.full():
            # Backpressure: don't pay scheduler submission for a vote
            # that can't be queued (submit() is the sole producer, so
            # this check is race-free).
            self.passthrough += 1
            self.cs.add_vote_from_peer(vote, peer_id)
            return
        try:
            sched = get_shared_scheduler()
            sb = vote.sign_bytes(chain_id)
            # Digest of the EXACT bytes handed to the scheduler: the
            # _pre_verified tag is only honored when verify() recomputes
            # the same digest, so a vote mutated between pre-verify and
            # add_vote can never ride the fast path (types/block.py).
            sb_digest = hashlib.sha256(sb).digest()
            handle = sched.submit(pub_key.bytes(), sb, vote.signature)
            ext_handle = None
            ext_digest = None
            if (
                vote.type == SIGNED_MSG_TYPE_PRECOMMIT
                and not vote.block_id.is_nil()
                and vote.extension_signature
            ):
                esb = vote.extension_sign_bytes(chain_id)
                ext_digest = hashlib.sha256(esb).digest()
                ext_handle = sched.submit(
                    pub_key.bytes(), esb, vote.extension_signature
                )
            self._q.put_nowait(
                (
                    vote,
                    peer_id,
                    pub_key,
                    handle,
                    ext_handle,
                    time.monotonic(),
                    sb_digest,
                    ext_digest,
                )
            )
        except (RuntimeError, queue.Full):
            # scheduler stopped or backpressure: inline path takes over
            self.passthrough += 1
            self.cs.add_vote_from_peer(vote, peer_id)

    def _forward_loop(self) -> None:
        from tendermint_tpu.crypto.batch import get_shared_scheduler

        while not self._stop_flag.is_set():
            try:
                (
                    vote,
                    peer_id,
                    pub_key,
                    handle,
                    ext_handle,
                    t_enq,
                    sb_digest,
                    ext_digest,
                ) = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            sched = get_shared_scheduler()
            deadline = t_enq + self.WAIT_DEADLINE
            ok = sched.wait(
                handle, timeout=max(0.0, deadline - time.monotonic())
            )
            ext_ok = (
                sched.wait(
                    ext_handle, timeout=max(0.0, deadline - time.monotonic())
                )
                if ext_handle is not None
                else None
            )
            if ok:
                self.batched += 1
                self._deadline_misses = 0
                vote.mark_pre_verified(
                    self.cs.state.chain_id,
                    pub_key.bytes(),
                    extension_too=bool(ext_ok),
                    sign_bytes_digest=sb_digest,
                    extension_digest=ext_digest,
                )
            else:
                self.passthrough += 1
                # Distinguish a verdict (flush ran, signature bad) from a
                # deadline miss (flush never returned — device wedged).
                if not handle.done.is_set():
                    self._deadline_misses += 1
                    if self._deadline_misses >= self.MISS_LIMIT:
                        self._warm.clear()
                        # Tell the shared health machine the device path
                        # wedged (a stall is a failure that never raises)
                        # so other callers also stop feeding it.
                        from tendermint_tpu.ops.device_policy import (
                            DeviceStallError,
                            shared as device_health,
                        )

                        device_health.record_failure(
                            DeviceStallError(
                                "vote pre-verify flush missed its deadline "
                                f"{self.MISS_LIMIT}x in a row"
                            )
                        )
                        threading.Thread(
                            target=self._warmup,
                            name="vote-preverify-rewarm",
                            daemon=True,
                        ).start()
            self.cs.add_vote_from_peer(vote, peer_id)


class ConsensusReactor(Broadcaster):
    def __init__(self, cs: ConsensusState, router: Router):
        self.cs = cs
        self.router = router
        self.state_ch = router.open_channel(STATE_CHANNEL)
        self.data_ch = router.open_channel(DATA_CHANNEL)
        self.vote_ch = router.open_channel(VOTE_CHANNEL)
        self.vote_bits_ch = router.open_channel(VOTE_SET_BITS_CHANNEL)
        cs.broadcaster = self
        self.preverifier = VotePreverifier(cs)
        self._stop_flag = threading.Event()
        self._threads = []
        self._peers: Dict[str, PeerState] = {}
        self._gossip_threads: Dict[str, threading.Thread] = {}
        self._peers_mtx = threading.Lock()

    def start(self) -> None:
        self._stop_flag.clear()
        self.preverifier.start()
        for ch, handler in (
            (self.state_ch, self._handle_state),
            (self.data_ch, self._handle_data),
            (self.vote_ch, self._handle_vote),
            (self.vote_bits_ch, self._handle_vote_bits),
        ):
            t = threading.Thread(
                target=self._recv_loop, args=(ch, handler), daemon=True
            )
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._peer_lifecycle_loop, daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._announce_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop_flag.set()
        # Join the channel handlers FIRST: a vote-channel thread still in
        # _handle_vote must not enqueue into a preverifier being drained.
        for t in self._threads:
            t.join(timeout=2)
        self.preverifier.stop()
        self._threads.clear()
        with self._peers_mtx:
            gossipers = list(self._gossip_threads.values())
            self._gossip_threads.clear()
            self._peers.clear()
        for t in gossipers:
            t.join(timeout=2)

    # --- peer lifecycle -------------------------------------------------------

    def _peer_lifecycle_loop(self) -> None:
        """Track router connections; one gossip routine per live peer
        (the reference subscribes to PeerUpdates, reactor.go:392)."""
        while not self._stop_flag.is_set():
            try:
                connected = set(self.router.connected_peers())
                with self._peers_mtx:
                    for pid in connected:
                        if pid not in self._gossip_threads:
                            ps = self._peers.get(pid) or PeerState(pid)
                            self._peers[pid] = ps
                            t = threading.Thread(
                                target=self._gossip_routine,
                                args=(ps,),
                                daemon=True,
                                name=f"cs-gossip-{pid[:8]}",
                            )
                            self._gossip_threads[pid] = t
                            t.start()
                    for pid in list(self._gossip_threads):
                        if pid not in connected:
                            del self._gossip_threads[pid]
                            self._peers.pop(pid, None)
            except Exception:
                pass
            self._stop_flag.wait(0.1)

    def _peer(self, peer_id: str) -> PeerState:
        with self._peers_mtx:
            ps = self._peers.get(peer_id)
            if ps is None:
                ps = PeerState(peer_id)
                self._peers[peer_id] = ps
            return ps

    # --- outbound (Broadcaster) ----------------------------------------------

    def broadcast_proposal(self, proposal: Proposal) -> None:
        self.data_ch.broadcast(encode_proposal(proposal))

    def broadcast_block_part(self, height: int, round_: int, part: Part) -> None:
        self.data_ch.broadcast(encode_block_part(height, round_, part))

    def broadcast_vote(self, vote: Vote) -> None:
        # The SM announces HasVote separately when the vote lands in a set.
        self.vote_ch.broadcast(encode_vote(vote))

    def broadcast_has_vote(
        self, height: int, round_: int, type_: int, index: int
    ) -> None:
        self.state_ch.broadcast(encode_has_vote(height, round_, type_, index))

    def broadcast_new_round_step(self, rs) -> None:
        lcr = rs.last_commit.round if rs.last_commit is not None else -1
        self.state_ch.broadcast(
            encode_new_round_step(rs.height, rs.round, int(rs.step), lcr)
        )

    # --- periodic announcements ----------------------------------------------

    def _announce_loop(self) -> None:
        """Broadcast NewRoundStep + our vote bitmaps periodically so late
        joiners and message-drop victims re-converge (the role of the
        reference's VoteSetMaj23/VoteSetBits query cycle, reactor.go:808)."""
        while not self._stop_flag.is_set():
            try:
                rs = self.cs.rs
                if rs.votes is not None:
                    self.broadcast_new_round_step(rs)
                    for type_, vs in (
                        (SIGNED_MSG_TYPE_PREVOTE, rs.votes.prevotes(rs.round)),
                        (SIGNED_MSG_TYPE_PRECOMMIT, rs.votes.precommits(rs.round)),
                    ):
                        if vs is not None:
                            self.vote_bits_ch.broadcast(
                                encode_vote_set_bits(
                                    rs.height, rs.round, type_, vs.bit_array()
                                )
                            )
            except Exception:
                pass
            self._stop_flag.wait(BITS_INTERVAL)

    # --- per-peer gossip ------------------------------------------------------

    def _gossip_routine(self, ps: PeerState) -> None:
        """reactor.go gossipDataRoutine+gossipVotesRoutine merged: each
        iteration sends the peer at most one part and a few votes."""
        while not self._stop_flag.is_set():
            with self._peers_mtx:
                if self._gossip_threads.get(ps.peer_id) is not threading.current_thread():
                    return  # unsubscribed
            try:
                sent = self._gossip_once(ps)
            except Exception:
                sent = False
            if not sent:
                self._stop_flag.wait(GOSSIP_SLEEP)

    def _gossip_once(self, ps: PeerState) -> bool:
        rs = self.cs.rs
        p_height, p_round, p_step, p_lcr = ps.snapshot()
        if p_height == 0:
            return False  # no NewRoundStep from the peer yet

        if p_height == rs.height:
            return self._gossip_same_height(ps, rs, p_round)
        if p_height < rs.height:
            return self._gossip_catchup(ps, p_height, p_round, p_lcr)
        return False  # peer ahead: blocksync pulls us forward, not gossip

    def _gossip_same_height(self, ps: PeerState, rs, p_round: int) -> bool:
        sent = False
        # Proposal + parts for the peer's current round (reactor.go:501).
        if p_round == rs.round and rs.proposal is not None and not ps.has_proposal:
            self.data_ch.send(
                Envelope(
                    DATA_CHANNEL,
                    encode_proposal(rs.proposal),
                    to_peer=ps.peer_id,
                )
            )
            ps.set_has_proposal(rs.height, rs.round)
            sent = True
        parts = rs.proposal_block_parts
        if p_round == rs.round and parts is not None:
            ps.init_parts(rs.height, rs.round, parts.header())
            idx = ps.pick_missing_part(parts.parts_bit_array)
            if idx is not None:
                part = parts.get_part(idx)
                if part is not None:
                    self.data_ch.send(
                        Envelope(
                            DATA_CHANNEL,
                            encode_block_part(rs.height, rs.round, part),
                            to_peer=ps.peer_id,
                        )
                    )
                    ps.set_has_part(rs.height, rs.round, idx)
                    sent = True
        # Votes: peer's round first, then our round, then POL round
        # (gossipVotesForHeight reactor.go:640-700).
        if rs.votes is not None:
            rounds = []
            for r in (p_round, rs.round, rs.valid_round):
                if r >= 0 and r not in rounds:
                    rounds.append(r)
            for r in rounds:
                for type_, vs in (
                    (SIGNED_MSG_TYPE_PREVOTE, rs.votes.prevotes(r)),
                    (SIGNED_MSG_TYPE_PRECOMMIT, rs.votes.precommits(r)),
                ):
                    if vs is None:
                        continue
                    if self._send_missing_votes(ps, vs, rs.height, r, type_):
                        sent = True
        return sent

    def _send_missing_votes(self, ps, vote_set, height, round_, type_) -> bool:
        ours = vote_set.bit_array()
        sent = False
        for _ in range(VOTES_PER_ITER):
            idx = ps.pick_missing_vote(height, round_, type_, ours)
            if idx is None:
                break
            vote = vote_set.get_by_index(idx)
            if vote is None:
                break
            self.vote_ch.send(
                Envelope(VOTE_CHANNEL, encode_vote(vote), to_peer=ps.peer_id)
            )
            ps.set_has_vote(height, round_, type_, idx, ours.size())
            sent = True
        return sent

    def _gossip_catchup(self, ps: PeerState, p_height, p_round, p_lcr) -> bool:
        """Peer is on an older height: serve the decided block's parts and
        its commit from the store (gossipDataForCatchup reactor.go:437)."""
        store = self.cs.block_store
        if p_height < store.base():
            return False
        meta = store.load_block_meta(p_height)
        # With vote extensions enabled the peer REQUIRES extensions on
        # every non-nil precommit, so when an extended commit is stored
        # it is the ONLY source served — its round/absence bookkeeping
        # can legitimately differ from the canonical commit (written by
        # the h+1 proposer), and mixing indices between the two would
        # serve wrong-round or unsigned votes that the peer rejects
        # while we mark them sent.
        ext_commit = store.load_block_extended_commit(p_height)
        commit = None
        if ext_commit is None:
            commit = store.load_block_commit(p_height)
            if commit is None:
                # The canonical commit for p_height is only stored once
                # block p_height+1 lands; until then the seen commit
                # covers it (reference serves rs.LastCommit to height-1
                # peers, reactor.go:736).
                seen = store.load_seen_commit()
                if seen is not None and seen.height == p_height:
                    commit = seen
        if meta is None:
            return False
        n_parts = meta.block_id.part_set_header.total
        if ext_commit is not None:
            n_sigs = ext_commit.size()
        else:
            n_sigs = commit.size() if commit is not None else 0
        ps.ensure_catchup(p_height, n_parts, n_sigs)
        sent = False
        # One part per iteration, preferring whatever the peer lacks.
        theirs = ps.parts if ps.parts is not None else BitArray(0)
        for i in range(n_parts):
            if ps.catchup_parts.get_index(i) or theirs.get_index(i):
                continue
            part = store.load_block_part(p_height, i)
            if part is None:
                break
            self.data_ch.send(
                Envelope(
                    DATA_CHANNEL,
                    encode_block_part(p_height, p_round, part),
                    to_peer=ps.peer_id,
                )
            )
            ps.catchup_parts.set_index(i, True)
            sent = True
            break
        # Commit precommits let the lagging peer finish its round
        # (reactor.go:736 LastCommit case). One source drives the whole
        # loop: the extended commit when stored, the canonical/seen
        # commit otherwise.
        if ext_commit is not None or commit is not None:
            budget = VOTES_PER_ITER
            for i in range(n_sigs):
                if budget == 0:
                    break
                if ps.catchup_commit.get_index(i):
                    continue
                if ext_commit is not None:
                    if not ext_commit.extended_signatures[i].commit_sig.signature:
                        ps.catchup_commit.set_index(i, True)
                        continue
                    vote = ext_commit.get_extended_vote(i)
                else:
                    if not commit.signatures[i].signature:
                        ps.catchup_commit.set_index(i, True)
                        continue
                    vote = commit.get_vote(i)
                self.vote_ch.send(
                    Envelope(VOTE_CHANNEL, encode_vote(vote), to_peer=ps.peer_id)
                )
                ps.catchup_commit.set_index(i, True)
                ps.set_has_vote(vote.height, vote.round, vote.type, i, n_sigs)
                sent = True
                budget -= 1
        return sent

    # --- inbound --------------------------------------------------------------

    def _recv_loop(self, ch: Channel, handler) -> None:
        while not self._stop_flag.is_set():
            env = ch.receive(timeout=0.2)
            if env is None:
                continue
            try:
                handler(env)
            except Exception:
                pass  # peer input must not kill the reactor

    def _handle_state(self, env: Envelope) -> None:
        if not env.message:
            return
        tag = env.message[0]
        if tag == TAG_NEW_ROUND_STEP:
            height, round_, step, lcr = struct.unpack_from(">qiii", env.message, 1)
            self._peer(env.from_peer).apply_new_round_step(height, round_, step, lcr)
        elif tag == TAG_HAS_VOTE:
            height, round_, type_, index = struct.unpack_from(">qibi", env.message, 1)
            if 0 <= index < MAX_WIRE_VALIDATORS:
                self._peer(env.from_peer).set_has_vote(height, round_, type_, index)

    def _handle_data(self, env: Envelope) -> None:
        if not env.message:
            return
        tag = env.message[0]
        if tag == TAG_PROPOSAL:
            proposal = Proposal.from_proto_bytes(env.message[1:])
            ps = self._peer(env.from_peer)
            ps.set_has_proposal(proposal.height, proposal.round)
            self.cs.add_proposal_from_peer(proposal, env.from_peer)
        elif tag == TAG_BLOCK_PART:
            height, round_ = struct.unpack_from(">qi", env.message, 1)
            part = Part.from_proto_bytes(env.message[13:])
            self._peer(env.from_peer).set_has_part(height, round_, part.index)
            self.cs.add_block_part_from_peer(height, round_, part, env.from_peer)

    def _handle_vote(self, env: Envelope) -> None:
        if not env.message or env.message[0] != TAG_VOTE:
            return
        vote = Vote.from_proto_bytes(env.message[1:])
        if not (0 <= vote.validator_index < MAX_WIRE_VALIDATORS):
            return
        self._peer(env.from_peer).set_has_vote(
            vote.height, vote.round, vote.type, vote.validator_index
        )
        # Batch the signature check on the device before the state loop
        # sees the vote (fail-open: see VotePreverifier).
        self.preverifier.submit(vote, env.from_peer)

    def _handle_vote_bits(self, env: Envelope) -> None:
        if not env.message or env.message[0] != TAG_VOTE_SET_BITS:
            return
        decoded = decode_vote_set_bits(env.message[1:])
        if decoded is None:
            return
        height, round_, type_, bits = decoded
        self._peer(env.from_peer).apply_vote_set_bits(height, round_, type_, bits)
