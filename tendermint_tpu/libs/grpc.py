"""Minimal gRPC-over-HTTP/2: spec-compliant subset, zero dependencies.

The reference exposes gRPC variants of the ABCI transport
(abci/client/grpc_client.go:184, abci/server/grpc_server.go:83) and the
remote signer (privval/grpc/client.go, privval/grpc/server.go) via the
grpc-go stack. This image has no grpc/protobuf runtime, so this module
implements the slice of HTTP/2 (RFC 9113) + HPACK (RFC 7541) + the gRPC
wire protocol that unary RPC needs:

- connection preface, SETTINGS exchange (INITIAL_WINDOW_SIZE is parsed
  and applied to stream send windows, per RFC 9113 6.9.2), PING
  replies, GOAWAY;
- HEADERS/CONTINUATION with END_HEADERS, DATA with END_STREAM;
- flow control at BOTH levels: connection and per-stream send windows
  are tracked and WINDOW_UPDATE is credited to the stream it names, so
  a real grpc-go peer with default 64KB stream windows is paced
  correctly; the receiver replenishes the connection window after every
  DATA frame and advertises 2^31-1 initial stream windows so a unary
  message never stalls against THIS implementation;
- HPACK: full RFC 7541 static table, dynamic-table inserts and indexed
  lookups on DECODE; the ENCODER emits only "literal without indexing"
  with raw strings — a legal encoding every compliant peer accepts.
  Huffman-coded strings are rejected (this pair never emits them);
- gRPC message framing (1-byte compressed flag + 4-byte BE length),
  ``application/grpc`` content type, ``grpc-status``/``grpc-message``
  trailers, per-call deadlines;
- resource bounds mirroring the socket codec: 64MB max message
  (abci/codec.py MAX_FRAME analog), 1MB max header block, bounded
  in-flight streams per server connection.

Scope: unary calls, one in flight per client connection (the callers —
block executor, mempool, consensus signer — are synchronous, the same
trade the socket transports make). A call that fails before its request
finished reaching the peer is retried once on a fresh connection (safe:
the server dispatches only on END_STREAM); a failure after that is
surfaced, never retried — ABCI calls are not idempotent. Streams,
huffman, and padding generation are deliberately out of scope and
documented here rather than half-built.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tendermint_tpu.libs import log

# --- frame types / flags ----------------------------------------------------

FRAME_DATA = 0x0
FRAME_HEADERS = 0x1
FRAME_PRIORITY = 0x2
FRAME_RST_STREAM = 0x3
FRAME_SETTINGS = 0x4
FRAME_PUSH_PROMISE = 0x5
FRAME_PING = 0x6
FRAME_GOAWAY = 0x7
FRAME_WINDOW_UPDATE = 0x8
FRAME_CONTINUATION = 0x9

FLAG_END_STREAM = 0x1
FLAG_END_HEADERS = 0x4
FLAG_ACK = 0x1
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
MAX_FRAME = 16384
BIG_WINDOW = 2**31 - 1
DEFAULT_WINDOW = 65535
# Same ceiling as the socket transport's codec (abci/codec.py): a peer
# cannot balloon memory with an endless DATA stream.
MAX_MESSAGE = 64 << 20
MAX_HEADER_BLOCK = 1 << 20
MAX_STREAMS_PER_CONN = 64

GRPC_OK = 0
GRPC_UNKNOWN = 2
GRPC_UNIMPLEMENTED = 12
GRPC_INTERNAL = 13


class GrpcError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"grpc-status {status}: {message}")
        self.status = status
        self.message = message


class H2ProtocolError(ConnectionError):
    pass


# --- HPACK (RFC 7541) -------------------------------------------------------

# Appendix A static table, 1-indexed.
_STATIC_TABLE: List[Tuple[str, str]] = [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""), ("access-control-allow-origin", ""),
    ("age", ""), ("allow", ""), ("authorization", ""), ("cache-control", ""),
    ("content-disposition", ""), ("content-encoding", ""),
    ("content-language", ""), ("content-length", ""), ("content-location", ""),
    ("content-range", ""), ("content-type", ""), ("cookie", ""), ("date", ""),
    ("etag", ""), ("expect", ""), ("expires", ""), ("from", ""), ("host", ""),
    ("if-match", ""), ("if-modified-since", ""), ("if-none-match", ""),
    ("if-range", ""), ("if-unmodified-since", ""), ("last-modified", ""),
    ("link", ""), ("location", ""), ("max-forwards", ""),
    ("proxy-authenticate", ""), ("proxy-authorization", ""), ("range", ""),
    ("referer", ""), ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""), ("via", ""),
    ("www-authenticate", ""),
]


def _encode_int(value: int, prefix_bits: int, pattern: int) -> bytes:
    """RFC 7541 5.1 integer with the high bits of the first byte set to
    ``pattern``."""
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([pattern | value])
    out = bytearray([pattern | limit])
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def _decode_int(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise H2ProtocolError("truncated HPACK integer")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return value, pos


def hpack_encode(headers: List[Tuple[str, str]]) -> bytes:
    """Literal-without-indexing, raw (non-huffman) strings only —
    the simplest legal HPACK stream (RFC 7541 6.2.2)."""
    out = bytearray()
    for name, value in headers:
        nb = name.encode()
        vb = value.encode()
        out.append(0x00)  # literal, not indexed, new name
        out += _encode_int(len(nb), 7, 0x00)  # H bit clear: raw
        out += nb
        out += _encode_int(len(vb), 7, 0x00)
        out += vb
    return bytes(out)


class HpackDecoder:
    """Stateful decoder: static table + dynamic table + all literal
    forms. Huffman-coded strings raise (neither of our endpoints emits
    them; a third-party peer that does gets a clean protocol error, not
    silent corruption)."""

    def __init__(self, max_table_size: int = 4096):
        self._dynamic: List[Tuple[str, str]] = []
        self._max_size = max_table_size
        self._size = 0

    def _entry(self, index: int) -> Tuple[str, str]:
        if index == 0:
            raise H2ProtocolError("HPACK index 0")
        if index <= len(_STATIC_TABLE):
            return _STATIC_TABLE[index - 1]
        d = index - len(_STATIC_TABLE) - 1
        if d >= len(self._dynamic):
            raise H2ProtocolError(f"HPACK index {index} out of range")
        return self._dynamic[d]

    def _insert(self, name: str, value: str) -> None:
        self._dynamic.insert(0, (name, value))
        self._size += len(name) + len(value) + 32
        while self._size > self._max_size and self._dynamic:
            n, v = self._dynamic.pop()
            self._size -= len(n) + len(v) + 32

    def _string(self, data: bytes, pos: int) -> Tuple[str, int]:
        huffman = bool(data[pos] & 0x80)
        length, pos = _decode_int(data, pos, 7)
        if pos + length > len(data):
            raise H2ProtocolError("truncated HPACK string")
        raw = data[pos : pos + length]
        if huffman:
            raise H2ProtocolError("huffman-coded HPACK string unsupported")
        return raw.decode("utf-8", "surrogateescape"), pos + length

    def decode(self, data: bytes) -> List[Tuple[str, str]]:
        headers: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed field
                index, pos = _decode_int(data, pos, 7)
                headers.append(self._entry(index))
            elif b & 0x40:  # literal with incremental indexing
                index, pos = _decode_int(data, pos, 6)
                name = self._entry(index)[0] if index else None
                if name is None:
                    name, pos = self._string(data, pos)
                value, pos = self._string(data, pos)
                self._insert(name, value)
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update
                size, pos = _decode_int(data, pos, 5)
                self._max_size = size
                while self._size > self._max_size and self._dynamic:
                    n, v = self._dynamic.pop()
                    self._size -= len(n) + len(v) + 32
            else:  # literal without indexing (0x00) / never indexed (0x10)
                index, pos = _decode_int(data, pos, 4)
                name = self._entry(index)[0] if index else None
                if name is None:
                    name, pos = self._string(data, pos)
                value, pos = self._string(data, pos)
                headers.append((name, value))
        return headers


# --- frame I/O --------------------------------------------------------------


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise H2ProtocolError("connection closed mid-frame")
        buf += chunk
    return bytes(buf)


def read_frame(sock: socket.socket) -> Tuple[int, int, int, bytes]:
    hdr = _read_exact(sock, 9)
    length = int.from_bytes(hdr[:3], "big")
    ftype, flags = hdr[3], hdr[4]
    stream_id = int.from_bytes(hdr[5:9], "big") & 0x7FFFFFFF
    payload = _read_exact(sock, length) if length else b""
    return ftype, flags, stream_id, payload


def write_frame(
    sock: socket.socket, ftype: int, flags: int, stream_id: int, payload: bytes
) -> None:
    sock.sendall(
        len(payload).to_bytes(3, "big")
        + bytes([ftype, flags])
        + (stream_id & 0x7FFFFFFF).to_bytes(4, "big")
        + payload
    )


def _settings_payload() -> bytes:
    return struct.pack(
        "!HIHI",
        SETTINGS_INITIAL_WINDOW_SIZE,
        BIG_WINDOW,
        SETTINGS_MAX_FRAME_SIZE,
        MAX_FRAME,
    )


def grpc_frame(payload: bytes) -> bytes:
    """gRPC length-prefixed message: flag byte 0 (uncompressed) + len."""
    return b"\x00" + len(payload).to_bytes(4, "big") + payload


def grpc_unframe(data: bytes) -> bytes:
    if len(data) < 5:
        raise GrpcError(GRPC_INTERNAL, "short gRPC message")
    if data[0] != 0:
        raise GrpcError(GRPC_UNIMPLEMENTED, "compressed gRPC messages unsupported")
    n = int.from_bytes(data[1:5], "big")
    if len(data) < 5 + n:
        raise GrpcError(GRPC_INTERNAL, "truncated gRPC message")
    return data[5 : 5 + n]


class _ConnState:
    """Shared per-connection bookkeeping: HPACK decoder, send windows
    (connection + per-stream), and the one place connection-level frames
    (SETTINGS/PING/WINDOW_UPDATE/GOAWAY) are serviced — both read loops
    and a blocked sender go through :meth:`pump_once`, so the handling
    cannot diverge between copies."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.decoder = HpackDecoder()
        self.send_window = DEFAULT_WINDOW  # connection-level
        self.peer_initial_window = DEFAULT_WINDOW
        self.stream_send: Dict[int, int] = {}
        self.window_cv = threading.Condition()
        self.wlock = threading.Lock()  # frame-write atomicity
        # Stream-level frames read while waiting for window grants; read
        # loops drain this before touching the socket.
        self.inbox: List[Tuple[int, int, int, bytes]] = []

    def open_stream(self, stream_id: int) -> None:
        with self.window_cv:
            self.stream_send[stream_id] = self.peer_initial_window

    def close_stream(self, stream_id: int) -> None:
        with self.window_cv:
            self.stream_send.pop(stream_id, None)

    def _apply_settings(self, payload: bytes) -> None:
        for off in range(0, len(payload) - 5, 6):
            ident, value = struct.unpack_from("!HI", payload, off)
            if ident == SETTINGS_INITIAL_WINDOW_SIZE:
                # RFC 9113 6.9.2: delta applies to all open streams.
                with self.window_cv:
                    delta = value - self.peer_initial_window
                    self.peer_initial_window = value
                    for sid in self.stream_send:
                        self.stream_send[sid] += delta
                    self.window_cv.notify_all()

    def pump_once(self) -> None:
        """Read ONE frame. Connection-level traffic (settings, pings,
        window grants, goaway) is handled here; stream frames are queued
        to ``inbox`` for the owning read loop."""
        ftype, flags, sid, frame = read_frame(self.sock)
        if ftype == FRAME_WINDOW_UPDATE:
            inc = int.from_bytes(frame, "big") & 0x7FFFFFFF
            with self.window_cv:
                if sid == 0:
                    self.send_window += inc
                elif sid in self.stream_send:
                    self.stream_send[sid] += inc
                self.window_cv.notify_all()
        elif ftype == FRAME_SETTINGS:
            if not flags & FLAG_ACK:
                self._apply_settings(frame)
                with self.wlock:
                    write_frame(self.sock, FRAME_SETTINGS, FLAG_ACK, 0, b"")
        elif ftype == FRAME_PING:
            if not flags & FLAG_ACK:
                with self.wlock:
                    write_frame(self.sock, FRAME_PING, FLAG_ACK, 0, frame)
        elif ftype == FRAME_GOAWAY:
            raise H2ProtocolError("peer sent GOAWAY")
        elif ftype == FRAME_PRIORITY:
            pass
        else:
            if len(self.inbox) > 4 * MAX_STREAMS_PER_CONN:
                raise H2ProtocolError("stream-frame backlog overflow")
            self.inbox.append((ftype, flags, sid, frame))

    def next_stream_frame(self) -> Tuple[int, int, int, bytes]:
        """Next stream-level frame, servicing connection frames inline."""
        while not self.inbox:
            self.pump_once()
        return self.inbox.pop(0)

    def send_data(self, stream_id: int, data: bytes, end_stream: bool) -> None:
        """DATA frames chunked to MAX_FRAME, honoring BOTH send windows.
        The caller's thread owns the socket's read side in this design
        (single in-flight call / per-connection server thread), so a
        starved send services incoming frames itself via pump_once."""
        off = 0
        total = len(data)
        if total == 0:
            with self.wlock:
                write_frame(
                    self.sock, FRAME_DATA,
                    FLAG_END_STREAM if end_stream else 0, stream_id, b"",
                )
            return
        while off < total:
            n = 0
            with self.window_cv:
                stream_w = self.stream_send.get(stream_id, self.peer_initial_window)
                avail = min(self.send_window, stream_w)
                if avail > 0:
                    n = min(MAX_FRAME, total - off, avail)
                    self.send_window -= n
                    if stream_id in self.stream_send:
                        self.stream_send[stream_id] -= n
            if n == 0:
                self.pump_once()  # the grant can only arrive by reading
                continue
            chunk = data[off : off + n]
            off += n
            last = off >= total
            with self.wlock:
                write_frame(
                    self.sock, FRAME_DATA,
                    FLAG_END_STREAM if (end_stream and last) else 0,
                    stream_id, chunk,
                )

    def send_headers(
        self, stream_id: int, headers: List[Tuple[str, str]], end_stream: bool
    ) -> None:
        block = hpack_encode(headers)
        flags = FLAG_END_HEADERS | (FLAG_END_STREAM if end_stream else 0)
        with self.wlock:
            write_frame(self.sock, FRAME_HEADERS, flags, stream_id, block)

    def replenish(self, consumed: int) -> None:
        """Grant the peer back what we just consumed (connection level)."""
        if consumed <= 0:
            return
        with self.wlock:
            write_frame(
                self.sock, FRAME_WINDOW_UPDATE, 0, 0,
                consumed.to_bytes(4, "big"),
            )


def _strip_padding(flags: int, payload: bytes) -> bytes:
    if flags & FLAG_PADDED:
        # RFC 7540 §6.1/§6.2: the Pad Length field must exist and the
        # padding must fit inside the remaining payload. A malformed
        # frame is a connection error, not an IndexError.
        if not payload:
            raise H2ProtocolError("PADDED frame with empty payload")
        pad = payload[0]
        if pad >= len(payload):
            raise H2ProtocolError("padding exceeds frame payload")
        payload = payload[1 : len(payload) - pad]
    return payload


# --- client -----------------------------------------------------------------


class GrpcChannel:
    """Blocking unary-call client channel; one call in flight at a time
    (matches the synchronous socket transports' contract). A connection
    failure before the request finished reaching the peer retries once
    on a fresh connection; later failures surface to the caller."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._addr = (host, port)
        self._timeout = timeout
        self._mtx = threading.Lock()
        self._conn: Optional[_ConnState] = None
        self._next_stream = 1

    def close(self) -> None:
        with self._mtx:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._conn is not None:
            try:
                with self._conn.wlock:
                    write_frame(
                        self._conn.sock, FRAME_GOAWAY, 0, 0, b"\x00" * 8
                    )
                self._conn.sock.close()
            except OSError:
                pass  # best-effort GOAWAY/close on teardown
            self._conn = None

    def _connect_locked(self) -> _ConnState:
        if self._conn is not None:
            return self._conn
        sock = socket.create_connection(self._addr, timeout=self._timeout)
        sock.settimeout(self._timeout)
        sock.sendall(PREFACE)
        write_frame(sock, FRAME_SETTINGS, 0, 0, _settings_payload())
        # open up the connection-level receive window for the peer
        write_frame(
            sock, FRAME_WINDOW_UPDATE, 0, 0,
            (BIG_WINDOW - DEFAULT_WINDOW).to_bytes(4, "big"),
        )
        conn = _ConnState(sock)
        self._conn = conn
        self._next_stream = 1
        return conn

    def unary(
        self,
        path: str,
        payload: bytes,
        timeout: Optional[float] = None,
    ) -> bytes:
        """One gRPC unary call; returns the response message payload or
        raises GrpcError with the peer's grpc-status."""
        with self._mtx:
            for attempt in (0, 1):
                try:
                    return self._unary_locked(path, payload, timeout)
                except _RequestNotSent:
                    self._close_locked()
                    if attempt == 1:
                        raise H2ProtocolError(
                            "connection failed before request delivery (retried)"
                        )
                    continue  # safe: the peer never saw END_STREAM
                except (OSError, H2ProtocolError):
                    self._close_locked()
                    raise

    def _unary_locked(
        self, path: str, payload: bytes, timeout: Optional[float]
    ) -> bytes:
        try:
            conn = self._connect_locked()
        except OSError as e:
            raise _RequestNotSent(str(e)) from e
        conn.sock.settimeout(timeout or self._timeout)
        stream_id = self._next_stream
        self._next_stream += 2
        conn.open_stream(stream_id)
        try:
            try:
                conn.send_headers(
                    stream_id,
                    [
                        (":method", "POST"),
                        (":scheme", "http"),
                        (":path", path),
                        (":authority", "%s:%d" % self._addr),
                        ("content-type", "application/grpc"),
                        ("te", "trailers"),
                    ],
                    end_stream=False,
                )
                conn.send_data(stream_id, grpc_frame(payload), end_stream=True)
            except (OSError, H2ProtocolError) as e:
                # END_STREAM never reached the peer: retryable.
                raise _RequestNotSent(str(e)) from e

            data = bytearray()
            headers: List[Tuple[str, str]] = []
            header_block = bytearray()
            block_end_stream = False
            while True:
                ftype, flags, sid, frame = conn.next_stream_frame()
                if sid != stream_id:
                    continue  # stale frame from an aborted stream
                if ftype == FRAME_RST_STREAM:
                    raise GrpcError(GRPC_INTERNAL, "stream reset by server")
                if ftype in (FRAME_HEADERS, FRAME_CONTINUATION):
                    if ftype == FRAME_HEADERS:
                        frame = _strip_padding(flags, frame)
                        if flags & FLAG_PRIORITY:
                            frame = frame[5:]
                        # END_STREAM rides the HEADERS frame, but the
                        # header block isn't complete (or decodable)
                        # until END_HEADERS — honoring it early would
                        # drop trailers split across CONTINUATION
                        # frames (losing grpc-status).
                        block_end_stream = bool(flags & FLAG_END_STREAM)
                    header_block += frame
                    if len(header_block) > MAX_HEADER_BLOCK:
                        raise H2ProtocolError("header block too large")
                    if flags & FLAG_END_HEADERS:
                        headers += conn.decoder.decode(bytes(header_block))
                        header_block.clear()
                        if block_end_stream:
                            break
                    continue
                if ftype == FRAME_DATA:
                    frame = _strip_padding(flags, frame)
                    data += frame
                    if len(data) > MAX_MESSAGE:
                        raise H2ProtocolError("gRPC message exceeds 64MB cap")
                    conn.replenish(len(frame))
                    if flags & FLAG_END_STREAM:
                        break
        finally:
            conn.close_stream(stream_id)
        hmap = dict(headers)
        status = int(hmap.get("grpc-status", "0") or "0")
        if status != GRPC_OK:
            raise GrpcError(status, hmap.get("grpc-message", ""))
        if hmap.get(":status", "200") != "200":
            raise GrpcError(GRPC_INTERNAL, f"http status {hmap.get(':status')}")
        return grpc_unframe(bytes(data))


class _RequestNotSent(Exception):
    """Connection died before END_STREAM was delivered — safe to retry."""


# --- server -----------------------------------------------------------------


Handler = Callable[[bytes], bytes]


class GrpcServer:
    """Threaded unary gRPC server: one thread per connection, handlers
    dispatched by :path. Handler exceptions become grpc-status INTERNAL;
    unknown paths UNIMPLEMENTED (grpc_server.go:83 shape)."""

    def __init__(self, handlers: Dict[str, Handler], host: str = "127.0.0.1",
                 port: int = 0, logger=None):
        self._handlers = handlers
        self._logger = logger if logger is not None else log.NOP_LOGGER
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # Bind eagerly (SocketServer does the same) so `address` is
        # valid before start() and a busy port fails at construction.
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.listen(16)
        self._lsock: Optional[socket.socket] = s

    @property
    def address(self) -> Tuple[str, int]:
        assert self._lsock is not None
        return self._lsock.getsockname()[:2]

    def start(self) -> None:
        self._stop.clear()
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass  # listener may already be closed; stop() is idempotent
            self._lsock = None
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            lsock = self._lsock
            if lsock is None:
                return
            try:
                conn_sock, _ = lsock.accept()
            except OSError:
                # Transient accept errors (ECONNABORTED: the client tore
                # the connection off mid-handshake) must not kill the
                # accept loop — only a closed listener / stop() ends it.
                if self._stop.is_set() or self._lsock is None:
                    return
                time.sleep(0.02)
                continue
            # prune finished connection threads so the list stays bounded
            self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(
                target=self._serve_conn, args=(conn_sock,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, sock: socket.socket) -> None:
        try:
            # Connections idle forever between calls (a halted chain must
            # not drop its ABCI/signer link); TCP keepalive reaps peers
            # that vanished without FIN.
            sock.settimeout(None)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            if _read_exact(sock, len(PREFACE)) != PREFACE:
                return
            write_frame(sock, FRAME_SETTINGS, 0, 0, _settings_payload())
            write_frame(
                sock, FRAME_WINDOW_UPDATE, 0, 0,
                (BIG_WINDOW - DEFAULT_WINDOW).to_bytes(4, "big"),
            )
            conn = _ConnState(sock)
            # stream_id -> [header_list or None, data bytearray, ended]
            streams: Dict[int, list] = {}
            header_block = bytearray()
            block_stream = 0
            while not self._stop.is_set():
                ftype, flags, sid, frame = conn.next_stream_frame()
                if ftype in (FRAME_HEADERS, FRAME_CONTINUATION):
                    if ftype == FRAME_HEADERS:
                        if block_stream != 0:
                            # RFC 7540 §4.3: a header block must not be
                            # interleaved with frames of any other kind
                            # or stream.
                            raise H2ProtocolError(
                                "HEADERS while a header block is open"
                            )
                        frame = _strip_padding(flags, frame)
                        if flags & FLAG_PRIORITY:
                            frame = frame[5:]
                        block_stream = sid
                        if len(streams) >= MAX_STREAMS_PER_CONN:
                            raise H2ProtocolError("too many in-flight streams")
                        streams[sid] = [None, bytearray(), False]
                        conn.open_stream(sid)
                    else:  # CONTINUATION
                        if block_stream == 0:
                            raise H2ProtocolError(
                                "CONTINUATION without a preceding HEADERS"
                            )
                        if sid != block_stream:
                            raise H2ProtocolError(
                                "CONTINUATION on the wrong stream"
                            )
                    header_block += frame
                    if len(header_block) > MAX_HEADER_BLOCK:
                        raise H2ProtocolError("header block too large")
                    if flags & FLAG_END_HEADERS:
                        # Decode even if the stream was reset meanwhile:
                        # skipping would desync the HPACK dynamic table
                        # for every later stream on this connection.
                        decoded = conn.decoder.decode(bytes(header_block))
                        if block_stream in streams:
                            streams[block_stream][0] = decoded
                        header_block.clear()
                        block_stream = 0
                    if flags & FLAG_END_STREAM and sid in streams:
                        streams[sid][2] = True
                elif ftype == FRAME_DATA and sid in streams:
                    frame = _strip_padding(flags, frame)
                    streams[sid][1] += frame
                    if len(streams[sid][1]) > MAX_MESSAGE:
                        raise H2ProtocolError("gRPC message exceeds 64MB cap")
                    conn.replenish(len(frame))
                    if flags & FLAG_END_STREAM:
                        streams[sid][2] = True
                elif ftype == FRAME_RST_STREAM and sid in streams:
                    del streams[sid]
                    conn.close_stream(sid)
                # dispatch complete streams
                done = [
                    s for s, st in streams.items()
                    if st[2] and st[0] is not None
                ]
                for s in done:
                    hdrs, body, _ = streams.pop(s)
                    try:
                        self._dispatch(conn, s, dict(hdrs), bytes(body))
                    finally:
                        conn.close_stream(s)
        except (H2ProtocolError, OSError, GrpcError) as exc:
            # A misbehaving or vanished peer ends its own connection
            # thread; the server and every other connection keep serving.
            peer = "?"
            try:
                # AF_INET returns a (host, port) tuple; AF_UNIX a path str
                name = sock.getpeername()
                peer = "%s:%s" % name[:2] if isinstance(name, tuple) else str(name)
            except OSError:
                pass  # peer already gone; log with the placeholder
            self._logger.debug(
                "grpc connection closed",
                peer=peer,
                error=type(exc).__name__,
                detail=str(exc),
            )
        finally:
            try:
                sock.close()
            except OSError:
                pass  # best-effort close of an already-dead socket

    def _dispatch(
        self, conn: _ConnState, stream_id: int, headers: Dict[str, str],
        body: bytes,
    ) -> None:
        path = headers.get(":path", "")
        handler = self._handlers.get(path)
        resp_headers = [(":status", "200"), ("content-type", "application/grpc")]
        if handler is None:
            conn.send_headers(stream_id, resp_headers, end_stream=False)
            conn.send_headers(
                stream_id,
                [("grpc-status", str(GRPC_UNIMPLEMENTED)),
                 ("grpc-message", f"unknown method {path}")],
                end_stream=True,
            )
            return
        try:
            result = handler(grpc_unframe(body))
            conn.send_headers(stream_id, resp_headers, end_stream=False)
            conn.send_data(stream_id, grpc_frame(result), end_stream=False)
            conn.send_headers(
                stream_id, [("grpc-status", "0")], end_stream=True
            )
        except GrpcError as e:
            conn.send_headers(stream_id, resp_headers, end_stream=False)
            conn.send_headers(
                stream_id,
                [("grpc-status", str(e.status)), ("grpc-message", e.message)],
                end_stream=True,
            )
        except Exception as e:  # handler bug -> INTERNAL, connection survives
            conn.send_headers(stream_id, resp_headers, end_stream=False)
            conn.send_headers(
                stream_id,
                [("grpc-status", str(GRPC_INTERNAL)),
                 ("grpc-message", f"{type(e).__name__}: {e}")],
                end_stream=True,
            )
