"""Consensus parameters.

Mirrors types/params.go: Block/Evidence/Validator/Version/Synchrony/
Timeout/ABCI parameter groups, defaults, validation, update-from-ABCI,
and the hash (SHA-256 of the HashedParams proto — params.go:385-399).
Durations are float seconds host-side (the reference uses ns).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace
from typing import List, Optional

from tendermint_tpu.crypto.keys import (
    ED25519_KEY_TYPE,
    SECP256K1_KEY_TYPE,
    SR25519_KEY_TYPE,
)
from tendermint_tpu.encoding.proto import encode_varint_field

MAX_BLOCK_SIZE_BYTES = 104857600  # 100MB, types/params.go:24
BLOCK_PART_SIZE_BYTES = 65536  # types/params.go:21
MAX_BLOCK_PARTS_COUNT = (MAX_BLOCK_SIZE_BYTES // BLOCK_PART_SIZE_BYTES) + 1

ABCI_PUBKEY_TYPE_ED25519 = ED25519_KEY_TYPE
ABCI_PUBKEY_TYPE_SECP256K1 = SECP256K1_KEY_TYPE
ABCI_PUBKEY_TYPE_SR25519 = SR25519_KEY_TYPE


@dataclass
class BlockParams:
    max_bytes: int = 22020096  # 21MB
    max_gas: int = -1


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration: float = 48 * 3600.0  # seconds
    max_bytes: int = 1048576


@dataclass
class ValidatorParams:
    pub_key_types: List[str] = field(
        default_factory=lambda: [ABCI_PUBKEY_TYPE_ED25519]
    )


@dataclass
class VersionParams:
    app_version: int = 0


@dataclass
class SynchronyParams:
    """Proposer-based timestamps bounds (types/params.go:81-89)."""

    precision: float = 0.505  # seconds
    message_delay: float = 12.0

    def in_round(self, round_: int) -> "SynchronyParams":
        """Per-round relaxation: message delay grows 10% per round so PBTS
        eventually accepts any proposer timestamp (params.go SynchronyParams)."""
        delay = self.message_delay
        for _ in range(round_):
            delay = delay * 1.1
        return SynchronyParams(self.precision, delay)


@dataclass
class TimeoutParams:
    """On-chain consensus timeouts (types/params.go:91-99)."""

    propose: float = 3.0
    propose_delta: float = 0.5
    vote: float = 1.0
    vote_delta: float = 0.5
    commit: float = 1.0
    bypass_commit_timeout: bool = False

    def propose_timeout(self, round_: int) -> float:
        return self.propose + self.propose_delta * round_

    def vote_timeout(self, round_: int) -> float:
        return self.vote + self.vote_delta * round_


@dataclass
class ABCIParams:
    vote_extensions_enable_height: int = 0

    def vote_extensions_enabled(self, height: int) -> bool:
        if self.vote_extensions_enable_height == 0:
            return False
        return height >= self.vote_extensions_enable_height


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)
    synchrony: SynchronyParams = field(default_factory=SynchronyParams)
    timeout: TimeoutParams = field(default_factory=TimeoutParams)
    abci: ABCIParams = field(default_factory=ABCIParams)

    def hash(self) -> bytes:
        """SHA-256 of HashedParams{block_max_bytes=1, block_max_gas=2}
        (types/params.go:385-399)."""
        payload = encode_varint_field(1, self.block.max_bytes) + encode_varint_field(
            2, self.block.max_gas
        )
        return hashlib.sha256(payload).digest()

    def validate(self) -> None:
        """types/params.go ValidateConsensusParams."""
        if self.block.max_bytes <= 0:
            raise ValueError(f"block.max_bytes must be > 0, got {self.block.max_bytes}")
        if self.block.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError(
                f"block.max_bytes exceeds {MAX_BLOCK_SIZE_BYTES}"
            )
        if self.block.max_gas < -1:
            raise ValueError(f"block.max_gas must be >= -1, got {self.block.max_gas}")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError("evidence.max_age_num_blocks must be > 0")
        if self.evidence.max_age_duration <= 0:
            raise ValueError("evidence.max_age_duration must be > 0")
        if (
            self.evidence.max_bytes > self.block.max_bytes
            or self.evidence.max_bytes < 0
        ):
            raise ValueError("evidence.max_bytes invalid")
        if self.synchrony.precision <= 0 or self.synchrony.message_delay <= 0:
            raise ValueError("synchrony params must be positive")
        for t in (
            self.timeout.propose,
            self.timeout.vote,
            self.timeout.commit,
        ):
            if t <= 0:
                raise ValueError("timeouts must be positive")
        if self.timeout.propose_delta < 0 or self.timeout.vote_delta < 0:
            raise ValueError("timeout deltas must be non-negative")
        if not self.validator.pub_key_types:
            raise ValueError("validator.pub_key_types must not be empty")
        for kt in self.validator.pub_key_types:
            if kt not in (
                ABCI_PUBKEY_TYPE_ED25519,
                ABCI_PUBKEY_TYPE_SECP256K1,
                ABCI_PUBKEY_TYPE_SR25519,
            ):
                raise ValueError(f"unknown pubkey type {kt}")
        if self.abci.vote_extensions_enable_height < 0:
            raise ValueError("abci.vote_extensions_enable_height must be >= 0")

    def update_from(self, updates: Optional["ConsensusParamsUpdate"]) -> "ConsensusParams":
        """Apply a partial ABCI update (params.go UpdateConsensusParams)."""
        if updates is None:
            return self
        out = replace(self)
        if updates.block is not None:
            out.block = updates.block
        if updates.evidence is not None:
            out.evidence = updates.evidence
        if updates.validator is not None:
            out.validator = updates.validator
        if updates.version is not None:
            out.version = updates.version
        if updates.synchrony is not None:
            out.synchrony = updates.synchrony
        if updates.timeout is not None:
            out.timeout = updates.timeout
        if updates.abci is not None:
            out.abci = updates.abci
        return out


@dataclass
class ConsensusParamsUpdate:
    """Partial update as delivered by the ABCI app (all groups optional)."""

    block: Optional[BlockParams] = None
    evidence: Optional[EvidenceParams] = None
    validator: Optional[ValidatorParams] = None
    version: Optional[VersionParams] = None
    synchrony: Optional[SynchronyParams] = None
    timeout: Optional[TimeoutParams] = None
    abci: Optional[ABCIParams] = None


def default_consensus_params() -> ConsensusParams:
    """Fresh defaults (types/params.go DefaultConsensusParams). A function,
    not a shared instance: ConsensusParams is mutable."""
    return ConsensusParams()


# --- proto encoding (tendermint.types.ConsensusParams) ----------------------
#
# Field layout follows proto/tendermint/types/params.proto: block=1,
# evidence=2, validator=3, version=4, synchrony=5, timeout=6, abci=7.
# Durations are google.protobuf.Duration {seconds=1, nanos=2}; host-side
# floats are converted at the boundary.


def _encode_duration(seconds_float: float) -> bytes:
    from tendermint_tpu.encoding.proto import encode_varint_field as evf

    total_ns = round(seconds_float * 1e9)
    secs, nanos = divmod(total_ns, 1_000_000_000)
    return evf(1, secs) + evf(2, nanos)


def _decode_duration(data: bytes) -> float:
    from tendermint_tpu.encoding.proto import Reader

    r = Reader(data)
    secs = nanos = 0
    for f, w in r.fields():
        if f == 1 and w == 0:
            secs = r.read_svarint()
        elif f == 2 and w == 0:
            nanos = r.read_svarint()
        else:
            r.skip(w)
    return secs + nanos / 1e9


def consensus_params_to_proto_bytes(p: "ConsensusParams") -> bytes:
    from tendermint_tpu.encoding.proto import (
        encode_bool_field,
        encode_bytes_field,
        encode_message_field,
        encode_varint_field as evf,
    )

    block = evf(1, p.block.max_bytes) + evf(2, p.block.max_gas)
    evidence = (
        evf(1, p.evidence.max_age_num_blocks)
        + encode_message_field(2, _encode_duration(p.evidence.max_age_duration), always=True)
        + evf(3, p.evidence.max_bytes)
    )
    validator = b"".join(
        encode_bytes_field(1, kt.encode()) for kt in p.validator.pub_key_types
    )
    version = evf(1, p.version.app_version)
    synchrony = encode_message_field(
        1, _encode_duration(p.synchrony.precision)
    ) + encode_message_field(2, _encode_duration(p.synchrony.message_delay))
    timeout = (
        encode_message_field(1, _encode_duration(p.timeout.propose))
        + encode_message_field(2, _encode_duration(p.timeout.propose_delta))
        + encode_message_field(3, _encode_duration(p.timeout.vote))
        + encode_message_field(4, _encode_duration(p.timeout.vote_delta))
        + encode_message_field(5, _encode_duration(p.timeout.commit))
        + encode_bool_field(6, p.timeout.bypass_commit_timeout)
    )
    abci = evf(1, p.abci.vote_extensions_enable_height)
    return (
        encode_message_field(1, block, always=True)
        + encode_message_field(2, evidence, always=True)
        + encode_message_field(3, validator, always=True)
        + encode_message_field(4, version, always=True)
        + encode_message_field(5, synchrony, always=True)
        + encode_message_field(6, timeout, always=True)
        + encode_message_field(7, abci, always=True)
    )


def consensus_params_from_proto_bytes(data: bytes) -> "ConsensusParams":
    from tendermint_tpu.encoding.proto import Reader

    p = ConsensusParams()
    r = Reader(data)
    for f, w in r.fields():
        if w != 2:
            r.skip(w)
            continue
        payload = r.read_bytes()
        pr = Reader(payload)
        if f == 1:
            max_bytes = max_gas = 0
            for pf, pw in pr.fields():
                if pf == 1 and pw == 0:
                    max_bytes = pr.read_svarint()
                elif pf == 2 and pw == 0:
                    max_gas = pr.read_svarint()
                else:
                    pr.skip(pw)
            p.block = BlockParams(max_bytes, max_gas)
        elif f == 2:
            blocks = 0
            dur = 0.0
            mb = 0
            for pf, pw in pr.fields():
                if pf == 1 and pw == 0:
                    blocks = pr.read_svarint()
                elif pf == 2 and pw == 2:
                    dur = _decode_duration(pr.read_bytes())
                elif pf == 3 and pw == 0:
                    mb = pr.read_svarint()
                else:
                    pr.skip(pw)
            p.evidence = EvidenceParams(blocks, dur, mb)
        elif f == 3:
            kts = []
            for pf, pw in pr.fields():
                if pf == 1 and pw == 2:
                    kts.append(pr.read_bytes().decode())
                else:
                    pr.skip(pw)
            p.validator = ValidatorParams(kts)
        elif f == 4:
            app_version = 0
            for pf, pw in pr.fields():
                if pf == 1 and pw == 0:
                    app_version = pr.read_varint()
                else:
                    pr.skip(pw)
            p.version = VersionParams(app_version)
        elif f == 5:
            precision = message_delay = 0.0
            for pf, pw in pr.fields():
                if pf == 1 and pw == 2:
                    precision = _decode_duration(pr.read_bytes())
                elif pf == 2 and pw == 2:
                    message_delay = _decode_duration(pr.read_bytes())
                else:
                    pr.skip(pw)
            p.synchrony = SynchronyParams(precision, message_delay)
        elif f == 6:
            vals = {}
            bypass = False
            for pf, pw in pr.fields():
                if pf in (1, 2, 3, 4, 5) and pw == 2:
                    vals[pf] = _decode_duration(pr.read_bytes())
                elif pf == 6 and pw == 0:
                    bypass = bool(pr.read_varint())
                else:
                    pr.skip(pw)
            p.timeout = TimeoutParams(
                propose=vals.get(1, 0.0),
                propose_delta=vals.get(2, 0.0),
                vote=vals.get(3, 0.0),
                vote_delta=vals.get(4, 0.0),
                commit=vals.get(5, 0.0),
                bypass_commit_timeout=bypass,
            )
        elif f == 7:
            h = 0
            for pf, pw in pr.fields():
                if pf == 1 and pw == 0:
                    h = pr.read_svarint()
                else:
                    pr.skip(pw)
            p.abci = ABCIParams(h)
    return p
