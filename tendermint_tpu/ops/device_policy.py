"""Shared device-fallback policy for the batch verification kernels.

One process-wide answer to "is the accelerator usable?": a failure to
initialize any jax backend is permanent for the process; transient
errors (an OOM, a flaky launch) retry a few times before the fallback
goes sticky. Both signature engines (ops/ed25519_batch.py,
ops/sr25519_batch.py) consult the SAME instance, so a backend declared
broken by one path is immediately broken for the other — no second
burn-in of failed launches.
"""

from __future__ import annotations

import threading


class DevicePolicy:
    FAILURE_LIMIT = 3

    def __init__(self):
        self._mtx = threading.Lock()
        self.broken = False
        self.failures = 0

    @staticmethod
    def _is_backend_init_failure(exc: Exception) -> bool:
        """No jax backend could come up at all (e.g. the axon plugin not
        registering in a subprocess) — permanent for this process."""
        text = str(exc).lower()
        return isinstance(exc, RuntimeError) and (
            "backend" in text or "platform" in text
        )

    def record_failure(self, exc: Exception) -> bool:
        """Returns True when the device path is now (or already) sticky-
        broken."""
        with self._mtx:
            self.failures += 1
            if (
                self._is_backend_init_failure(exc)
                or self.failures >= self.FAILURE_LIMIT
            ):
                self.broken = True
            return self.broken

    def record_success(self) -> None:
        with self._mtx:
            self.failures = 0


# The process-wide instance both engines share.
shared = DevicePolicy()
