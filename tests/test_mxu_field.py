"""The int8-MXU field-multiply formulation vs the f32 engine and the
host oracle (ops/field_mxu.py).

Pins, on the CPU backend:

- value parity of fe_mul_mxu with field32.fe_mul and with Python-int
  arithmetic across random loose inputs and boundary values;
- the output invariant (limbs bounded like fe_carry's contract) so the
  mxu product composes with every downstream field op;
- the lowering contract the TPU path depends on: the hot contraction is
  a single dot_general with int8 operands and an int32 accumulator
  (the quantized-matmul pattern XLA maps to the MXU int8 systolic
  path);
- end-to-end signature verification parity through verify_kernel with
  the trace-time switch engaged, including the compiled-cache keying.

Reference semantics unchanged: crypto/ed25519/ed25519.go:198-233.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.ops import ed25519_batch as eb, field32 as field
from tendermint_tpu.ops.field_mxu import fe_mul_mxu


def _rand_loose(rng, n, hi=451):
    return jnp.asarray(rng.integers(0, hi, (field.NLIMBS, n)).astype(np.float32))


def test_mxu_mul_matches_vpu_and_oracle():
    rng = np.random.default_rng(7)
    a = _rand_loose(rng, 128)
    b = _rand_loose(rng, 128)
    vpu = np.asarray(field.fe_mul(a, b))
    mxu = np.asarray(fe_mul_mxu(a, b))
    an, bn = np.asarray(a), np.asarray(b)
    for i in range(128):
        want = (
            field.limbs_to_int(an[:, i]) * field.limbs_to_int(bn[:, i])
        ) % field.P
        assert field.limbs_to_int(mxu[:, i]) == want
        assert field.limbs_to_int(vpu[:, i]) == want


def test_mxu_mul_boundary_values():
    # All-zero, all-max-loose (450), p-1, and 2^256-ish wrap values.
    vals = [
        [0] * 32,
        [450] * 32,
        field.int_to_limbs(field.P - 1),
        field.int_to_limbs(2**255 - 20),
        [255] * 32,
    ]
    a = jnp.asarray(np.array(vals, dtype=np.float32).T)
    b = jnp.asarray(np.array(vals[::-1], dtype=np.float32).T)
    mxu = np.asarray(fe_mul_mxu(a, b))
    an, bn = np.asarray(a), np.asarray(b)
    for i in range(len(vals)):
        want = (
            field.limbs_to_int(an[:, i]) * field.limbs_to_int(bn[:, i])
        ) % field.P
        assert field.limbs_to_int(mxu[:, i]) == want


def test_mxu_mul_output_invariant():
    """Output limbs must satisfy the loose bound so every field op
    (including a following fe_sub, whose BIAS construction needs
    b <= 654 on limb 0) accepts the result."""
    rng = np.random.default_rng(11)
    out = np.asarray(fe_mul_mxu(_rand_loose(rng, 256), _rand_loose(rng, 256)))
    assert out.min() >= 0
    assert out.max() <= 293  # fe_carry's documented bound


def test_mxu_lowering_is_int8_dot_general():
    rng = np.random.default_rng(3)
    a = _rand_loose(rng, 16)
    b = _rand_loose(rng, 16)
    jaxpr = jax.make_jaxpr(fe_mul_mxu)(a, b)
    dots = [e for e in jaxpr.eqns if e.primitive.name == "dot_general"]
    assert len(dots) == 1, "exactly one hot contraction expected"
    (eqn,) = dots
    assert all(v.aval.dtype == jnp.int8 for v in eqn.invars)
    assert eqn.params["preferred_element_type"] == jnp.int32
    assert eqn.outvars[0].aval.dtype == jnp.int32
    # batched over lanes, contracting the full 64-digit axis
    (contract, batch) = eqn.params["dimension_numbers"]
    assert contract == (((1,), (0,)))
    assert batch == (((2,), (1,)))


def test_mxu_switch_roundtrip():
    assert field.get_mul_impl() == "vpu"
    field.set_mul_impl("mxu")
    assert field.get_mul_impl() == "mxu"
    field.set_mul_impl("vpu")
    with pytest.raises(ValueError):
        field.set_mul_impl("gpu")


@pytest.fixture()
def batch12():
    pks, msgs, sigs = [], [], []
    for i in range(12):
        priv, pub = ref.keypair_from_seed(bytes([i + 101]) * 32)
        msg = b"mxu vote %d" % i
        pks.append(pub)
        msgs.append(msg)
        sigs.append(ref.sign(priv, msg))
    return pks, msgs, sigs


def test_mxu_verify_kernel_end_to_end(batch12):
    pks, msgs, sigs = batch12
    # Tamper lanes 2 (signature bit) and 9 (message).
    sigs = list(sigs)
    msgs = list(msgs)
    sigs[2] = sigs[2][:33] + bytes([sigs[2][33] ^ 1]) + sigs[2][34:]
    msgs[9] = b"a different message"
    inputs, host_ok = eb.prepare_batch(pks, msgs, sigs, pad_to=64)
    args = tuple(jnp.asarray(inputs[k]) for k in ("pk", "r", "s", "k"))
    got_vpu = np.asarray(eb._compiled_kernel(64, None, "vpu")(*args))[:12]
    got_mxu = np.asarray(eb._compiled_kernel(64, None, "mxu")(*args))[:12]
    want = [ref.verify_zip215(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    assert list(np.logical_and(got_mxu, host_ok[:12])) == want
    assert list(got_mxu) == list(got_vpu)


def test_mxu_active_impl_env(monkeypatch):
    monkeypatch.setenv(eb._IMPL_ENV, "mxu")
    assert eb.active_impl() == "mxu"
    monkeypatch.setenv(eb._IMPL_ENV, "auto")
    assert eb.active_impl() in ("xla", "pallas")
