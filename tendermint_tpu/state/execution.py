"""BlockExecutor: the consensus <-> ABCI <-> storage bridge.

Mirrors internal/state/execution.go:53-420: CreateProposalBlock (reap
mempool + evidence, ABCI PrepareProposal), ProcessProposal, ValidateBlock
(header/state linkage + LastCommit batch verification on the device path),
ApplyBlock (FinalizeBlock -> state.Update -> Commit -> save), ExtendVote /
VerifyVoteExtension.
"""

from __future__ import annotations

import time as _time
from typing import Callable, List, Optional, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import AbciClient
from tendermint_tpu.crypto import merkle
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.storage.blockstore import BlockStore
from tendermint_tpu.types import Vote
from tendermint_tpu.types.block import (
    BLOCK_ID_FLAG_ABSENT,
    Block,
    BlockID,
    Commit,
    ExtendedCommit,
    Header,
    make_block,
)
from tendermint_tpu.types.evidence import Evidence
from tendermint_tpu.types.validator import Validator


class InvalidBlockError(ValueError):
    pass


class Mempool:
    """Minimal mempool contract the executor needs
    (internal/mempool/mempool.go Mempool interface subset)."""

    def lock(self) -> None: ...

    def unlock(self) -> None: ...

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        return []

    def update(
        self,
        height: int,
        txs: List[bytes],
        tx_results: List[abci.ExecTxResult],
        recheck: bool = True,
    ) -> None: ...

    def remove_tx_by_key(self, key: bytes) -> None: ...

    def flush(self) -> None: ...


class EvidencePool:
    """Minimal evidence-pool contract (internal/evidence/pool.go subset)."""

    def pending_evidence(self, max_bytes: int) -> Tuple[List[Evidence], int]:
        return [], 0

    def check_evidence(self, evidence: List[Evidence]) -> None: ...

    def update(self, state: State, evidence: List[Evidence]) -> None: ...


def max_data_bytes(max_bytes: int, evidence_bytes: int, num_validators: int) -> int:
    """types/block.go MaxDataBytes: block budget minus header/commit/evidence
    overhead (approximated with the same worst-case constants)."""
    max_overhead = 1000  # header+encoding slack
    commit_overhead = 110 * num_validators
    return max(0, max_bytes - max_overhead - commit_overhead - evidence_bytes)


class BlockExecutor:
    def __init__(
        self,
        state_store: StateStore,
        app_client: AbciClient,
        block_store: BlockStore,
        mempool: Optional[Mempool] = None,
        evidence_pool: Optional[EvidencePool] = None,
        event_publisher: Optional[Callable] = None,
        now: Optional[Callable[[], Timestamp]] = None,
        metrics=None,
    ):
        from tendermint_tpu.libs.metrics import StateMetrics

        self.metrics = metrics or StateMetrics.nop()
        self.state_store = state_store
        self.app = app_client
        self.block_store = block_store
        # `is not None`, NOT truthiness: an empty TxMempool has len() == 0
        # and would be silently swapped for the no-op default.
        self.mempool = mempool if mempool is not None else Mempool()
        self.evidence_pool = (
            evidence_pool if evidence_pool is not None else EvidencePool()
        )
        self.event_publisher = event_publisher
        self._now = now or (lambda: Timestamp.from_unix_ns(_time.time_ns()))
        self._validate_cache: set = set()

    # --- proposal -----------------------------------------------------------

    def create_proposal_block(
        self,
        height: int,
        state: State,
        last_ext_commit: ExtendedCommit,
        proposer_addr: bytes,
    ) -> Block:
        """execution.go:86-143."""
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence, ev_size = self.evidence_pool.pending_evidence(
            state.consensus_params.evidence.max_bytes
        )
        data_budget = max_data_bytes(max_bytes, ev_size, len(state.validators))
        txs = self.mempool.reap_max_bytes_max_gas(data_budget, max_gas)
        commit = last_ext_commit.to_commit()
        block = self._make_block(state, height, txs, commit, evidence, proposer_addr)
        rpp = self.app.prepare_proposal(
            abci.RequestPrepareProposal(
                max_tx_bytes=data_budget,
                txs=list(block.data.txs),
                local_last_commit=self._build_extended_commit_info(
                    last_ext_commit, state
                ),
                misbehavior=_evidence_to_abci(evidence),
                height=height,
                time=block.header.time,
                next_validators_hash=block.header.next_validators_hash,
                proposer_address=proposer_addr,
            )
        )
        included: List[bytes] = []
        total = 0
        for rec in rpp.tx_records:
            if rec.action == abci.TX_RECORD_REMOVED:
                from tendermint_tpu.types.block import tx_hash

                self.mempool.remove_tx_by_key(tx_hash(rec.tx))
                continue
            if rec.action in (abci.TX_RECORD_UNMODIFIED, abci.TX_RECORD_ADDED):
                total += len(rec.tx)
                if total > data_budget:
                    raise InvalidBlockError(
                        "PrepareProposal returned more tx bytes than the limit"
                    )
                included.append(rec.tx)
        return self._make_block(
            state, height, included, commit, evidence, proposer_addr,
            time=block.header.time,
        )

    def _make_block(
        self,
        state: State,
        height: int,
        txs: List[bytes],
        commit: Commit,
        evidence: List[Evidence],
        proposer_addr: bytes,
        time: Optional[Timestamp] = None,
    ) -> Block:
        """internal/state/state.go:264-285 MakeBlock + Header.Populate."""
        block = make_block(height, txs, commit, evidence)
        h = block.header
        h.version = state.version
        h.chain_id = state.chain_id
        h.time = time if time is not None else self._now()
        h.last_block_id = state.last_block_id
        h.validators_hash = state.validators.hash()
        h.next_validators_hash = state.next_validators.hash()
        h.consensus_hash = state.consensus_params.hash()
        h.app_hash = state.app_hash
        h.last_results_hash = state.last_results_hash
        h.proposer_address = proposer_addr
        return block

    def process_proposal(self, block: Block, state: State) -> bool:
        """execution.go:144-172."""
        resp = self.app.process_proposal(
            abci.RequestProcessProposal(
                hash=block.hash(),
                height=block.header.height,
                time=block.header.time,
                txs=list(block.data.txs),
                proposed_last_commit=self._build_last_commit_info(block, state),
                misbehavior=_evidence_to_abci(block.evidence),
                proposer_address=block.header.proposer_address,
                next_validators_hash=block.header.next_validators_hash,
            )
        )
        return resp.is_accepted()

    # --- validation ---------------------------------------------------------

    def validate_block(self, state: State, block: Block) -> None:
        """execution.go:173-198 + internal/state/validation.go:14-138."""
        hash_ = block.hash()
        if hash_ in self._validate_cache:
            return
        validate_block(state, block)
        self.evidence_pool.check_evidence(block.evidence)
        self._validate_cache.add(hash_)

    # --- apply --------------------------------------------------------------

    def apply_block(self, state: State, block_id: BlockID, block: Block) -> State:
        """execution.go:199-305."""
        try:
            self.validate_block(state, block)
        except ValueError as e:
            raise InvalidBlockError(str(e)) from e
        _t0 = _time.monotonic()
        fres = self.app.finalize_block(
            abci.RequestFinalizeBlock(
                hash=block.hash(),
                height=block.header.height,
                time=block.header.time,
                txs=list(block.data.txs),
                decided_last_commit=self._build_last_commit_info(block, state),
                misbehavior=_evidence_to_abci(block.evidence),
                proposer_address=block.header.proposer_address,
                next_validators_hash=block.header.next_validators_hash,
            )
        )
        # execution.go:222 block-processing latency metric
        self.metrics.block_processing_time.observe(_time.monotonic() - _t0)
        self.state_store.save_finalize_block_response(
            block.header.height, _marshal_finalize_response(fres)
        )
        validator_updates = _validate_validator_updates(
            fres.validator_updates, state.consensus_params
        )
        if validator_updates:
            self.metrics.validator_set_updates.inc()
        if fres.consensus_param_updates is not None:
            self.metrics.consensus_param_updates.inc()
        results_hash = merkle.hash_from_byte_slices(
            [r.deterministic_bytes() for r in fres.tx_results]
        )
        new_state = state.update(
            block_id,
            block.header,
            results_hash,
            fres.consensus_param_updates,
            validator_updates,
        )
        retain_height = self._commit(new_state, block, fres.tx_results)
        self.evidence_pool.update(new_state, block.evidence)
        new_state.app_hash = fres.app_hash
        self.state_store.save(new_state)
        if retain_height > 0:
            try:
                self.block_store.prune_blocks(retain_height)
            except ValueError:
                pass
        self._validate_cache = set()
        if self.event_publisher is not None:
            self.event_publisher(block, block_id, fres, validator_updates)
        return new_state

    def _commit(
        self, state: State, block: Block, tx_results: List[abci.ExecTxResult]
    ) -> int:
        """execution.go:330-380: lock mempool, ABCI Commit, mempool update."""
        self.mempool.lock()
        try:
            res = self.app.commit()
            self.mempool.update(
                block.header.height, list(block.data.txs), tx_results
            )
            return res.retain_height
        finally:
            self.mempool.unlock()

    # --- vote extensions ----------------------------------------------------

    def extend_vote(self, vote: Vote) -> bytes:
        resp = self.app.extend_vote(
            abci.RequestExtendVote(hash=vote.block_id.hash, height=vote.height)
        )
        return resp.vote_extension

    def verify_vote_extension(self, vote: Vote) -> None:
        resp = self.app.verify_vote_extension(
            abci.RequestVerifyVoteExtension(
                hash=vote.block_id.hash,
                validator_address=vote.validator_address,
                height=vote.height,
                vote_extension=vote.extension,
            )
        )
        if not resp.is_accepted():
            raise InvalidBlockError("invalid vote extension")

    # --- commit info builders ------------------------------------------------

    def _build_last_commit_info(self, block: Block, state: State) -> abci.CommitInfo:
        """execution.go:388-427."""
        if block.header.height == state.initial_height:
            return abci.CommitInfo()
        last_val_set = self.state_store.load_validators(block.header.height - 1)
        commit = block.last_commit
        if commit.size() != len(last_val_set):
            raise InvalidBlockError(
                f"commit size ({commit.size()}) doesn't match validator set "
                f"length ({len(last_val_set)}) at height {block.header.height}"
            )
        votes = [
            abci.VoteInfo(
                validator_address=val.address,
                validator_power=val.voting_power,
                signed_last_block=sig.block_id_flag != BLOCK_ID_FLAG_ABSENT,
            )
            for val, sig in zip(last_val_set.validators, commit.signatures)
        ]
        return abci.CommitInfo(round=commit.round, votes=votes)

    def _build_extended_commit_info(
        self, ec: ExtendedCommit, state: State
    ) -> abci.ExtendedCommitInfo:
        """execution.go buildExtendedCommitInfo."""
        if ec.height < state.initial_height:
            return abci.ExtendedCommitInfo()
        val_set = self.state_store.load_validators(ec.height)
        extensions_enabled = state.consensus_params.abci.vote_extensions_enabled(
            ec.height
        )
        votes = []
        for val, esig in zip(val_set.validators, ec.extended_signatures):
            sig = esig.commit_sig
            if extensions_enabled and sig.block_id_flag != BLOCK_ID_FLAG_ABSENT:
                ext, ext_sig = esig.extension, esig.extension_signature
            else:
                ext, ext_sig = b"", b""
            votes.append(
                abci.ExtendedVoteInfo(
                    validator_address=val.address,
                    validator_power=val.voting_power,
                    signed_last_block=sig.block_id_flag != BLOCK_ID_FLAG_ABSENT,
                    vote_extension=ext,
                    extension_signature=ext_sig,
                )
            )
        return abci.ExtendedCommitInfo(round=ec.round, votes=votes)


def validate_block(state: State, block: Block) -> None:
    """internal/state/validation.go:14-138. The LastCommit check routes
    through the batch verifier (device path for >=2 signatures)."""
    block.validate_basic()
    if (
        block.header.version.app != state.version.app
        or block.header.version.block != state.version.block
    ):
        raise ValueError(
            f"wrong Block.Header.Version. Expected {state.version}, got "
            f"{block.header.version}"
        )
    if block.header.chain_id != state.chain_id:
        raise ValueError(
            f"wrong Block.Header.ChainID. Expected {state.chain_id}, got "
            f"{block.header.chain_id}"
        )
    if state.last_block_height == 0 and block.header.height != state.initial_height:
        raise ValueError(
            f"wrong Block.Header.Height. Expected {state.initial_height} for "
            f"initial block, got {block.header.height}"
        )
    if (
        state.last_block_height > 0
        and block.header.height != state.last_block_height + 1
    ):
        raise ValueError(
            f"wrong Block.Header.Height. Expected {state.last_block_height + 1}, "
            f"got {block.header.height}"
        )
    if block.header.last_block_id != state.last_block_id:
        raise ValueError(
            f"wrong Block.Header.LastBlockID. Expected {state.last_block_id}, "
            f"got {block.header.last_block_id}"
        )
    if block.header.app_hash != state.app_hash:
        raise ValueError("wrong Block.Header.AppHash")
    if block.header.consensus_hash != state.consensus_params.hash():
        raise ValueError("wrong Block.Header.ConsensusHash")
    if block.header.last_results_hash != state.last_results_hash:
        raise ValueError("wrong Block.Header.LastResultsHash")
    if block.header.validators_hash != state.validators.hash():
        raise ValueError("wrong Block.Header.ValidatorsHash")
    if block.header.next_validators_hash != state.next_validators.hash():
        raise ValueError("wrong Block.Header.NextValidatorsHash")

    if block.header.height == state.initial_height:
        if block.last_commit.signatures:
            raise ValueError("initial block can't have LastCommit signatures")
    else:
        state.last_validators.verify_commit(
            state.chain_id,
            state.last_block_id,
            block.header.height - 1,
            block.last_commit,
        )

    if not state.validators.has_address(block.header.proposer_address):
        raise ValueError(
            f"block.Header.ProposerAddress {block.header.proposer_address.hex()} "
            "is not a validator"
        )

    if block.header.height > state.initial_height:
        if block.header.time.to_unix_ns() <= state.last_block_time.to_unix_ns():
            raise ValueError(
                f"block time {block.header.time} not greater than last block "
                f"time {state.last_block_time}"
            )
    elif block.header.height == state.initial_height:
        if block.header.time.to_unix_ns() < state.last_block_time.to_unix_ns():
            raise ValueError("block time is before genesis time")
    else:
        raise ValueError(
            f"block height {block.header.height} lower than initial height "
            f"{state.initial_height}"
        )
    ev_bytes = sum(len(ev.bytes()) for ev in block.evidence)
    if ev_bytes > state.consensus_params.evidence.max_bytes:
        raise ValueError("evidence exceeds max bytes")


def _validate_validator_updates(
    updates: List[abci.ValidatorUpdate], params
) -> List[Validator]:
    """execution.go validateValidatorUpdates + PB2TM conversion."""
    out = []
    for vu in updates:
        if vu.power < 0:
            raise ValueError(f"voting power can't be negative: {vu}")
        if vu.power == 0:
            pass  # removal
        if vu.pub_key_type not in params.validator.pub_key_types:
            raise ValueError(
                f"validator {vu} is using pubkey {vu.pub_key_type}, which is "
                "unsupported for consensus"
            )
        out.append(vu.to_validator())
    return out


def _evidence_to_abci(evidence: List[Evidence]) -> List[abci.Misbehavior]:
    out = []
    for ev in evidence:
        for m in ev.abci():
            out.append(
                abci.Misbehavior(
                    type=m["type"],
                    validator_address=m["validator"]["address"],
                    validator_power=m["validator"]["power"],
                    height=m["height"],
                    time=m["time"],
                    total_voting_power=m["total_voting_power"],
                )
            )
    return out


def _events_to_json(events: List[abci.Event]) -> list:
    return [
        {
            "type": e.type,
            "attributes": [
                {"key": a.key, "value": a.value, "index": a.index}
                for a in e.attributes
            ],
        }
        for e in events
    ]


def _events_from_json(data: list) -> List[abci.Event]:
    return [
        abci.Event(
            type=e["type"],
            attributes=[
                abci.EventAttribute(
                    key=a["key"], value=a["value"], index=a.get("index", False)
                )
                for a in e["attributes"]
            ],
        )
        for e in data
    ]


def _unmarshal_finalize_response(raw: bytes) -> abci.ResponseFinalizeBlock:
    """Inverse of _marshal_finalize_response (RPC /block_results and
    index rebuilds read the full persisted response back)."""
    import json

    d = json.loads(raw.decode())
    return abci.ResponseFinalizeBlock(
        app_hash=bytes.fromhex(d["app_hash"]),
        events=_events_from_json(d.get("events", [])),
        tx_results=[
            abci.ExecTxResult(
                code=r["code"],
                data=bytes.fromhex(r["data"]),
                log=r.get("log", ""),
                info=r.get("info", ""),
                gas_wanted=r["gas_wanted"],
                gas_used=r["gas_used"],
                events=_events_from_json(r.get("events", [])),
                codespace=r.get("codespace", ""),
            )
            for r in d["tx_results"]
        ],
        validator_updates=[
            abci.ValidatorUpdate(
                pub_key_type=vu["type"],
                pub_key_bytes=bytes.fromhex(vu["pub_key"]),
                power=vu["power"],
            )
            for vu in d["validator_updates"]
        ],
    )


def _marshal_finalize_response(fres: abci.ResponseFinalizeBlock) -> bytes:
    """Persistence of the FinalizeBlock response for replay, /block_results,
    and index rebuilds (store.go SaveFinalizeBlockResponses). Events and
    logs are retained — ABCI-event consumers (indexer/relayers) depend on
    /block_results carrying them."""
    import json

    return json.dumps(
        {
            "app_hash": fres.app_hash.hex(),
            "events": _events_to_json(fres.events),
            "tx_results": [
                {
                    "code": r.code,
                    "data": r.data.hex(),
                    "log": r.log,
                    "info": r.info,
                    "gas_wanted": r.gas_wanted,
                    "gas_used": r.gas_used,
                    "events": _events_to_json(r.events),
                    "codespace": r.codespace,
                }
                for r in fres.tx_results
            ],
            "validator_updates": [
                {
                    "type": vu.pub_key_type,
                    "pub_key": vu.pub_key_bytes.hex(),
                    "power": vu.power,
                }
                for vu in fres.validator_updates
            ],
        }
    ).encode()
