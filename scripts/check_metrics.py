#!/usr/bin/env python3
"""Static audit: every instrument declared in libs/metrics.py is used.

Walks the metrics-class declarations (``self.X = reg.counter|gauge|
histogram(...)``) with the ast module, then greps the package source for
``.X`` attribute references outside the declaration site. A declared-but-
never-referenced instrument is dead weight on every /metrics scrape and
usually means an instrumentation seam silently fell off in a refactor —
this script makes that a CI failure instead of a dashboard mystery.

Usage: python scripts/check_metrics.py  (exit 0 clean, 1 on dead
instruments; also asserted by tests/test_metrics.py).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "tendermint_tpu")
METRICS_PY = os.path.join(PACKAGE, "libs", "metrics.py")

_FACTORIES = {"counter", "gauge", "histogram"}


def declared_instruments(path: str = METRICS_PY) -> dict:
    """Map attribute name -> (class, lineno) for every ``self.X =
    reg.counter|gauge|histogram(...)`` assignment."""
    with open(path, "r") as fh:
        tree = ast.parse(fh.read(), filename=path)
    out = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                continue
            call = node.value
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in _FACTORIES
            ):
                continue
            out[tgt.attr] = (cls.name, node.lineno)
    return out


def referenced_attrs(root: str = PACKAGE, skip: str = METRICS_PY) -> set:
    """Attribute names referenced as ``.X`` anywhere under ``root``
    except the declaration file itself."""
    refs = set()
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if os.path.abspath(path) == os.path.abspath(skip):
                continue
            with open(path, "r") as fh:
                try:
                    tree = ast.parse(fh.read(), filename=path)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Attribute):
                    refs.add(node.attr)
    return refs


def find_dead_instruments() -> list:
    decls = declared_instruments()
    refs = referenced_attrs()
    return sorted(
        (name, cls, lineno)
        for name, (cls, lineno) in decls.items()
        if name not in refs
    )


def main() -> int:
    decls = declared_instruments()
    dead = find_dead_instruments()
    if dead:
        for name, cls, lineno in dead:
            print(
                f"DEAD INSTRUMENT {cls}.{name} "
                f"(libs/metrics.py:{lineno}): declared but never "
                f"referenced under tendermint_tpu/",
                file=sys.stderr,
            )
        return 1
    print(f"ok: all {len(decls)} declared instruments are referenced")
    return 0


if __name__ == "__main__":
    sys.exit(main())
