"""Light-block providers (light/provider/provider.go).

A provider serves LightBlocks by height and accepts evidence of
misbehavior. MemoryProvider is the in-process test double (the mock/http
split of the reference); an RPC-backed provider plugs in the same ABC.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from tendermint_tpu.types.evidence import Evidence
from tendermint_tpu.types.light import LightBlock


class ProviderError(Exception):
    pass


class LightBlockNotFoundError(ProviderError):
    """provider.ErrLightBlockNotFound."""


class HeightTooHighError(ProviderError):
    """provider.ErrHeightTooHigh: the provider chain is shorter."""


class Provider:
    def chain_id(self) -> str:
        raise NotImplementedError

    def light_block(self, height: int) -> LightBlock:
        """Returns the LightBlock at height (0 = latest); raises
        LightBlockNotFoundError / HeightTooHighError."""
        raise NotImplementedError

    def report_evidence(self, evidence: Evidence) -> None:
        raise NotImplementedError


class MemoryProvider(Provider):
    def __init__(self, chain_id: str, blocks: Optional[List[LightBlock]] = None):
        self._chain_id = chain_id
        self._blocks: Dict[int, LightBlock] = {}
        self.evidence: List[Evidence] = []
        self._lock = threading.Lock()
        for lb in blocks or []:
            self._blocks[lb.height] = lb

    def chain_id(self) -> str:
        return self._chain_id

    def add(self, lb: LightBlock) -> None:
        with self._lock:
            self._blocks[lb.height] = lb

    def latest_height(self) -> int:
        with self._lock:
            return max(self._blocks) if self._blocks else 0

    def light_block(self, height: int) -> LightBlock:
        with self._lock:
            if not self._blocks:
                raise LightBlockNotFoundError(f"no blocks (chain {self._chain_id})")
            latest = max(self._blocks)
            if height == 0:
                return self._blocks[latest]
            if height > latest:
                raise HeightTooHighError(f"height {height} > latest {latest}")
            if height not in self._blocks:
                raise LightBlockNotFoundError(f"no light block at height {height}")
            return self._blocks[height]

    def report_evidence(self, evidence: Evidence) -> None:
        with self._lock:
            self.evidence.append(evidence)
