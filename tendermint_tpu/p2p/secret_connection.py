"""Authenticated encrypted connections (internal/p2p/conn/secret_connection.go).

Same construction as the reference in spirit: X25519 ephemeral ECDH →
HKDF-SHA256 → two ChaCha20-Poly1305 keys (one per direction, chosen by
ephemeral-key sort order), then each side signs the session challenge
with its ed25519 identity key and sends (pubkey, sig) encrypted. Frames
are fixed 1024-byte chunks sealed with a 12-byte LE counter nonce, as in
the reference (secret_connection.go:92-181, deriveSecrets:337). The
transcript hash here is HKDF over sorted ephemerals (the reference uses
a Merlin transcript; byte-level wire compat is not a goal — SURVEY.md §7
step 7 'compatible-in-spirit').
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import struct
from typing import Optional, Tuple

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.serialization import (
    Encoding,
    PublicFormat,
)

from tendermint_tpu.crypto.keys import Ed25519PrivKey, Ed25519PubKey, PubKey

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE
AEAD_TAG_SIZE = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_TAG_SIZE


def _hkdf(secret: bytes, info: bytes, length: int) -> bytes:
    """HKDF-SHA256 (extract with zero salt + expand)."""
    prk = _hmac.new(b"\x00" * 32, secret, hashlib.sha256).digest()
    okm = b""
    t = b""
    i = 1
    while len(okm) < length:
        t = _hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


class SecretConnectionError(Exception):
    pass


class SecretConnection:
    """Wraps a stream-like object (must expose sendall/recv_exact)."""

    def __init__(self, stream, local_priv: Ed25519PrivKey):
        self._stream = stream
        self._local_priv = local_priv
        self.remote_pubkey: Optional[PubKey] = None
        self._send_cipher: Optional[ChaCha20Poly1305] = None
        self._recv_cipher: Optional[ChaCha20Poly1305] = None
        self._send_nonce = 0
        self._recv_nonce = 0
        self._recv_buffer = b""
        self._handshake()

    # --- handshake -----------------------------------------------------------

    def _handshake(self) -> None:
        """secret_connection.go MakeSecretConnection."""
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes(
            Encoding.Raw, PublicFormat.Raw
        )
        # 1. Exchange ephemeral pubkeys in the clear.
        self._stream.sendall(eph_pub)
        remote_eph = self._stream.recv_exact(32)
        # 2. Shared secret + key derivation. Key order by ephemeral sort:
        # the lexicographically lower key is the "first" party.
        shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        lo, hi = sorted([eph_pub, remote_eph])
        material = _hkdf(shared, b"TENDERMINT_TPU_SECRET_CONNECTION" + lo + hi, 96)
        key1, key2, challenge = material[:32], material[32:64], material[64:96]
        if eph_pub == lo:
            send_key, recv_key = key1, key2
        else:
            send_key, recv_key = key2, key1
        self._send_cipher = ChaCha20Poly1305(send_key)
        self._recv_cipher = ChaCha20Poly1305(recv_key)
        # 3. Authenticate: sign the challenge, swap (pubkey, sig) encrypted.
        sig = self._local_priv.sign(challenge)
        auth = self._local_priv.pub_key().bytes() + sig
        self.send(auth)
        remote_auth = self.recv()
        if len(remote_auth) != 32 + 64:
            raise SecretConnectionError("malformed auth message")
        remote_pub = Ed25519PubKey(remote_auth[:32])
        if not remote_pub.verify_signature(challenge, remote_auth[32:]):
            raise SecretConnectionError("challenge verification failed")
        self.remote_pubkey = remote_pub

    # --- framing -------------------------------------------------------------

    def _nonce(self, n: int) -> bytes:
        # 12-byte nonce: 4 zero bytes + u64 LE counter (reference layout).
        return b"\x00" * 4 + struct.pack("<Q", n)

    def send(self, data: bytes) -> None:
        """Chunk into sealed 1024-byte frames (secret_connection.go Write)."""
        view = memoryview(data)
        while True:
            chunk = view[:DATA_MAX_SIZE]
            view = view[DATA_MAX_SIZE:]
            frame = struct.pack("<I", len(chunk)) + bytes(chunk)
            frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
            sealed = self._send_cipher.encrypt(
                self._nonce(self._send_nonce), frame, None
            )
            self._send_nonce += 1
            self._stream.sendall(sealed)
            if not view:
                break

    def recv(self) -> bytes:
        """One logical message may span frames only via caller protocol;
        recv returns one frame's payload."""
        sealed = self._stream.recv_exact(SEALED_FRAME_SIZE)
        try:
            frame = self._recv_cipher.decrypt(
                self._nonce(self._recv_nonce), sealed, None
            )
        except Exception as e:
            raise SecretConnectionError(f"failed to decrypt frame: {e}") from e
        self._recv_nonce += 1
        (length,) = struct.unpack_from("<I", frame)
        if length > DATA_MAX_SIZE:
            raise SecretConnectionError("frame length exceeds max")
        return frame[DATA_LEN_SIZE : DATA_LEN_SIZE + length]

    # --- length-prefixed message helpers ------------------------------------

    def send_msg(self, msg: bytes) -> None:
        """Length-prefixed message of arbitrary size over frames."""
        self.send(struct.pack("<I", len(msg)) + msg)

    def recv_msg(self, max_size: int = 64 * 1024 * 1024) -> bytes:
        while len(self._recv_buffer) < 4:
            self._recv_buffer += self.recv()
        (length,) = struct.unpack_from("<I", self._recv_buffer)
        if length > max_size:
            raise SecretConnectionError(f"message size {length} exceeds max")
        needed = 4 + length
        while len(self._recv_buffer) < needed:
            self._recv_buffer += self.recv()
        msg = self._recv_buffer[4:needed]
        self._recv_buffer = self._recv_buffer[needed:]
        return msg
