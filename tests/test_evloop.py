"""Selector event-loop regressions (PR 9 tentpole, libs/evloop.py):
write backpressure against slow readers, mid-frame disconnects, the
connection gauge, and the 1k-connection soak proving thread count does
not scale with connections."""

import json
import socket
import threading
import time

import pytest

from tendermint_tpu.libs.evloop import EvloopServer
from tendermint_tpu.libs.grpc import PREFACE, GrpcChannel, GrpcServer
from tendermint_tpu.libs.metrics import EvloopMetrics, Registry
from tendermint_tpu.rpc.server import RPCServer

BLAST = bytes(range(256)) * 16384  # 4 MiB echo payload


class BlastProto:
    """Writes a 4 MiB payload for every byte received — the worst case
    for a slow reader: the outbuf must absorb it, pause reads past the
    high-water mark, and drain as the client catches up."""

    def __init__(self, transport):
        self.transport = transport

    def data_received(self, data):
        for _ in data:
            self.transport.write(BLAST)

    def eof_received(self):
        self.transport.close()

    def connection_lost(self, exc):
        pass


def start_evloop(proto_factory, **kw):
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(128)
    srv = EvloopServer(proto_factory, listener_ref=lambda: lsock, **kw)
    srv.start()
    return srv, lsock


def stop_evloop(srv, lsock):
    srv.stop()
    lsock.close()


class TestBackpressure:
    def test_slow_reader_gets_every_byte(self):
        transports = []

        def factory(t):
            transports.append(t)
            return BlastProto(t)

        srv, lsock = start_evloop(
            factory, name="blast", high_water=64 * 1024,
            low_water=16 * 1024,
        )
        try:
            with socket.create_connection(lsock.getsockname()) as c:
                c.sendall(b"x")
                time.sleep(0.2)  # let the outbuf climb past high water
                assert transports and transports[0].buffered() > 0
                got = bytearray()
                while len(got) < len(BLAST):
                    chunk = c.recv(65536)
                    assert chunk, "server dropped a backpressured conn"
                    got += chunk
                assert bytes(got) == BLAST
                # Reads resumed after the drain: a second request works.
                c.sendall(b"y")
                got = bytearray()
                while len(got) < len(BLAST):
                    chunk = c.recv(65536)
                    assert chunk
                    got += chunk
                assert bytes(got) == BLAST
        finally:
            stop_evloop(srv, lsock)

    def test_connection_gauge_tracks_sockets(self):
        reg = Registry()
        srv, lsock = start_evloop(
            BlastProto, name="gauged", metrics=EvloopMetrics(reg)
        )
        try:
            conns = [
                socket.create_connection(lsock.getsockname())
                for _ in range(3)
            ]
            deadline = time.monotonic() + 5
            while srv.connection_count() < 3:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            assert 'connections{server="gauged"} 3' in reg.expose()
            for c in conns:
                c.close()
            while srv.connection_count() > 0:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            assert 'connections{server="gauged"} 0' in reg.expose()
        finally:
            stop_evloop(srv, lsock)


class TestMidFrameDisconnect:
    def test_grpc_survives_torn_frames(self):
        srv = GrpcServer({"/echo.Echo/Ping": lambda b: b}, evloop=True)
        srv.start()
        try:
            host, port = srv.address
            # A client that dies mid-frame (preface + torn frame header)
            # must not wedge the loop or poison later connections.
            for torn in (b"", PREFACE[:7], PREFACE + b"\x00\x00"):
                with socket.create_connection((host, port)) as c:
                    c.sendall(torn)
            time.sleep(0.05)
            ch = GrpcChannel(host, port)
            try:
                assert ch.unary("/echo.Echo/Ping", b"hi") == b"hi"
            finally:
                ch.close()
        finally:
            srv.stop()

    def test_rpc_survives_torn_requests(self):
        srv = RPCServer({"echo": lambda **kw: kw}, evloop=True)
        srv.start()
        try:
            host, port = srv.address
            for torn in (b"", b"POST / HT", b"POST / HTTP/1.1\r\nContent"):
                with socket.create_connection((host, port)) as c:
                    if torn:
                        c.sendall(torn)
            body = json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": "echo",
                 "params": {"a": 1}}
            ).encode()
            with socket.create_connection((host, port)) as c:
                c.sendall(
                    b"POST / HTTP/1.1\r\nContent-Type: application/json\r\n"
                    b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
                )
                resp = c.recv(65536)
            assert b'"a": 1' in resp
        finally:
            srv.stop()


@pytest.mark.slow
class TestSoak:
    def test_1k_connections_flat_thread_count(self):
        """Acceptance pin: 1k+ concurrent connections multiplex onto the
        loop + bounded pool; OS threads must NOT grow with connections
        (the threaded fallback would add one thread per socket)."""
        srv = RPCServer({"echo": lambda **kw: kw}, evloop=True)
        srv.start()
        conns = []
        try:
            host, port = srv.address
            before = threading.active_count()
            for _ in range(1000):
                c = socket.create_connection((host, port))
                conns.append(c)
            deadline = time.monotonic() + 30
            while srv._ev.connection_count() < 1000:
                assert time.monotonic() < deadline, (
                    "accepted %d" % srv._ev.connection_count()
                )
                time.sleep(0.05)
            grown = threading.active_count() - before
            # Loop thread + bounded worker pool; nothing per-connection.
            assert grown <= 24, "thread count grew to +%d" % grown
            # The tier still serves real requests under the idle herd.
            body = json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": "echo",
                 "params": {"n": 7}}
            ).encode()
            req = (
                b"POST / HTTP/1.1\r\nContent-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
            )
            for c in conns[::100]:
                c.sendall(req)
                assert b'"n": 7' in c.recv(65536)
        finally:
            for c in conns:
                try:
                    c.close()
                except OSError:
                    pass
            srv.stop()
