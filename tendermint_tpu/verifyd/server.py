"""verifyd server: one shared scheduler, many tenants, many connections.

The daemon owns the accelerator and serves batched verification over
the zero-dependency gRPC transport. Every connection's lanes funnel
into ONE ``VerifyScheduler`` per algorithm, so batches form ACROSS
clients — a lone light client's header check rides the same device
launch as a validator's commit flood. Scheduling behavior:

- continuous batching: the scheduler's dispatch workers overlap batch
  prep with the in-flight kernel (``crypto/scheduler.py``), so newly
  arrived lanes join the NEXT dispatch instead of waiting behind a
  flush barrier; ``verifyd_dispatch_occupancy`` observes the pipeline
  depth at every hand-off;
- deadline-aware flush: each lane carries ``flush_by`` derived from the
  request's wire deadline (minus a respond margin), so the accumulator
  flushes early rather than letting a lane's deadline expire in queue;
- priority-ordered dequeue: when more lanes are pending than one batch
  holds, consensus < blocksync < light/rpc decides who flushes first;
- multi-tenant namespaces: requests carry a tenant/chain id
  (``protocol`` field 6; absent = ``default``). Admission budgets,
  resident-table pin quotas, and ``tendermint_verifyd_*{tenant=...}``
  metrics are kept per tenant, so one chain's spike exhausts its own
  budget, not the fleet's. Label cardinality is bounded: at most
  ``max_tenants`` distinct labels; later tenants collapse into
  ``other`` (one shared budget bucket);
- admission control: ``light``/``rpc`` requests are shed with an
  explicit RESOURCE_EXHAUSTED response — never a silent drop — when
  the tenant budget, queue depth, or estimated service time exceeds
  budget. ``consensus``/``blocksync`` are never shed by admission
  (losing them stalls the chain, not just a reader); they land in the
  scheduler's own ``max_pending`` backstop instead;
- per-tenant SLO budgets: a tenant may declare a p99 latency target
  (``--tenant-slo name=ms`` server-side, or protocol field 8 from the
  client — the tightest wins, operator config beats the wire). The
  server keeps a bounded sketch of each tenant's attributed latency
  (the same wall the stage vector tiles) and, on a sustained p99
  breach, sheds that tenant's sheddable classes — scoped to the
  tenant, BEFORE the load-based ladder moves — releasing on the same
  hysteresis-clock shape the ladder uses;
- adaptive serving: schedulers run with deadline-aware dynamic
  batching (``crypto/adaptive.py``) unless ``TENDERMINT_TPU_DYN_BATCH=off``
  (or ``dyn_batch=False``) pins the static config; ``stats()`` reports
  the knobs actually in force under ``"scheduler"``.

Brownout ladder (the documented degradation contract, see README):
under SUSTAINED overload — or device COOLDOWN — the server walks an
explicit ladder, one rung per ``escalate_after`` of continuous
pressure, back down one rung per ``recover_after`` of calm:

    0 normal          everything admitted (per-tenant budgets apply)
    1 shed_rpc        rpc requests shed (brownout)
    2 shed_light      + light shed
    3 shed_blocksync  + blocksync shed
    4 shrink_shares   per-tenant budgets shrink to 1/4; consensus past
                      a tenant's shrunken dispatch share verifies on
                      the HOST oracle instead of the device
    5 host_consensus  ALL consensus verifies host-direct (the device is
                      out of the loop, e.g. COOLDOWN); everything else
                      sheds

Consensus is NEVER shed at any rung — its worst case is the host
oracle, which is slower but sound (same ZIP-215 ground truth).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.crypto.adaptive import dyn_batch_default
from tendermint_tpu.crypto.scheduler import (
    DEFAULT_PIPELINE_DEPTH,
    SchedulerSaturatedError,
    VerifyScheduler,
)
from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.grpc import GrpcServer, current_conn_tag
from tendermint_tpu.libs.sanitizer import instrument_attrs
from tendermint_tpu.libs.metrics import VerifydMetrics
from tendermint_tpu.verifyd import protocol
from tendermint_tpu.verifyd import shm as shm_transport
from tendermint_tpu.verifyd.protocol import (
    ALGO_ED25519,
    ALGO_SR25519,
    CLASS_BLOCKSYNC,
    CLASS_CONSENSUS,
    CLASS_LIGHT,
    CLASS_NAMES,
    CLASS_RPC,
    DEFAULT_TENANT,
    KIND_NAMES,
    SHEDDABLE_CLASSES,
    STATUS_DEADLINE_EXCEEDED,
    STATUS_INTERNAL,
    STATUS_INVALID,
    STATUS_NAMES,
    STATUS_OK,
    STATUS_RESOURCE_EXHAUSTED,
    STATS_PATH,
    VERIFY_PATH,
)

DEFAULT_ADMISSION_CAP = 1024  # pending-lane ceiling for sheddable classes
DEFAULT_MAX_PENDING = 4096  # hard scheduler cap (all classes)
DEFAULT_SERVICE_BUDGET = 0.5  # seconds of estimated queue service time
DEFAULT_WAIT = 10.0  # verdict wait for requests without a deadline
DEFAULT_TENANT_CAP = 512  # outstanding sheddable lanes per tenant
DEFAULT_PIN_QUOTA = 256  # resident-table pins per tenant
DEFAULT_MAX_TENANTS = 16  # distinct tenant label/budget buckets
_EWMA_ALPHA = 0.2
_SHRINK_DIVISOR = 4  # tenant share divisor at the shrink_shares rung

# --- per-tenant SLO budgets --------------------------------------------------
# A tenant may declare a p99 latency target (``--tenant-slo name=ms`` or
# protocol field 8). The server keeps a bounded ring of attributed
# server-side latencies per tenant (the same wall the stage vector
# tiles) and, when the tenant's p99 drifts past its target for
# ``slo_breach_after`` seconds, sheds that tenant's SHEDDABLE classes
# scoped to the tenant — BEFORE the load-based brownout ladder would
# move, and without touching any other tenant. Release rides the same
# hysteresis-clock shape as the ladder: after ``slo_recover_after`` of
# shedding the gate opens and the sample ring resets, so the verdict on
# re-breach comes from fresh post-recovery samples, not the stale storm.
SLO_BREACH_AFTER = 0.25  # sustained p99 breach before the scoped shed
SLO_RECOVER_AFTER = 1.0  # shed dwell before release (ring resets)
_SLO_RING = 512  # latency samples kept per tenant
_SLO_RECOMPUTE = 16  # recompute the cached p99 every N samples
_SLO_MIN_SAMPLES = 20  # no verdicts from a cold sketch

# --- brownout ladder ---------------------------------------------------------

LEVEL_NORMAL = 0
LEVEL_SHED_RPC = 1
LEVEL_SHED_LIGHT = 2
LEVEL_SHED_BLOCKSYNC = 3
LEVEL_SHRINK_SHARES = 4
LEVEL_HOST_CONSENSUS = 5
LEVEL_NAMES = {
    LEVEL_NORMAL: "normal",
    LEVEL_SHED_RPC: "shed_rpc",
    LEVEL_SHED_LIGHT: "shed_light",
    LEVEL_SHED_BLOCKSYNC: "shed_blocksync",
    LEVEL_SHRINK_SHARES: "shrink_shares",
    LEVEL_HOST_CONSENSUS: "host_consensus",
}
# the declared shed order: rpc first, light next, blocksync last;
# consensus has NO entry — no rung ever sheds it
_CLASS_SHED_LEVEL = {
    CLASS_RPC: LEVEL_SHED_RPC,
    CLASS_LIGHT: LEVEL_SHED_LIGHT,
    CLASS_BLOCKSYNC: LEVEL_SHED_BLOCKSYNC,
}


def level_sheds_class(level: int, klass: int) -> bool:
    """True when the ladder rung ``level`` sheds priority class
    ``klass``. Consensus is never shed at any level."""
    at = _CLASS_SHED_LEVEL.get(klass)
    return at is not None and level >= at


def _device_cooling() -> bool:
    """Process-wide device health says the accelerator is cooling down
    (or terminally disabled): pin the ladder at host_consensus."""
    try:
        from tendermint_tpu.ops.device_policy import (
            COOLDOWN,
            DISABLED,
            shared,
        )

        return shared.state in (COOLDOWN, DISABLED)
    except Exception:
        # health machinery unavailable (host-only build): never escalate
        return False


@instrument_attrs
class BrownoutController:
    """Walks the degradation ladder on sustained pressure.

    Fed one boolean load sample per request (``observe``): pressure
    sustained for ``escalate_after`` seconds climbs one rung (and
    restarts the clock); calm sustained for ``recover_after`` descends
    one. ``cooldown_fn`` (default: the process-wide device health
    machine) pins the EFFECTIVE level at host_consensus while the
    device is in COOLDOWN/DISABLED, regardless of load. ``force``
    overrides the level outright (tests, operator override).
    """

    def __init__(
        self,
        escalate_after: float = 0.25,
        recover_after: float = 1.0,
        cooldown_fn: Optional[Callable[[], bool]] = _device_cooling,
    ):
        self.escalate_after = escalate_after
        self.recover_after = recover_after
        self._cooldown_fn = cooldown_fn
        self._mtx = threading.Lock()
        self._level = LEVEL_NORMAL  # guarded-by: _mtx
        self._forced: Optional[int] = None  # guarded-by: _mtx
        self._pressure_since: Optional[float] = None  # guarded-by: _mtx
        self._calm_since: Optional[float] = None  # guarded-by: _mtx
        self.transitions = {"up": 0, "down": 0}  # guarded-by: _mtx

    def force(self, level: Optional[int]) -> None:
        """Pin the effective level (None releases the pin)."""
        with self._mtx:
            self._forced = level

    @property
    def level(self) -> int:
        """The organic (load-driven) level, ignoring force/cooldown."""
        with self._mtx:
            return self._level

    def effective(self) -> int:
        with self._mtx:
            return self._effective_locked()

    def _effective_locked(self) -> int:
        lvl = self._level if self._forced is None else self._forced
        if self._cooldown_fn is not None:
            try:
                cooling = self._cooldown_fn()
            except Exception:
                cooling = False  # a broken probe must not change policy
            if cooling:
                lvl = max(lvl, LEVEL_HOST_CONSENSUS)
        return lvl

    def snapshot(self) -> dict:
        """Locked view of the ladder state for monitors and tests —
        reading ``transitions`` raw races every in-flight ``observe``."""
        with self._mtx:
            return {
                "level": self._level,
                "forced": self._forced,
                "effective": self._effective_locked(),
                "transitions": dict(self.transitions),
            }

    def observe(
        self, pressure: bool, now: Optional[float] = None
    ) -> Tuple[int, int]:
        """Feed one load sample; returns ``(effective_level, delta)``
        where delta is +1/-1 when this sample moved the organic level."""
        now = time.monotonic() if now is None else now
        delta = 0
        with self._mtx:
            if pressure:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                elif (
                    now - self._pressure_since >= self.escalate_after
                    and self._level < LEVEL_HOST_CONSENSUS
                ):
                    self._level += 1
                    self.transitions["up"] += 1
                    self._pressure_since = now
                    delta = 1
            else:
                self._pressure_since = None
                if self._level == LEVEL_NORMAL:
                    self._calm_since = None
                elif self._calm_since is None:
                    self._calm_since = now
                elif now - self._calm_since >= self.recover_after:
                    self._level -= 1
                    self.transitions["down"] += 1
                    self._calm_since = now
                    delta = -1
            return self._effective_locked(), delta


# --- tenants -----------------------------------------------------------------

TENANT_OVERFLOW_LABEL = "other"


def sanitize_tenant_label(name: str) -> str:
    """Metrics-safe tenant label: alnum/dash/underscore/dot, max 32
    chars. Names that don't survive sanitization intact become a stable
    hash so distinct ugly ids don't collide with each other."""
    safe = "".join(c for c in name if c.isalnum() or c in "-_.")[:32]
    if safe == name and safe:
        return safe
    return "t" + hashlib.sha1(name.encode("utf-8")).hexdigest()[:8]


class _TenantState:
    """Per-tenant accounting. All fields guarded by the server's
    ``_tenant_mtx`` (one lock for the whole registry: tenant counts are
    bounded and the critical sections are tiny)."""

    __slots__ = (
        "label", "depth", "lanes", "sheds", "host_direct",
        "slo_ms", "slo_pinned", "lat_ring", "lat_idx", "lat_new",
        "p99", "slo_breach_since", "slo_shed_since", "slo_shedding",
        "slo_sheds",
    )

    def __init__(self, label: str):
        self.label = label
        self.depth = 0  # outstanding (admitted, unresolved) lanes
        self.lanes = 0  # total lanes admitted
        self.sheds = 0  # total requests shed
        self.host_direct = 0  # lanes verified on the host oracle
        # SLO budget: declared p99 target (0 = none) and the bounded
        # attributed-latency sketch that polices it
        self.slo_ms = 0  # declared p99 target; 0 = no SLO
        self.slo_pinned = False  # server-config target beats the wire's
        self.lat_ring: List[float] = []  # bounded latency samples (s)
        self.lat_idx = 0  # ring write cursor
        self.lat_new = 0  # samples since the last p99 recompute
        self.p99 = 0.0  # cached ring p99 (seconds)
        self.slo_breach_since: Optional[float] = None
        self.slo_shed_since: Optional[float] = None
        self.slo_shedding = False
        self.slo_sheds = 0  # requests shed by the SLO gate


# --- admission ---------------------------------------------------------------


def _introspect_bytes() -> Dict[str, int]:
    """Device-byte ledger for stats(); never fails the stats call."""
    try:
        from tendermint_tpu.ops import introspect

        return introspect.accountant.snapshot()["device_bytes"]
    except Exception:
        return {}


def _introspect_compiles() -> Dict[str, int]:
    try:
        from tendermint_tpu.ops import introspect

        return introspect.accountant.snapshot()["compile_events"]
    except Exception:
        return {}


def _default_sr25519_verify(pks, msgs, sigs) -> List[bool]:
    """Tiered sr25519 dispatch, mirroring the ed25519 policy."""
    if len(pks) < crypto_batch.DEVICE_THRESHOLD:
        return _host_sr25519_verify(pks, msgs, sigs)
    from tendermint_tpu.ops.sr25519_batch import verify_batch_sr

    return list(verify_batch_sr(pks, msgs, sigs))


def _host_sr25519_verify(pks, msgs, sigs) -> List[bool]:
    from tendermint_tpu.crypto.sr25519 import verify as sr_verify

    return [sr_verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)]


@instrument_attrs
class AdmissionController:
    """Sheds sheddable-class load when the queue is past budget.

    Two trip-wires, both checked at enqueue time: unresolved depth past
    ``cap`` lanes, or estimated service time for the queue (EWMA
    per-lane flush cost x depth) past ``service_budget`` seconds. The
    estimate learns from real flushes via ``observe_flush``.
    """

    def __init__(
        self,
        cap: int = DEFAULT_ADMISSION_CAP,
        service_budget: float = DEFAULT_SERVICE_BUDGET,
    ):
        self.cap = cap
        self.service_budget = service_budget
        self._lane_ewma = 0.0  # seconds per lane, learned  # guarded-by: _mtx
        self._mtx = threading.Lock()

    def observe_flush(self, lanes: int, seconds: float) -> None:
        if lanes <= 0 or seconds <= 0:
            return
        per_lane = seconds / lanes
        with self._mtx:
            if self._lane_ewma == 0.0:
                self._lane_ewma = per_lane
            else:
                self._lane_ewma += _EWMA_ALPHA * (per_lane - self._lane_ewma)

    def estimated_service_time(self, depth: int) -> float:
        with self._mtx:
            return depth * self._lane_ewma

    def pressure(self, depth: int) -> bool:
        """Load sample for the brownout controller: is the queue past
        either budget right now?"""
        if depth > self.cap:
            return True
        return self.estimated_service_time(depth) > self.service_budget

    def admit(self, klass: int, lanes: int, depth: int) -> Optional[str]:
        """None = admitted; else the shed reason. Only sheddable
        classes (light/rpc) are ever refused here."""
        if klass not in SHEDDABLE_CLASSES:
            return None
        if depth + lanes > self.cap:
            return "queue_depth"
        if self.estimated_service_time(depth + lanes) > self.service_budget:
            return "service_time"
        return None


@instrument_attrs
class VerifydServer:
    """The verification daemon. ``verify_fn`` defaults to the tiered
    host/device ed25519 dispatch; tests inject a host oracle."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch: Optional[int] = None,
        max_delay: float = 0.002,
        admission_cap: int = DEFAULT_ADMISSION_CAP,
        max_pending: int = DEFAULT_MAX_PENDING,
        service_budget: float = DEFAULT_SERVICE_BUDGET,
        verify_fn: Optional[Callable[..., List[bool]]] = None,
        sr25519_verify_fn: Optional[Callable[..., List[bool]]] = None,
        metrics: Optional[VerifydMetrics] = None,
        evloop_metrics=None,
        continuous: Optional[bool] = None,
        pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
        tenant_cap: int = DEFAULT_TENANT_CAP,
        tenant_pin_quota: int = DEFAULT_PIN_QUOTA,
        max_tenants: int = DEFAULT_MAX_TENANTS,
        brownout: Optional[BrownoutController] = None,
        shm: Optional[str] = None,
        dyn_batch: Optional[bool] = None,
        tenant_slos: Optional[Dict[str, int]] = None,
        slo_breach_after: float = SLO_BREACH_AFTER,
        slo_recover_after: float = SLO_RECOVER_AFTER,
        shard_id: int = -1,
    ):
        self.metrics = metrics or VerifydMetrics.nop()
        # federation identity: -1 = standalone (pre-federation wire
        # behaviour: response field 6 is omitted entirely)
        self.shard_id = int(shard_id)
        if self.shard_id > protocol.MAX_SHARD_ID:
            raise ValueError(f"shard id too large: {self.shard_id}")
        self.max_delay = max_delay
        self.admission = AdmissionController(admission_cap, service_budget)
        self.brownout = brownout or BrownoutController()
        self.tenant_cap = tenant_cap
        self.tenant_pin_quota = tenant_pin_quota
        self.max_tenants = max(1, max_tenants)
        self.slo_breach_after = slo_breach_after
        self.slo_recover_after = slo_recover_after
        # None = env default: the serving tier is adaptive unless
        # TENDERMINT_TPU_DYN_BATCH=off pins the static scheduler
        self.dyn_batch = (
            dyn_batch_default() if dyn_batch is None else bool(dyn_batch)
        )
        self._verify_fns = {
            ALGO_ED25519: (
                verify_fn or crypto_batch.tiered_verify_ed25519,
                crypto_batch.host_verify_ed25519,
            ),
            ALGO_SR25519: (
                sr25519_verify_fn or _default_sr25519_verify,
                _host_sr25519_verify,
            ),
        }
        # None = mesh-aware default, resolved LAZILY by the scheduler
        # against the mesh config generation — a server built before
        # MeshManager.configure() no longer bakes the pre-config device
        # count into max_batch (the stale-default fix).
        self._sched_args = dict(
            max_batch=max_batch,
            max_delay=max_delay,
            max_pending=max_pending,
            continuous=continuous,
            pipeline_depth=pipeline_depth,
            dyn_batch=self.dyn_batch,
        )
        self._schedulers: Dict[int, VerifyScheduler] = {}  # guarded-by: _sched_mtx
        self._sched_mtx = threading.Lock()
        self._depth_mtx = threading.Lock()
        self._class_depth: Dict[int, int] = {}  # guarded-by: _depth_mtx
        self._tenant_mtx = threading.Lock()
        self._tenants: Dict[str, _TenantState] = {}  # guarded-by: _tenant_mtx
        # plain counters for tests and bench (metrics-free introspection).
        # Handler threads and the schedulers' dispatch threads all write
        # these, so they take their own mutex.
        self._stats_mtx = threading.Lock()
        self.cross_client_flushes: Dict[str, int] = {
            "size": 0, "deadline": 0, "shutdown": 0,
        }  # guarded-by: _stats_mtx
        self.admission_rejections = 0  # guarded-by: _stats_mtx
        self.deadline_expired = 0  # guarded-by: _stats_mtx
        self.requests_served = 0  # guarded-by: _stats_mtx
        self.host_direct_lanes = 0  # guarded-by: _stats_mtx
        self.shm_lanes = 0  # guarded-by: _stats_mtx
        self.shm_torn_slabs = 0  # guarded-by: _stats_mtx
        self.shm_fallbacks = 0  # guarded-by: _stats_mtx
        # requests stamped for a DIFFERENT shard (stale client shard
        # map); served anyway — routing is placement advice, not an
        # authorization boundary — but counted so operators see churn
        self.misroutes = 0  # guarded-by: _stats_mtx
        self.route_epoch_seen = 0  # guarded-by: _stats_mtx
        self._evloop_metrics = evloop_metrics
        # zero-copy ingress: the slab-ring endpoint starts beside the
        # TCP listener unless the mode (param beats config/env) is off
        self._shm_mode = shm if shm is not None else shm_transport.shm_mode()
        if self._shm_mode not in ("auto", "on", "off"):
            raise ValueError(f"bad shm mode {self._shm_mode!r}")
        # _shm_endpoint is published by start() and retired by stop()
        # while handler threads read it per-request; _shm_mtx guards the
        # reference (methods on a snapshot are called outside the lock)
        self._shm_mtx = threading.Lock()
        self._shm_endpoint: Optional[shm_transport.ShmEndpoint] = None
        self._grpc = GrpcServer(
            {VERIFY_PATH: self._handle, STATS_PATH: self._handle_stats},
            host, port,
            evloop_metrics=evloop_metrics,
        )
        # operator-declared p99 targets (--tenant-slo name=ms): pinned,
        # so a wire-declared target (protocol field 8) never loosens them
        for name, slo_ms in (tenant_slos or {}).items():
            ts = self._tenant_for(name)
            with self._tenant_mtx:
                ts.slo_ms = max(0, int(slo_ms))
                ts.slo_pinned = True

    # --- lifecycle ----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._grpc.address

    @property
    def max_batch(self) -> int:
        """Resolved size-flush threshold (mesh-aware when defaulted) —
        delegated to the scheduler, which re-resolves the mesh-aware
        default whenever the mesh configuration generation moves."""
        return self.scheduler.max_batch

    @property
    def scheduler(self) -> VerifyScheduler:
        """The ed25519 scheduler (the common case; tests poke it)."""
        return self._scheduler_for(ALGO_ED25519)

    def start(self) -> None:
        self._scheduler_for(ALGO_ED25519)  # eager: first request is hot
        self._grpc.start()
        with self._shm_mtx:
            want_shm = self._shm_mode != "off" and self._shm_endpoint is None
        if want_shm:
            ep = shm_transport.ShmEndpoint(
                self._serve,
                metrics=self.metrics,
                evloop_metrics=self._evloop_metrics,
                on_stat=self._shm_stat,
            )
            try:
                ep.start(self.address[1])
            except OSError:
                # no AF_UNIX / unwritable tempdir: TCP-only serving is
                # strictly correct, so degrade instead of failing start
                self._shm_stat("shm_fallbacks", 1)
                ep = None
            with self._shm_mtx:
                self._shm_endpoint = ep

    def stop(self) -> None:
        self._grpc.stop()
        # doorbells close before the schedulers so no NEW slab drains
        # race scheduler teardown; drains already in flight resolve
        # against the shutdown flush below
        with self._shm_mtx:
            ep, self._shm_endpoint = self._shm_endpoint, None
        if ep is not None:
            ep.stop()
        with self._sched_mtx:
            scheds, self._schedulers = dict(self._schedulers), {}
        for sched in scheds.values():
            sched.stop()

    @property
    def shm_socket_path(self) -> str:
        """Doorbell socket path when the shm endpoint is live ('' when
        negotiation is off or the endpoint failed to start)."""
        with self._shm_mtx:
            ep = self._shm_endpoint
        return ep.socket_path if ep is not None else ""

    def shm_backlog(self) -> int:
        """Lanes committed to slab rings but not yet in the scheduler —
        added to ``load_depth`` so admission and the brownout ladder see
        shm pressure exactly like TCP pressure."""
        with self._shm_mtx:
            ep = self._shm_endpoint
        return ep.backlog_lanes() if ep is not None else 0

    def _shm_stat(self, field: str, n: int) -> None:
        with self._stats_mtx:
            setattr(self, field, getattr(self, field) + n)

    def _scheduler_for(self, algo: int) -> VerifyScheduler:
        with self._sched_mtx:
            sched = self._schedulers.get(algo)
            if sched is None:
                verify_fn, fallback_fn = self._verify_fns[algo]
                sched = VerifyScheduler(
                    verify_fn,
                    fallback_fn=fallback_fn,
                    on_flush=(
                        lambda reason, batch, seconds, _algo=algo: (
                            self._on_flush(reason, batch, seconds, _algo)
                        )
                    ),
                    on_dispatch=self._on_dispatch,
                    **self._sched_args,
                )
                sched.start()
                self._schedulers[algo] = sched
            return sched

    # --- tenants ------------------------------------------------------------

    def _tenant_for(self, name: str) -> _TenantState:
        """Registry lookup with bounded cardinality: once
        ``max_tenants`` distinct states exist, every UNSEEN tenant maps
        to one shared ``other`` bucket (label and budget both)."""
        with self._tenant_mtx:
            ts = self._tenants.get(name)
            if ts is not None:
                return ts
            distinct = len(set(id(t) for t in self._tenants.values()))
            if distinct >= self.max_tenants:
                ts = self._tenants.get(TENANT_OVERFLOW_LABEL)
                if ts is None:
                    ts = _TenantState(TENANT_OVERFLOW_LABEL)
                    self._tenants[TENANT_OVERFLOW_LABEL] = ts
            else:
                ts = _TenantState(sanitize_tenant_label(name))
            self._tenants[name] = ts
            return ts

    def stats(self) -> Dict[str, object]:
        """Locked snapshot of the wire counters. Handler threads write
        these under ``_stats_mtx`` while requests are in flight, so live
        monitors (tests polling mid-run, bench sections) must read here
        — a raw attribute read races the serving path even after a
        client got its response, because the TCP round-trip is not a
        synchronization edge the counters ride on."""
        with self._shm_mtx:
            ep = self._shm_endpoint
        # resolved scheduler knobs (the config actually under test):
        # snapshot the LIVE scheduler if one exists — stats() must not
        # resurrect a scheduler after stop()
        with self._sched_mtx:
            sched = self._schedulers.get(ALGO_ED25519)
        knobs = sched.resolved_knobs() if sched is not None else None
        with self._stats_mtx:
            return {
                "shard_id": self.shard_id,
                "misroutes": self.misroutes,
                "route_epoch_seen": self.route_epoch_seen,
                "requests_served": self.requests_served,
                "admission_rejections": self.admission_rejections,
                "deadline_expired": self.deadline_expired,
                "host_direct_lanes": self.host_direct_lanes,
                "cross_client_flushes": dict(self.cross_client_flushes),
                "shm_lanes": self.shm_lanes,
                "shm_torn_slabs": self.shm_torn_slabs,
                "shm_fallbacks": self.shm_fallbacks,
                "shm_sessions": ep.session_count() if ep is not None else 0,
                "scheduler": knobs,
                # device-tier ledger (ops/introspect.py): resident /
                # slab bytes by owner + compile counters, so `verifyd
                # stats` answers "what is sitting on the device" too
                "device_bytes": _introspect_bytes(),
                "compile_events": _introspect_compiles(),
            }

    def tenant_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-label accounting snapshot (bench/chaos introspection)."""
        out: Dict[str, Dict[str, int]] = {}
        with self._tenant_mtx:
            for ts in self._tenants.values():
                if ts.label not in out:
                    out[ts.label] = {
                        "depth": ts.depth,
                        "lanes": ts.lanes,
                        "sheds": ts.sheds,
                        "host_direct": ts.host_direct,
                        "slo_ms": ts.slo_ms,
                        "slo_sheds": ts.slo_sheds,
                        "slo_shedding": ts.slo_shedding,
                        "p99_ms": round(ts.p99 * 1000.0, 3),
                    }
        return out

    def _tenant_shed(self, ts: _TenantState, reason: str) -> None:
        with self._tenant_mtx:
            ts.sheds += 1
        self.metrics.tenant_rejections.labels(
            tenant=ts.label, reason=reason
        ).inc()

    def _tenant_admit(self, ts: _TenantState, n: int) -> None:
        with self._tenant_mtx:
            ts.depth += n
            ts.lanes += n
            depth = ts.depth
        self.metrics.tenant_lanes.labels(tenant=ts.label).inc(n)
        self.metrics.tenant_queue_depth.labels(tenant=ts.label).set(depth)

    def _tenant_release(self, ts: _TenantState, n: int) -> None:
        with self._tenant_mtx:
            ts.depth = max(0, ts.depth - n)
            depth = ts.depth
        self.metrics.tenant_queue_depth.labels(tenant=ts.label).set(depth)

    def _tenant_budget(self, level: int) -> int:
        """Effective per-tenant outstanding-lane budget at this rung."""
        if level >= LEVEL_SHRINK_SHARES:
            return max(1, self.tenant_cap // _SHRINK_DIVISOR)
        return self.tenant_cap

    # --- per-tenant SLO budgets ---------------------------------------------

    def _tenant_declare_slo(self, ts: _TenantState, slo_ms: int) -> None:
        """Wire-declared target (protocol field 8): adopted unless the
        operator pinned one via --tenant-slo; the TIGHTEST wire value
        wins so one lax client can't loosen its tenant's budget."""
        if slo_ms <= 0:
            return
        with self._tenant_mtx:
            if ts.slo_pinned:
                return
            if ts.slo_ms == 0 or slo_ms < ts.slo_ms:
                ts.slo_ms = slo_ms

    def _tenant_observe_latency(
        self, ts: _TenantState, seconds: float, now: Optional[float] = None
    ) -> None:
        """Fold one attributed server-side latency (the wall the stage
        vector tiles) into the tenant's sketch and run the breach
        hysteresis. ``now`` is injectable for synthetic-clock tests."""
        now = time.monotonic() if now is None else now
        with self._tenant_mtx:
            if len(ts.lat_ring) < _SLO_RING:
                ts.lat_ring.append(seconds)
            else:
                ts.lat_ring[ts.lat_idx] = seconds
                ts.lat_idx = (ts.lat_idx + 1) % _SLO_RING
            ts.lat_new += 1
            if ts.lat_new >= _SLO_RECOMPUTE or ts.p99 == 0.0:
                ts.lat_new = 0
                ordered = sorted(ts.lat_ring)
                ts.p99 = ordered[max(0, int(len(ordered) * 0.99) - 1)]
            if ts.slo_ms <= 0 or ts.slo_shedding:
                return
            if (
                len(ts.lat_ring) >= _SLO_MIN_SAMPLES
                and ts.p99 > ts.slo_ms / 1000.0
            ):
                if ts.slo_breach_since is None:
                    ts.slo_breach_since = now
                elif now - ts.slo_breach_since >= self.slo_breach_after:
                    # sustained breach: tenant-scoped brownout, BEFORE
                    # the load-based ladder has any reason to move
                    ts.slo_shedding = True
                    ts.slo_shed_since = now
                    ts.slo_breach_since = None
                    tracing.instant(
                        "verifyd_tenant_slo_breach",
                        tenant=ts.label,
                        p99_ms=round(ts.p99 * 1000.0, 3),
                        slo_ms=ts.slo_ms,
                    )
            else:
                ts.slo_breach_since = None

    def _tenant_slo_gate(
        self, ts: _TenantState, now: Optional[float] = None
    ) -> bool:
        """True while the tenant's sheddable classes are SLO-shed.
        Release is the existing hysteresis-clock shape: after
        ``slo_recover_after`` of shedding the gate opens and the sample
        ring resets, so re-breach verdicts come from fresh samples."""
        now = time.monotonic() if now is None else now
        with self._tenant_mtx:
            if not ts.slo_shedding:
                return False
            if (
                ts.slo_shed_since is not None
                and now - ts.slo_shed_since >= self.slo_recover_after
            ):
                ts.slo_shedding = False
                ts.slo_shed_since = None
                ts.lat_ring = []
                ts.lat_idx = 0
                ts.lat_new = 0
                ts.p99 = 0.0
                return False
            ts.slo_sheds += 1
            return True

    # --- flush / dispatch observers -----------------------------------------

    def _on_dispatch(self, depth: int, lanes: int, reason: str) -> None:
        """Scheduler hand-off hook: depth = outstanding dispatches
        (queued + in flight) — the continuous-batching occupancy."""
        self.metrics.dispatch_occupancy.observe(depth)

    def _on_flush(
        self, reason: str, batch: list, seconds: float, algo: int = ALGO_ED25519
    ) -> None:
        lanes = len(batch)
        self.admission.observe_flush(lanes, seconds)
        self.metrics.flushes.labels(reason=reason).inc()
        self.metrics.batch_occupancy.observe(lanes)
        if algo == ALGO_ED25519:
            # Repeat signers from set-less verifyd traffic feed the
            # device-resident table store's hot-key pinning
            # (ops/resident.py), capped per tenant so one chain's
            # validator universe can't evict everyone else's; the
            # import stays lazy + guarded so a host-only daemon config
            # never pays for the ops engine.
            try:
                from tendermint_tpu.ops import resident

                by_tenant: Dict[Optional[str], list] = {}
                for p in batch:
                    by_tenant.setdefault(p.tenant, []).append(p.pubkey)
                for tname, pks in by_tenant.items():
                    resident.note_hot_keys(
                        pks,
                        tenant=tname or DEFAULT_TENANT,
                        quota=self.tenant_pin_quota,
                    )
            except Exception:
                # accounting hook only — a broken ops import must never
                # touch the serving path
                pass
        if len({p.tag for p in batch}) > 1:
            with self._stats_mtx:
                self.cross_client_flushes[reason] = (
                    self.cross_client_flushes.get(reason, 0) + 1
                )
            self.metrics.cross_client_flushes.labels(reason=reason).inc()

    # --- per-class depth gauge ----------------------------------------------

    def _track_depth(self, klass: int, delta: int) -> None:
        with self._depth_mtx:
            depth = self._class_depth.get(klass, 0) + delta
            self._class_depth[klass] = max(0, depth)
            self.metrics.queue_depth.labels(klass=CLASS_NAMES[klass]).set(
                self._class_depth[klass]
            )

    # --- request handler ----------------------------------------------------

    def _respond(
        self,
        status: int,
        verdicts: List[bool],
        message: str,
        t0: float,
        kind_name: str,
        queue_depth: int = 0,
        tenant_label: str = "",
        stages: Optional[Dict[str, float]] = None,
    ) -> protocol.VerifyResponse:
        with tracing.span("verifyd_respond", status=STATUS_NAMES[status]):
            with self._stats_mtx:
                self.requests_served += 1
            self.metrics.requests.labels(
                kind=kind_name, status=STATUS_NAMES[status]
            ).inc()
            self.metrics.request_seconds.labels(kind=kind_name).observe(
                time.monotonic() - t0
            )
            if tenant_label:
                self.metrics.tenant_request_seconds.labels(
                    tenant=tenant_label
                ).observe(time.monotonic() - t0)
            return protocol.VerifyResponse(
                status=status,
                verdicts=verdicts,
                message=message,
                queue_depth=queue_depth,
                stages=protocol.pack_stages(stages) if stages else b"",
                shard_id=self.shard_id,
            )

    def _shed(
        self,
        ts: _TenantState,
        klass_name: str,
        reason: str,
        n: int,
        message: str,
        t0: float,
        kind_name: str,
        depth: int,
    ) -> protocol.VerifyResponse:
        """Every shed path funnels here: explicit RESOURCE_EXHAUSTED on
        the wire, a reasoned rejection metric per class AND per tenant —
        never a silent drop."""
        with self._stats_mtx:
            self.admission_rejections += 1
        self._tenant_shed(ts, reason)
        self.metrics.admission_rejections.labels(
            klass=klass_name, reason=reason
        ).inc()
        tracing.instant(
            "verifyd_shed",
            klass=klass_name,
            reason=reason,
            lanes=n,
            tenant=ts.label,
        )
        return self._respond(
            STATUS_RESOURCE_EXHAUSTED,
            [],
            message,
            t0,
            kind_name,
            depth,
            tenant_label=ts.label,
        )

    def _host_direct(
        self,
        req,
        ts: _TenantState,
        t0: float,
        kind_name: str,
        level: int,
    ) -> protocol.VerifyResponse:
        """host_consensus rung: consensus lanes bypass the device
        scheduler and verify on the host oracle — slower, sound, and
        immune to whatever took the device out."""
        n = len(req)
        _verify_fn, host_fn = self._verify_fns[req.algo]
        # shm requests hand msgs over as slab memoryviews; the host
        # oracle path bypasses the scheduler's flush-assembly (where
        # they normally materialise), so copy them out here
        msgs = [
            m.tobytes() if type(m) is memoryview else m for m in req.msgs
        ]
        t_dev0 = time.monotonic()
        with tracing.span(
            "verifyd_host_direct", lanes=n, tenant=ts.label, level=level
        ):
            verdicts = list(host_fn(req.pks, msgs, req.sigs))
        t_dev1 = time.monotonic()
        with self._stats_mtx:
            self.host_direct_lanes += n
        with self._tenant_mtx:
            ts.host_direct += n
            ts.lanes += n
        self.metrics.host_direct_lanes.inc(n)
        self.metrics.tenant_lanes.labels(tenant=ts.label).inc(n)
        return self._respond(
            STATUS_OK, verdicts, "", t0, kind_name, 0, tenant_label=ts.label,
            stages={
                "admission": t_dev0 - t0,
                "device": t_dev1 - t_dev0,
                "collect": time.monotonic() - t_dev1,
            },
        )

    def _handle_stats(self, payload: bytes) -> bytes:
        """STATS_PATH unary: one JSON snapshot of everything a
        federation client (or ``verifyd stats``) needs to gossip — wire
        counters, per-tenant SLO view, brownout level, and this shard's
        pinned resident-table slice. The request payload is ignored
        (reserved), so any client version can poll any server version."""
        del payload
        from tendermint_tpu.ops import resident

        snap = {
            "shard_id": self.shard_id,
            "stats": self.stats(),
            "tenants": self.tenant_stats(),
            "brownout": self.brownout.snapshot(),
            "resident": resident.stats(),
            "pinned_keys": resident.pinned_keys(),
        }
        return json.dumps(snap, sort_keys=True).encode("utf-8")

    def _handle(self, payload: bytes) -> bytes:
        """TCP entry point: decode the wire frame, serve, re-encode.
        The shm drain path skips both codec halves and enters
        ``_serve`` directly — that is the entire zero-copy win."""
        t0 = time.monotonic()
        with tracing.span("verifyd_decode", nbytes=len(payload)):
            try:
                req = protocol.decode_request(payload)
            except ValueError as exc:
                return protocol.encode_response(
                    self._respond(STATUS_INVALID, [], str(exc), t0, "raw")
                )
        # Connection identity for cross-client batching stats. Under
        # the event loop many connections share few worker threads,
        # so the transport's per-connection tag is authoritative;
        # the thread ident covers direct (non-gRPC) handler calls.
        tag = current_conn_tag(threading.get_ident())
        return protocol.encode_response(self._serve(req, t0, tag=tag))

    def _serve(
        self,
        req: protocol.VerifyRequest,
        t0: float,
        tag: Optional[object] = None,
        on_entries: Optional[Callable[[List[object]], None]] = None,
    ) -> protocol.VerifyResponse:
        """Transport-independent serving path: admission, brownout,
        tenant budgets, enqueue, wait. ``on_entries`` (shm drain) gets
        the scheduler entries right after submit so the caller can tell
        whether a deadline response left lanes still holding slab
        memoryviews (the held-slab reclaim protocol).

        When the request carries a trace context (protocol field 7 /
        slab header trace words) every span this handler opens links
        under the CLIENT's span, so a fleet-merged timeline shows the
        client's ``verifyd_call`` as ancestor of the server's enqueue,
        dispatch, and chunk spans."""
        ctx = (
            tracing.TraceContext.from_bytes(req.trace) if req.trace else None
        )
        if ctx is None:
            return self._serve_inner(req, t0, tag, on_entries, None)
        with tracing.attach(ctx):
            return self._serve_inner(req, t0, tag, on_entries, ctx)

    def _serve_inner(
        self,
        req: protocol.VerifyRequest,
        t0: float,
        tag: Optional[object],
        on_entries: Optional[Callable[[List[object]], None]],
        ctx: Optional[tracing.TraceContext],
    ) -> protocol.VerifyResponse:
        kind_name = "raw"
        t_entry = time.monotonic()  # decode/transport hand-off boundary
        try:
            kind_name = KIND_NAMES[req.kind]
            klass_name = CLASS_NAMES[req.klass]
            # federation bookkeeping: a request stamped for another
            # shard means the client's shard map is stale — serve it
            # anyway (any shard verifies correctly; only table locality
            # suffers) but count it and leave a trace breadcrumb
            if req.route_epoch:
                with self._stats_mtx:
                    if req.route_epoch > self.route_epoch_seen:
                        self.route_epoch_seen = req.route_epoch
            if (
                req.shard_id >= 0
                and self.shard_id >= 0
                and req.shard_id != self.shard_id
            ):
                with self._stats_mtx:
                    self.misroutes += 1
                tracing.instant(
                    "verifyd_misroute",
                    want=req.shard_id,
                    got=self.shard_id,
                    epoch=req.route_epoch,
                )
            ts = self._tenant_for(req.tenant)
            if req.slo_ms:
                self._tenant_declare_slo(ts, req.slo_ms)
            n = len(req)
            if n == 0:
                return self._respond(
                    STATUS_OK, [], "", t0, kind_name, tenant_label=ts.label
                )
            sched = self._scheduler_for(req.algo)
            # the caller-observed wire/decode wait is the adaptive
            # controller's shrink signal (queueing ahead of the
            # accumulator dominating the flush deadline)
            sched.note_queue_wait(t_entry - t0)
            deadline_s = req.deadline_ms / 1000.0 if req.deadline_ms else 0.0

            # load_depth counts in-flight lanes too: on the continuous
            # path lanes leave the accumulator while their dispatch
            # still occupies the device, and admission must see them.
            # Committed-but-undrained slab-ring lanes ride on top, so
            # shm pressure moves the brownout ladder like TCP pressure.
            depth = sched.load_depth() + self.shm_backlog()
            level, moved = self.brownout.observe(
                self.admission.pressure(depth)
            )
            self.metrics.brownout_level.set(level)
            if moved:
                direction = "up" if moved > 0 else "down"
                self.metrics.brownout_transitions.labels(
                    direction=direction
                ).inc()
                tracing.instant(
                    "verifyd_brownout",
                    level=LEVEL_NAMES[level],
                    direction=direction,
                )

            # per-tenant SLO gate, BEFORE the load-based ladder: a
            # tenant whose attributed p99 drifted past its declared
            # budget sheds ITS OWN sheddable classes while every other
            # tenant — and the global ladder — is untouched. Consensus
            # and blocksync are exempt exactly as on the ladder.
            if req.klass in SHEDDABLE_CLASSES and self._tenant_slo_gate(ts):
                return self._shed(
                    ts, klass_name, "slo", n,
                    f"tenant {ts.label} over SLO budget"
                    f" ({ts.slo_ms}ms p99 target)",
                    t0, kind_name, depth,
                )

            # ladder rungs 1-3: whole-class sheds (rpc -> light ->
            # blocksync; consensus never)
            if level_sheds_class(level, req.klass):
                return self._shed(
                    ts, klass_name, "brownout", n,
                    f"{klass_name} shed (brownout {LEVEL_NAMES[level]})",
                    t0, kind_name, depth,
                )
            # ladder rung 5: device out of the loop — consensus goes
            # host-direct (rung 3 already shed everything else)
            if level >= LEVEL_HOST_CONSENSUS and req.klass == CLASS_CONSENSUS:
                return self._host_direct(req, ts, t0, kind_name, level)

            # per-tenant budget: all-or-nothing for the WHOLE request —
            # an atomic lane group never splits on the budget boundary
            budget = self._tenant_budget(level)
            if req.klass in SHEDDABLE_CLASSES:
                with self._tenant_mtx:
                    over = ts.depth + n > budget
                if over:
                    return self._shed(
                        ts, klass_name, "tenant_budget", n,
                        f"tenant {ts.label} over budget ({budget} lanes)",
                        t0, kind_name, depth,
                    )
            elif (
                level >= LEVEL_SHRINK_SHARES
                and req.klass == CLASS_CONSENSUS
            ):
                # shrink_shares rung: consensus past the tenant's
                # shrunken dispatch share rides the host oracle instead
                # of the device — never shed, never silently dropped
                with self._tenant_mtx:
                    over = ts.depth + n > budget
                if over:
                    return self._host_direct(req, ts, t0, kind_name, level)

            shed = self.admission.admit(req.klass, n, depth)
            if shed is not None:
                return self._shed(
                    ts, klass_name, shed, n,
                    f"{klass_name} load shed ({shed}, {depth} pending)",
                    t0, kind_name, depth,
                )

            # enqueue: the wire deadline (minus a respond margin) becomes
            # the lane's flush_by so the scheduler flushes early instead
            # of letting the deadline lapse inside the accumulator
            flush_by = None
            if deadline_s:
                margin = max(0.001, 0.2 * deadline_s)
                flush_by = t0 + max(0.0, deadline_s - margin)
            if tag is None:
                tag = threading.get_ident()
            try:
                with tracing.span(
                    "verifyd_enqueue", lanes=n, klass=klass_name,
                    tenant=ts.label,
                ):
                    # submit_many is atomic against max_pending: the
                    # group lands whole or not at all, even while the
                    # continuous dispatcher is draining concurrently
                    entries = sched.submit_many(
                        list(zip(req.pks, req.msgs, req.sigs)),
                        priority=req.klass,
                        flush_by=flush_by,
                        tag=tag,
                        tenant=ts.label,
                        # inside the enqueue span the current context IS
                        # the enqueue span (deepest linkage); when tracing
                        # is off locally, propagate the client's context
                        # so coalesced waiters still link in the merge
                        trace=tracing.current_context() or ctx,
                    )
            except SchedulerSaturatedError as exc:
                return self._shed(
                    ts, klass_name, "saturated", n,
                    str(exc), t0, kind_name, sched.pending_depth(),
                )
            t_submit = time.monotonic()
            self._track_depth(req.klass, n)
            self._tenant_admit(ts, n)
            self.metrics.lanes.labels(klass=klass_name).inc(n)
            if on_entries is not None:
                on_entries(entries)

            try:
                verdicts: List[bool] = []
                with tracing.span("verifyd_wait", lanes=n):
                    for entry in entries:
                        if deadline_s:
                            left = deadline_s - (time.monotonic() - t0)
                            if left <= 0 or not entry.done.wait(timeout=left):
                                with self._stats_mtx:
                                    self.deadline_expired += 1
                                return self._respond(
                                    STATUS_DEADLINE_EXCEEDED,
                                    [],
                                    f"deadline ({req.deadline_ms}ms) expired"
                                    " awaiting flush",
                                    t0,
                                    kind_name,
                                    sched.pending_depth(),
                                    tenant_label=ts.label,
                                )
                            verdicts.append(entry.ok)
                        else:
                            verdicts.append(
                                sched.wait(entry, timeout=DEFAULT_WAIT)
                            )
            finally:
                self._track_depth(req.klass, -n)
                self._tenant_release(ts, n)
            # latency attribution: the stage vector tiles the full
            # server wall t0 -> now with REAL span boundaries, so the
            # client can see where its round trip went (any gap between
            # the client's observed wall and this sum is transport).
            disp = [e.t_dispatch for e in entries if e.t_dispatch > 0.0]
            fin = [e.t_done for e in entries if e.t_done > 0.0]
            t_disp = min(disp) if disp else t_submit
            t_fin = max(fin) if fin else t_disp
            now = time.monotonic()
            stages = {
                "wire_wait": t_entry - t0,
                "admission": t_submit - t_entry,
                "batch_residency": t_disp - t_submit,
                "device": t_fin - t_disp,
                "collect": now - t_fin,
            }
            # the SLO sketch eats the same wall the stage vector tiles
            self._tenant_observe_latency(ts, now - t0, now)
            return self._respond(
                STATUS_OK, verdicts, "", t0, kind_name,
                sched.pending_depth(), tenant_label=ts.label,
                stages=stages,
            )
        except Exception as exc:  # never tear the stream on a handler bug
            return self._respond(
                STATUS_INTERNAL, [], repr(exc), t0, kind_name
            )
