"""Deterministic structured wire fuzzer — the runtime half of tpuflow.

The TPT taint checker (``scripts/analysis/taint.py``) proves statically
that every wire-derived length/bound is guarded before it reaches a
sink; this harness proves the same property dynamically. A seeded
mutator (bit flips, varint boundary values, truncations, length-field
inflation, duplicate/unknown fields) runs over a checked-in corpus of
valid frames for all four decode surfaces:

- **protocol** — ``decode_request`` / ``decode_response`` (TCP framing)
- **shm**      — ``unpack_header`` (doorbell slab headers)
- **grpc**     — ``grpc_unframe`` / ``HpackDecoder.decode`` /
  ``_strip_padding`` (the pure HTTP/2 parsers)
- **rpc**      — ``RPCServer._post_body`` (JSON-RPC envelope)

Every mutated frame must yield a clean *typed* error (the surface's
declared exception) or a correct decode — never a hang, never an
uncaught ``struct.error``/``IndexError``/``MemoryError``, and never a
silently-accepted wrong decode: any accepted frame is re-encoded and
re-decoded, and the two decodes must agree (canonical round-trip).

Everything is derived from ``random.Random(seed)``, so a failing seed
replays byte-identically:

    python tests/fuzz_wire.py --seed 7
    python tests/fuzz_wire.py --seed 7 --surface grpc --verbose

The corpus lives in ``tests/fuzz_corpus/`` and is checked in;
``--regen`` rewrites it from the builders below (the pytest corpus
tests fail if the two drift apart).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import sys
import time
from typing import Callable, Dict, List, Tuple

if __name__ == "__main__":  # CLI use: make the repo importable
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.libs import grpc as grpclib
from tendermint_tpu.libs.grpc import (
    FLAG_PADDED,
    GrpcError,
    H2ProtocolError,
    HpackDecoder,
    grpc_frame,
    grpc_unframe,
    hpack_encode,
)
from tendermint_tpu.verifyd import protocol, shm

CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fuzz_corpus")

# exceptions that are NEVER acceptable, no matter how they surface —
# the exact classes the taint checker's sinks exist to prevent
_FORBIDDEN = (MemoryError, RecursionError, SystemError)

# soft hang detector: any single decode this slow on a <=4 KiB frame
# means an attacker-controlled bound made it into a loop
_HANG_BUDGET_S = 5.0

_VARINT_BOUNDARIES = (
    0, 1, 127, 128, 2**31 - 1, 2**31, 2**63 - 1, 2**63, 2**64 - 1
)


class FuzzFailure(AssertionError):
    """One mutated frame violated the harness contract. Carries enough
    context to replay: seed, surface, parser, corpus index, frame hex."""

    def __init__(self, message: str, *, seed: int, parser: str,
                 index: int, frame: bytes):
        super().__init__(
            f"{message}\n  replay: python tests/fuzz_wire.py --seed {seed}"
            f"\n  parser={parser} corpus_index={index}"
            f"\n  frame={frame[:256].hex()}{'...' if len(frame) > 256 else ''}"
        )
        self.seed = seed
        self.parser = parser
        self.index = index
        self.frame = frame


def _encode_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


# --- corpus builders ---------------------------------------------------------
#
# One function per parser, returning the valid frames mutations start
# from. Checked-in copies live in tests/fuzz_corpus/<parser>.<i>.bin;
# test_corpus_matches_builders keeps them in sync.


def _corpus_request() -> List[bytes]:
    lane = lambda i: (  # noqa: E731 - local shorthand
        bytes([i]) * protocol.PUBKEY_SIZE,
        b"msg-%d" % i,
        bytes([0x80 | i]) * protocol.SIG_SIZE,
    )
    minimal = protocol.VerifyRequest()
    one = protocol.VerifyRequest(
        pks=[lane(1)[0]], msgs=[lane(1)[1]], sigs=[lane(1)[2]]
    )
    full = protocol.VerifyRequest(
        kind=protocol.KIND_COMMIT,
        klass=protocol.CLASS_CONSENSUS,
        deadline_ms=1500,
        algo=protocol.ALGO_SR25519,
        pks=[lane(i)[0] for i in range(3)],
        msgs=[lane(i)[1] for i in range(3)],
        sigs=[lane(i)[2] for i in range(3)],
        tenant="fuzz-tenant",
        trace=b"\x01" * 17,
        slo_ms=250,
        shard_id=7,
        route_epoch=42,
    )
    return [protocol.encode_request(r) for r in (minimal, one, full)]


def _corpus_response() -> List[bytes]:
    ok = protocol.VerifyResponse(verdicts=[True, False, True])
    err = protocol.VerifyResponse(
        status=protocol.STATUS_RESOURCE_EXHAUSTED,
        message="shed: queue full",
        queue_depth=17,
        shard_id=3,
    )
    staged = protocol.VerifyResponse(
        verdicts=[True],
        stages=protocol.pack_stages(
            {name: 0.25 for name in protocol.STAGE_NAMES}
        ),
    )
    return [protocol.encode_response(r) for r in (ok, err, staged)]


def _corpus_slab_header() -> List[bytes]:
    frames = []
    for kwargs in (
        dict(gen=2, kind=protocol.KIND_RAW, klass=protocol.CLASS_RPC,
             deadline_ms=0, algo=protocol.ALGO_ED25519, lanes=1),
        dict(gen=44, kind=protocol.KIND_COMMIT,
             klass=protocol.CLASS_CONSENSUS, deadline_ms=900,
             algo=protocol.ALGO_SR25519, lanes=64, tenant="fuzz-tenant",
             trace=b"\x02" * 17, slo_ms=100, shard_id=2, route_epoch=9),
    ):
        buf = bytearray(shm.SLAB_HEADER_BYTES)
        shm.pack_header(buf, 0, **kwargs)
        frames.append(bytes(buf))
    return frames


def _corpus_grpc_message() -> List[bytes]:
    return [
        grpc_frame(b""),
        grpc_frame(b"verify-payload"),
        grpc_frame(b"\x00" * 64),
    ]


def _corpus_hpack_block() -> List[bytes]:
    return [
        hpack_encode([(":method", "POST"), (":path", "/verifyd.Verify")]),
        hpack_encode([
            (":status", "200"),
            ("content-type", "application/grpc"),
            ("grpc-status", "0"),
        ]),
    ]


def _corpus_padded_frame() -> List[bytes]:
    # _strip_padding input: Pad Length byte + data + padding
    return [
        bytes([4]) + b"payload" + b"\x00" * 4,
        bytes([0]) + b"no-padding",
    ]


def _corpus_jsonrpc() -> List[bytes]:
    single = {"jsonrpc": "2.0", "id": 1, "method": "echo",
              "params": {"x": 1}}
    batch = [
        {"jsonrpc": "2.0", "id": 2, "method": "echo", "params": {}},
        {"jsonrpc": "2.0", "id": 3, "method": "missing", "params": {}},
    ]
    notification = {"jsonrpc": "2.0", "method": "echo", "params": {}}
    return [json.dumps(v).encode() for v in (single, batch, notification)]


_CORPUS_BUILDERS: Dict[str, Callable[[], List[bytes]]] = {
    "request": _corpus_request,
    "response": _corpus_response,
    "slab_header": _corpus_slab_header,
    "grpc_message": _corpus_grpc_message,
    "hpack_block": _corpus_hpack_block,
    "padded_frame": _corpus_padded_frame,
    "jsonrpc": _corpus_jsonrpc,
}

SURFACES: Dict[str, Tuple[str, ...]] = {
    "protocol": ("request", "response"),
    "shm": ("slab_header",),
    "grpc": ("grpc_message", "hpack_block", "padded_frame"),
    "rpc": ("jsonrpc",),
}


def corpus_files() -> List[Tuple[str, bytes]]:
    """(relative filename, frame bytes) for the whole checked-in corpus."""
    out = []
    for parser, builder in sorted(_CORPUS_BUILDERS.items()):
        for i, frame in enumerate(builder()):
            out.append((f"{parser}.{i}.bin", frame))
    return out


def load_corpus(parser: str) -> List[bytes]:
    """The checked-in frames for one parser, falling back to the
    builders when the corpus directory is absent (fresh checkout)."""
    frames = []
    if os.path.isdir(CORPUS_DIR):
        for name in sorted(os.listdir(CORPUS_DIR)):
            if name.startswith(parser + ".") and name.endswith(".bin"):
                with open(os.path.join(CORPUS_DIR, name), "rb") as fh:
                    frames.append(fh.read())
    return frames or _CORPUS_BUILDERS[parser]()


# --- structured mutator ------------------------------------------------------


class Mutator:
    """Seeded structured mutations; every choice flows from one
    ``random.Random(seed)`` so a seed fully determines the run."""

    def __init__(self, rng: random.Random):
        self.rng = rng
        self._ops = (
            self._bit_flip,
            self._byte_set,
            self._truncate,
            self._extend,
            self._varint_boundary,
            self._inflate_len32,
            self._duplicate_slice,
            self._unknown_field,
        )

    def mutate(self, frame: bytes) -> bytes:
        data = bytearray(frame)
        for _ in range(self.rng.randint(1, 3)):
            data = self.rng.choice(self._ops)(data)
        return bytes(data)

    def _bit_flip(self, data: bytearray) -> bytearray:
        if data:
            for _ in range(self.rng.randint(1, 8)):
                i = self.rng.randrange(len(data))
                data[i] ^= 1 << self.rng.randrange(8)
        return data

    def _byte_set(self, data: bytearray) -> bytearray:
        if data:
            i = self.rng.randrange(len(data))
            data[i] = self.rng.randrange(256)
        return data

    def _truncate(self, data: bytearray) -> bytearray:
        if data:
            del data[self.rng.randrange(len(data)):]
        return data

    def _extend(self, data: bytearray) -> bytearray:
        data += bytes(
            self.rng.randrange(256)
            for _ in range(self.rng.randint(1, 16))
        )
        return data

    def _varint_boundary(self, data: bytearray) -> bytearray:
        """Splice an encoded varint boundary value (0, 1, 2^31, 2^63,
        2^64-1, ...) over a random window — the length-field abuse the
        TPT001/TPT002 sinks exist for."""
        enc = _encode_varint(self.rng.choice(_VARINT_BOUNDARIES))
        pos = self.rng.randrange(len(data) + 1)
        data[pos:pos + len(enc)] = enc
        return data

    def _inflate_len32(self, data: bytearray) -> bytearray:
        """Overwrite a 4-byte window with a huge big-endian length —
        targets the fixed-width length prefixes (gRPC framing, slab
        u32 fields)."""
        if len(data) >= 4:
            pos = self.rng.randrange(len(data) - 3)
            data[pos:pos + 4] = self.rng.choice(
                (0xFFFFFFFF, 0x7FFFFFFF, 1 << 20, (1 << 20) + 1)
            ).to_bytes(4, "big")
        return data

    def _duplicate_slice(self, data: bytearray) -> bytearray:
        if data:
            a = self.rng.randrange(len(data))
            b = self.rng.randrange(a, min(len(data), a + 64) + 1)
            data[a:a] = data[a:b]
        return data

    def _unknown_field(self, data: bytearray) -> bytearray:
        """Append a well-formed proto field with an unassigned number —
        decoders must skip it, not choke."""
        fld = self.rng.randint(11, 30)
        if self.rng.random() < 0.5:
            data += _encode_varint(fld << 3) + _encode_varint(
                self.rng.choice(_VARINT_BOUNDARIES)
            )
        else:
            payload = bytes(self.rng.randrange(256)
                            for _ in range(self.rng.randint(0, 8)))
            data += _encode_varint((fld << 3) | 2)
            data += _encode_varint(len(payload)) + payload
        return data


# --- per-parser drivers ------------------------------------------------------
#
# Each driver: mutated frame -> outcome string. Typed rejections come
# back as "err:<Class>"; accepted frames are round-tripped and come
# back as "ok:<sha256 of the canonical re-encode>". Anything else
# raises FuzzViolation (wrapped into FuzzFailure by the runner).


class FuzzViolation(Exception):
    pass


def _drive_request(data: bytes) -> str:
    try:
        req = protocol.decode_request(data)
    except ValueError as exc:
        return f"err:ValueError:{type(exc.__cause__).__name__}"
    canon = protocol.encode_request(req)
    if protocol.decode_request(canon) != req:
        raise FuzzViolation("request round-trip mismatch (silent wrong decode)")
    return "ok:" + hashlib.sha256(canon).hexdigest()


def _drive_response(data: bytes) -> str:
    try:
        resp = protocol.decode_response(data)
    except ValueError as exc:
        return f"err:ValueError:{type(exc.__cause__).__name__}"
    canon = protocol.encode_response(resp)
    if protocol.decode_response(canon) != resp:
        raise FuzzViolation("response round-trip mismatch (silent wrong decode)")
    return "ok:" + hashlib.sha256(canon).hexdigest()


def _drive_slab_header(data: bytes) -> str:
    try:
        hdr = shm.unpack_header(bytearray(data), 0)
    except ValueError:
        return "err:ValueError"
    if len(hdr["tenant"].encode("utf-8")) > protocol.MAX_TENANT_LEN:
        # hostile tenant bytes decode via 'replace' into a string whose
        # re-encoding outgrows the fixed slab field; the decode itself
        # was faithful, it just has no canonical re-encoding
        return "ok:unencodable:" + hashlib.sha256(
            repr(hdr).encode()
        ).hexdigest()
    buf = bytearray(shm.SLAB_HEADER_BYTES)
    shm.pack_header(buf, 0, **hdr)
    if shm.unpack_header(buf, 0) != hdr:
        raise FuzzViolation("slab header round-trip mismatch")
    return "ok:" + hashlib.sha256(bytes(buf)).hexdigest()


def _drive_grpc_message(data: bytes) -> str:
    try:
        payload = grpc_unframe(data)
    except GrpcError:
        return "err:GrpcError"
    if grpc_unframe(grpc_frame(payload)) != payload:
        raise FuzzViolation("gRPC message round-trip mismatch")
    return "ok:" + hashlib.sha256(payload).hexdigest()


def _drive_hpack_block(data: bytes) -> str:
    try:
        headers = HpackDecoder().decode(data)
    except H2ProtocolError:
        return "err:H2ProtocolError"
    try:
        canon = hpack_encode(headers)
    except UnicodeEncodeError:
        # surrogateescape preserved undecodable bytes faithfully; the
        # decode was correct, it just has no clean re-encoding
        return "ok:unencodable:" + hashlib.sha256(
            repr(headers).encode("utf-8", "surrogateescape")
        ).hexdigest()
    if HpackDecoder().decode(canon) != headers:
        raise FuzzViolation("HPACK round-trip mismatch")
    return "ok:" + hashlib.sha256(canon).hexdigest()


def _drive_padded_frame(data: bytes) -> str:
    try:
        payload = grpclib._strip_padding(FLAG_PADDED, data)
    except H2ProtocolError:
        return "err:H2ProtocolError"
    # re-wrap with the padding the parser said it stripped
    pad = data[0]
    canon = bytes([pad]) + payload + b"\x00" * pad
    if grpclib._strip_padding(FLAG_PADDED, canon) != payload:
        raise FuzzViolation("padding round-trip mismatch")
    return "ok:" + hashlib.sha256(payload).hexdigest()


_RPC_SERVER = None


def _rpc_server():
    global _RPC_SERVER
    if _RPC_SERVER is None:
        from tendermint_tpu.rpc.server import RPCServer

        _RPC_SERVER = RPCServer(
            {"echo": lambda **params: params}, evloop=False
        )
    return _RPC_SERVER


def _drive_jsonrpc(data: bytes) -> str:
    # _post_body must never raise: every malformed body becomes a
    # JSON-RPC error envelope
    out = _rpc_server()._post_body(data)
    try:
        env = json.loads(out)
    except ValueError as exc:
        raise FuzzViolation(f"non-JSON RPC response: {exc}") from exc
    for item in env if isinstance(env, list) else [env]:
        if not isinstance(item, dict) or item.get("jsonrpc") != "2.0":
            raise FuzzViolation(f"malformed RPC envelope: {item!r}")
        if "result" not in item and "error" not in item:
            raise FuzzViolation(f"RPC envelope lacks result/error: {item!r}")
    return "ok:" + hashlib.sha256(out).hexdigest()


_DRIVERS: Dict[str, Callable[[bytes], str]] = {
    "request": _drive_request,
    "response": _drive_response,
    "slab_header": _drive_slab_header,
    "grpc_message": _drive_grpc_message,
    "hpack_block": _drive_hpack_block,
    "padded_frame": _drive_padded_frame,
    "jsonrpc": _drive_jsonrpc,
}


# --- runner ------------------------------------------------------------------


def fuzz_parser(parser: str, seed: int, iterations: int) -> List[str]:
    """Fuzz one parser; returns the per-case outcome log (used for the
    byte-identical replay check). Raises FuzzFailure on any violation."""
    rng = random.Random(f"{parser}:{seed}")
    mut = Mutator(rng)
    drive = _DRIVERS[parser]
    corpus = load_corpus(parser)
    log = []
    for i, frame in enumerate(corpus):
        # the pristine frame must always be accepted
        base = drive(frame)
        if not base.startswith("ok:"):
            raise FuzzFailure(
                f"corpus frame rejected: {base}",
                seed=seed, parser=parser, index=i, frame=frame,
            )
        log.append(f"{parser}.{i}.base {base}")
        for case in range(iterations):
            frame_m = mut.mutate(frame)
            start = time.monotonic()
            try:
                outcome = drive(frame_m)
            except FuzzViolation as exc:
                raise FuzzFailure(
                    str(exc), seed=seed, parser=parser, index=i,
                    frame=frame_m,
                ) from exc
            except _FORBIDDEN as exc:
                raise FuzzFailure(
                    f"forbidden {type(exc).__name__}: {exc}",
                    seed=seed, parser=parser, index=i, frame=frame_m,
                ) from exc
            except Exception as exc:
                raise FuzzFailure(
                    f"uncaught {type(exc).__name__}: {exc}",
                    seed=seed, parser=parser, index=i, frame=frame_m,
                ) from exc
            elapsed = time.monotonic() - start
            if elapsed > _HANG_BUDGET_S:
                raise FuzzFailure(
                    f"hang: one decode took {elapsed:.1f}s",
                    seed=seed, parser=parser, index=i, frame=frame_m,
                )
            log.append(f"{parser}.{i}.{case} {outcome}")
    return log


def fuzz_run(seed: int, iterations: int, surfaces=None) -> Tuple[str, int]:
    """Fuzz every parser of the requested surfaces. Returns (sha256
    digest of the full outcome log, number of cases)."""
    names = surfaces or sorted(SURFACES)
    log: List[str] = []
    for surface in names:
        for parser in SURFACES[surface]:
            log.extend(fuzz_parser(parser, seed, iterations))
    blob = "\n".join(log).encode()
    return hashlib.sha256(blob).hexdigest(), len(log)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--iters", type=int, default=300,
                    help="mutations per corpus frame (default 300)")
    ap.add_argument("--surface", choices=sorted(SURFACES), default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke: fewer iterations per frame")
    ap.add_argument("--regen", action="store_true",
                    help="rewrite tests/fuzz_corpus/ from the builders")
    args = ap.parse_args(argv)

    if args.regen:
        os.makedirs(CORPUS_DIR, exist_ok=True)
        for name, frame in corpus_files():
            with open(os.path.join(CORPUS_DIR, name), "wb") as fh:
                fh.write(frame)
            print(f"wrote fuzz_corpus/{name} ({len(frame)}B)")
        return 0

    iters = 60 if args.smoke else args.iters
    surfaces = [args.surface] if args.surface else None
    try:
        digest, cases = fuzz_run(args.seed, iters, surfaces)
    except FuzzFailure as exc:
        print(f"FUZZ FAILURE (seed={args.seed}):\n{exc}", file=sys.stderr)
        return 1
    print(f"fuzz_wire: seed={args.seed} cases={cases} digest={digest}")
    return 0


# --- pytest integration ------------------------------------------------------


def test_corpus_matches_builders():
    """The checked-in corpus must equal what the builders produce —
    corpus drift would silently shrink fuzz coverage."""
    for name, frame in corpus_files():
        path = os.path.join(CORPUS_DIR, name)
        assert os.path.exists(path), (
            f"missing corpus file {name}; run "
            "`python tests/fuzz_wire.py --regen`"
        )
        with open(path, "rb") as fh:
            assert fh.read() == frame, (
                f"corpus file {name} drifted from its builder; run "
                "`python tests/fuzz_wire.py --regen`"
            )


def test_corpus_round_trips():
    """Every checked-in frame decodes cleanly and round-trips on every
    surface (the 'base' case the mutator starts from)."""
    for surface, parsers in sorted(SURFACES.items()):
        for parser in parsers:
            drive = _DRIVERS[parser]
            for i, frame in enumerate(load_corpus(parser)):
                outcome = drive(frame)
                assert outcome.startswith("ok:"), (
                    f"{surface}/{parser} corpus frame {i} rejected: "
                    f"{outcome}"
                )


def test_fuzz_all_surfaces_seed0():
    digest, cases = fuzz_run(seed=0, iterations=40)
    assert cases > 0 and len(digest) == 64


def test_fuzz_all_surfaces_seed1():
    digest, cases = fuzz_run(seed=1, iterations=40)
    assert cases > 0 and len(digest) == 64


def test_same_seed_replay_is_byte_identical():
    first, n1 = fuzz_run(seed=7, iterations=25)
    second, n2 = fuzz_run(seed=7, iterations=25)
    assert (first, n1) == (second, n2)


def test_different_seeds_mutate_differently():
    a, _ = fuzz_run(seed=2, iterations=25, surfaces=["protocol"])
    b, _ = fuzz_run(seed=3, iterations=25, surfaces=["protocol"])
    assert a != b


if __name__ == "__main__":
    sys.exit(main())
