"""Proposer-based timestamp (PBTS) tests — pbts_test.go analog.

Exercises the timeliness predicate and the prevote decision directly:
an untimely proposal (timestamp too far in the future, or too old
relative to receipt) draws a nil prevote from honest validators; timely
ones are prevoted; the per-round relaxation eventually accepts any
timestamp; and a proposal whose header time disagrees with the proposal
timestamp is rejected outright.
"""

import time

import pytest

from tendermint_tpu.consensus import cstypes
from tendermint_tpu.consensus.cstypes import RoundStep
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PREVOTE,
    Timestamp,
)
from tendermint_tpu.types.block import BlockID, PartSetHeader, Proposal
from tendermint_tpu.types.params import SynchronyParams
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.block import BLOCK_PART_SIZE_BYTES

from tests.test_consensus import BASE_NS, CHAIN_ID, build_validator

NS = 1_000_000_000


def _stage_proposal(cs, ts_ns: int, recv_ns: int, round_: int = 0):
    """Put a complete proposal+block into the round state with the given
    proposal timestamp and receive time (the way gossip ingestion would)."""
    rs = cs.rs
    block = cs.block_exec.create_proposal_block(
        rs.height, cs.state, cs.rs.last_commit.make_extended_commit()
        if rs.last_commit is not None
        else __import__(
            "tendermint_tpu.types", fromlist=["ExtendedCommit"]
        ).ExtendedCommit(),
        cs.state.validators.get_proposer().address,
    )
    block.header.time = Timestamp.from_unix_ns(ts_ns)
    block._hash = None
    parts = PartSet.from_data(block.to_proto_bytes(), BLOCK_PART_SIZE_BYTES)
    proposal = Proposal(
        height=rs.height,
        round=round_,
        pol_round=-1,
        block_id=BlockID(block.hash(), parts.header()),
        timestamp=Timestamp.from_unix_ns(ts_ns),
    )
    rs.round = round_
    rs.step = RoundStep.PROPOSE
    rs.proposal = proposal
    rs.proposal_receive_time = Timestamp.from_unix_ns(recv_ns)
    rs.proposal_block = block
    rs.proposal_block_parts = parts
    return block


def _prevote_cast(cs):
    """Run the prevote decision; return the block hash prevoted (b'' = nil)."""
    votes = []
    orig = cs._sign_add_vote

    def capture(type_, block_hash, psh):
        votes.append((type_, block_hash))

    cs._sign_add_vote = capture
    try:
        cs._do_prevote(cs.rs.height, cs.rs.round)
    finally:
        cs._sign_add_vote = orig
    assert votes and votes[0][0] == SIGNED_MSG_TYPE_PREVOTE
    return votes[0][1]


@pytest.fixture()
def validator(tmp_path):
    cs, privs, app = build_validator(tmp_path)
    # deterministic clock for the kernel of these tests
    sp = cs.state.consensus_params.synchrony
    assert sp.precision > 0 and sp.message_delay > 0
    yield cs
    cs.stop()


class TestTimelinessPredicate:
    def test_exact_receipt_is_timely(self, validator):
        cs = validator
        now = time.time_ns()
        _stage_proposal(cs, ts_ns=now, recv_ns=now)
        assert cs._proposal_is_timely()

    def test_future_timestamp_untimely(self, validator):
        cs = validator
        sp = cs.state.consensus_params.synchrony
        now = time.time_ns()
        # proposal claims a time more than PRECISION ahead of receipt
        ahead = int(sp.precision * NS) + 200_000_000
        _stage_proposal(cs, ts_ns=now + ahead, recv_ns=now)
        assert not cs._proposal_is_timely()

    def test_stale_timestamp_untimely(self, validator):
        cs = validator
        sp = cs.state.consensus_params.synchrony
        now = time.time_ns()
        behind = int((sp.precision + sp.message_delay) * NS) + 200_000_000
        _stage_proposal(cs, ts_ns=now - behind, recv_ns=now)
        assert not cs._proposal_is_timely()

    def test_round_relaxation_eventually_accepts(self, validator):
        """params.go SynchronyParams.InRound: message_delay grows per
        round so a lagging proposer's timestamp is eventually timely."""
        cs = validator
        sp = cs.state.consensus_params.synchrony
        now = time.time_ns()
        behind = int((sp.precision + sp.message_delay) * NS) + 500_000_000
        for round_ in range(0, 60):
            _stage_proposal(cs, ts_ns=now - behind, recv_ns=now, round_=round_)
            if cs._proposal_is_timely():
                assert round_ > 0, "round 0 must reject this stale proposal"
                return
        pytest.fail("relaxation never accepted the proposal")


class TestPrevoteDecision:
    def test_timely_proposal_prevoted(self, validator):
        cs = validator
        now = time.time_ns()
        block = _stage_proposal(cs, ts_ns=now, recv_ns=now)
        assert _prevote_cast(cs) == block.hash()

    def test_untimely_proposal_gets_nil_prevote(self, validator):
        cs = validator
        sp = cs.state.consensus_params.synchrony
        now = time.time_ns()
        ahead = int(sp.precision * NS) + 500_000_000
        _stage_proposal(cs, ts_ns=now + ahead, recv_ns=now)
        assert _prevote_cast(cs) == b""

    def test_header_time_mismatch_gets_nil_prevote(self, validator):
        """A proposer whose block header time differs from the proposal
        timestamp is lying about one of them; prevote nil
        (state.go defaultDoPrevote timestamp equality check)."""
        cs = validator
        now = time.time_ns()
        _stage_proposal(cs, ts_ns=now, recv_ns=now)
        # desync header time from proposal timestamp
        cs.rs.proposal_block.header.time = Timestamp.from_unix_ns(now + NS)
        cs.rs.proposal_block._hash = None
        assert _prevote_cast(cs) == b""

    def test_locked_validator_ignores_timeliness(self, validator):
        """PBTS only gates FRESH proposals (pol_round == -1, nothing
        locked): a validator already locked on the block re-prevotes it
        even if the receive time looks stale (state.go:1512-1560)."""
        cs = validator
        sp = cs.state.consensus_params.synchrony
        now = time.time_ns()
        behind = int((sp.precision + sp.message_delay) * NS) + 500_000_000
        block = _stage_proposal(cs, ts_ns=now - behind, recv_ns=now)
        cs.rs.locked_round = 0
        cs.rs.locked_block = block
        cs.rs.locked_block_parts = cs.rs.proposal_block_parts
        cs.rs.round = 1
        cs.rs.proposal.round = 1
        assert _prevote_cast(cs) == block.hash()
