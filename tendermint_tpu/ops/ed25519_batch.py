"""Batched Ed25519 ZIP-215 verification on TPU.

The device kernel verifies, for each lane i, the cofactored equation

    [8]([s_i]B - R_i - [k_i]A_i) == identity

with a shared-doubling (Straus) double-scalar multiplication: 64
4-bit windows, per-window additions from a constant basepoint table and
a per-lane table of [0..15](-A_i). All lanes execute the same 64-step
loop, so the computation is pure SIMD over the batch — the TPU analog
of the reference's CPU multi-scalar batch verify
(crypto/ed25519/ed25519.go:198-233, types/validation.go:154).

Host side does what is cheap and sequential: SHA-512 challenge hashing,
scalar reduction mod L, byte -> limb/window unpacking (vectorized
numpy), and the s < L canonicity check. The device does all curve
arithmetic. Compiled kernels are cached per padded batch-size bucket.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops import curve, field
from tendermint_tpu.ops.tables import B_TABLE

L = 2**252 + 27742317777372353535851937790883648493

NWINDOWS = 64  # 256 bits / 4


# --- device kernel ----------------------------------------------------------


def _select_from_const_table(digit: jnp.ndarray, table: jnp.ndarray) -> curve.Point:
    """digit: (N,) int32 in [0,16); table: (16, 4, 20, 1) constant.
    Constant-time one-hot selection (no gather: stays on the VPU)."""
    onehot = (jnp.arange(16, dtype=jnp.int32)[:, None] == digit[None, :]).astype(
        jnp.int32
    )  # (16, N)
    sel = jnp.einsum("tn,tcl->cln", onehot, table[:, :, :, 0])
    return (sel[0], sel[1], sel[2], sel[3])


def _select_from_lane_table(digit: jnp.ndarray, table: jnp.ndarray) -> curve.Point:
    """digit: (N,); table: (16, 4, 20, N) per-lane table."""
    onehot = (jnp.arange(16, dtype=jnp.int32)[:, None] == digit[None, :]).astype(
        jnp.int32
    )
    sel = (onehot[:, None, None, :] * table).sum(axis=0)
    return (sel[0], sel[1], sel[2], sel[3])


def _build_lane_table(p: curve.Point) -> jnp.ndarray:
    """(16, 4, 20, N): [0..15]p via chained complete additions (lax.scan
    keeps the traced graph to a single pt_add)."""
    n = p[0].shape[1]
    p_stacked = jnp.stack(p)

    def step(acc, _):
        nxt = jnp.stack(
            curve.pt_add((acc[0], acc[1], acc[2], acc[3]), p)
        )
        return nxt, nxt

    _, rows = jax.lax.scan(step, p_stacked, None, length=14)
    return jnp.concatenate(
        [jnp.stack(curve.pt_identity(n))[None], p_stacked[None], rows], axis=0
    )


def verify_kernel(
    a_y: jnp.ndarray,
    a_sign: jnp.ndarray,
    r_y: jnp.ndarray,
    r_sign: jnp.ndarray,
    s_win: jnp.ndarray,
    k_win: jnp.ndarray,
) -> jnp.ndarray:
    """(20,N),(N,),(20,N),(N,),(64,N),(64,N) -> (N,) bool."""
    # Decompress A and R as one 2N batch: halves the decompression HLO and
    # doubles its SIMD width.
    both_pt, both_ok = curve.pt_decompress(
        jnp.concatenate([a_y, r_y], axis=1),
        jnp.concatenate([a_sign, r_sign], axis=0),
    )
    nn = a_y.shape[1]
    a_pt = tuple(c[:, :nn] for c in both_pt)
    r_pt = tuple(c[:, nn:] for c in both_pt)
    a_ok, r_ok = both_ok[:nn], both_ok[nn:]
    neg_a = curve.pt_neg(a_pt)
    a_table = _build_lane_table(neg_a)
    b_table = jnp.asarray(B_TABLE)

    n = a_y.shape[1]
    init = tuple(jnp.stack(curve.pt_identity(n)))

    def body(i, acc_stacked):
        acc = (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])
        for _ in range(4):
            acc = curve.pt_double(acc)
        sd = jax.lax.dynamic_index_in_dim(s_win, i, keepdims=False)
        kd = jax.lax.dynamic_index_in_dim(k_win, i, keepdims=False)
        acc = curve.pt_add(acc, _select_from_const_table(sd, b_table))
        acc = curve.pt_add(acc, _select_from_lane_table(kd, a_table))
        return jnp.stack(acc)

    acc_stacked = jax.lax.fori_loop(0, NWINDOWS, body, jnp.stack(init))
    acc = (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])
    # [s]B - [k]A computed; subtract R, multiply by cofactor 8, test identity.
    acc = curve.pt_add(acc, curve.pt_neg(r_pt))
    for _ in range(3):
        acc = curve.pt_double(acc)
    return curve.pt_is_identity(acc) & a_ok & r_ok


def _enable_persistent_cache() -> None:
    """First compilation of the verifier is expensive; persist it across
    processes (driver, tests, bench) in a repo-local cache dir."""
    import os

    cache_dir = os.environ.get(
        "TENDERMINT_TPU_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), ".jax_cache"),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover - cache is best-effort
        pass


_enable_persistent_cache()


@lru_cache(maxsize=16)
def _compiled_kernel(n: int, backend: Optional[str]):
    return jax.jit(verify_kernel, backend=backend)


# --- host-side preparation --------------------------------------------------

_BIT_WEIGHTS = (1 << np.arange(field.RADIX_BITS, dtype=np.int64)).astype(np.int32)


def _bytes_to_y_sign(raw: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(N, 32) uint8 little-endian encodings -> ((20, N) y limbs, (N,) sign).

    The y value is NOT reduced mod p: ZIP-215 liberal decompression
    accepts y in [p, 2^255) and every device op treats limbs as a loosely
    reduced representative, so bit-slicing is sufficient.
    """
    bits = np.unpackbits(raw, axis=1, bitorder="little")  # (N, 256)
    sign = bits[:, 255].astype(np.int32)
    ybits = bits[:, :255]
    limbs = np.zeros((field.NLIMBS, raw.shape[0]), dtype=np.int32)
    for i in range(field.NLIMBS):
        chunk = ybits[:, i * 13 : (i + 1) * 13]  # last limb: 8 bits
        limbs[i] = chunk.astype(np.int32) @ _BIT_WEIGHTS[: chunk.shape[1]]
    return limbs, sign


def _scalars_to_windows(raw: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 little-endian scalars -> (64, N) 4-bit digits,
    most-significant window first (matches the MSB-first Straus loop)."""
    lo = (raw & 0x0F).astype(np.int32)
    hi = (raw >> 4).astype(np.int32)
    digits = np.empty((raw.shape[0], 64), dtype=np.int32)
    digits[:, 0::2] = lo
    digits[:, 1::2] = hi
    return digits[:, ::-1].T.copy()  # MSB window first, (64, N)


_BUCKETS = [8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192]


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + 8191) // 8192) * 8192


# A known-good padding triple so padded lanes verify true and never mask
# real failures (they are sliced off anyway).
def _make_pad_entry() -> Tuple[bytes, bytes, bytes]:
    from tendermint_tpu.crypto import ed25519_ref as ref

    priv, pub = ref.keypair_from_seed(b"\x42" * 32)
    msg = b"tendermint-tpu-pad"
    return pub, msg, ref.sign(priv, msg)


_PAD_PK, _PAD_MSG, _PAD_SIG = _make_pad_entry()


def prepare_batch(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    pad_to: Optional[int] = None,
) -> Tuple[dict, np.ndarray]:
    """Host prep: hash challenges, unpack limbs/windows, pad to bucket.

    Returns (device inputs dict, host_ok (N,) bool of structural checks:
    lengths and s < L canonicity)."""
    n = len(pubkeys)
    host_ok = np.ones(n, dtype=bool)
    pk_arr = np.zeros((n, 32), dtype=np.uint8)
    r_arr = np.zeros((n, 32), dtype=np.uint8)
    s_arr = np.zeros((n, 32), dtype=np.uint8)
    k_arr = np.zeros((n, 32), dtype=np.uint8)
    for i, (pk, msg, sig) in enumerate(zip(pubkeys, msgs, sigs)):
        if len(pk) != 32 or len(sig) != 64:
            host_ok[i] = False
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= L:  # non-canonical s: reject (ZIP-215 keeps this check)
            host_ok[i] = False
            continue
        k = int.from_bytes(hashlib.sha512(sig[:32] + pk + msg).digest(), "little") % L
        pk_arr[i] = np.frombuffer(pk, dtype=np.uint8)
        r_arr[i] = np.frombuffer(sig[:32], dtype=np.uint8)
        s_arr[i] = np.frombuffer(sig[32:], dtype=np.uint8)
        k_arr[i] = np.frombuffer(k.to_bytes(32, "little"), dtype=np.uint8)

    m = pad_to if pad_to is not None else _bucket(n)
    if m > n:
        pad_pk = np.frombuffer(_PAD_PK, dtype=np.uint8)
        pad_r = np.frombuffer(_PAD_SIG[:32], dtype=np.uint8)
        pad_s = np.frombuffer(_PAD_SIG[32:], dtype=np.uint8)
        pad_k = int.from_bytes(
            hashlib.sha512(_PAD_SIG[:32] + _PAD_PK + _PAD_MSG).digest(), "little"
        ) % L
        pad_kb = np.frombuffer(pad_k.to_bytes(32, "little"), dtype=np.uint8)
        pk_arr = np.concatenate([pk_arr, np.tile(pad_pk, (m - n, 1))])
        r_arr = np.concatenate([r_arr, np.tile(pad_r, (m - n, 1))])
        s_arr = np.concatenate([s_arr, np.tile(pad_s, (m - n, 1))])
        k_arr = np.concatenate([k_arr, np.tile(pad_kb, (m - n, 1))])

    a_y, a_sign = _bytes_to_y_sign(pk_arr)
    r_y, r_sign = _bytes_to_y_sign(r_arr)
    inputs = dict(
        a_y=a_y,
        a_sign=a_sign,
        r_y=r_y,
        r_sign=r_sign,
        s_win=_scalars_to_windows(s_arr),
        k_win=_scalars_to_windows(k_arr),
    )
    return inputs, host_ok


def verify_batch(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    backend: Optional[str] = None,
) -> List[bool]:
    """Batch ZIP-215 verification; returns per-entry validity.

    The entry point behind crypto.Ed25519BatchVerifier — reference
    contract crypto/crypto.go:58-76 / crypto/ed25519/ed25519.go:198-233.
    """
    n = len(pubkeys)
    if n == 0:
        return []
    inputs, host_ok = prepare_batch(pubkeys, msgs, sigs)
    fn = _compiled_kernel(inputs["a_y"].shape[1], backend)
    device_ok = np.asarray(
        fn(
            jnp.asarray(inputs["a_y"]),
            jnp.asarray(inputs["a_sign"]),
            jnp.asarray(inputs["r_y"]),
            jnp.asarray(inputs["r_sign"]),
            jnp.asarray(inputs["s_win"]),
            jnp.asarray(inputs["k_win"]),
        )
    )[:n]
    return list(np.logical_and(device_ok, host_ok))
