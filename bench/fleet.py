"""Spawned verifyd shard fleets for the ``verifyd_fleet`` bench section.

Shards must be real OS processes, not threads: the section's claims —
aggregate sigs/s scaling with shard count, per-shard resident tables
staying flat — are exactly the properties the GIL and the
process-singleton resident store would fake in-process. Each child runs
one ``VerifydServer`` with a MODELED verifier (a fixed sleep per lane;
the bytes are never read) and the server's REAL hot-key pin path, so
the pinned slice each shard reports over STATS_PATH is genuine
``ops.resident`` accounting, not bench bookkeeping.

``shard_main`` is module-level so the spawn context can pickle it. The
child reports its bound address through a Pipe and blocks until the
parent sends stop (or the Pipe hits EOF with the parent — no orphans).
A mid-run ``ShardFleet.kill`` is SIGKILL, the same abrupt death the
chaos suite models: no graceful drain, in-flight connections reset.
"""

from __future__ import annotations

import multiprocessing
import time
from typing import Dict, List, Optional


def shard_main(shard_id: int, conn, lane_us: int) -> None:
    """Child entry: one verifyd shard process serving until told stop."""
    from tendermint_tpu.ops import introspect
    from tendermint_tpu.verifyd.server import VerifydServer

    def modeled(pks, msgs, sigs):
        time.sleep(lane_us * 1e-6 * len(pks))
        return [True] * len(pks)

    introspect.set_shard_identity(shard_id)
    srv = VerifydServer(
        verify_fn=modeled,
        max_batch=512,
        max_delay=0.001,
        admission_cap=8192,
        max_pending=8192,
        shard_id=shard_id,
        shm="off",
    )
    srv.start()
    host, port = srv.address
    try:
        conn.send("%s:%d" % (host, port))
        try:
            conn.recv()  # any message (or parent death) = stop
        except EOFError:
            pass
    finally:
        srv.stop()


class ShardFleet:
    """Launch/kill/stop a set of shard child processes (bench harness).

    ``addrs[i]`` is shard i's listen address in launch order — the same
    order the parent's FederationClient numbers its shards, so a
    ``kill(sid)`` here is exactly the federation's shard ``sid``.
    """

    def __init__(self, lane_us: int):
        self.lane_us = lane_us
        self._ctx = multiprocessing.get_context("spawn")
        self._procs: List[Optional[object]] = []
        self._conns: List[Optional[object]] = []
        self.addrs: List[str] = []

    def launch(self, n_shards: int, startup_timeout: float = 60.0) -> List[str]:
        for sid in range(n_shards):
            parent_conn, child_conn = self._ctx.Pipe()
            proc = self._ctx.Process(
                target=shard_main,
                args=(sid, child_conn, self.lane_us),
                daemon=True,
            )
            proc.start()
            child_conn.close()
            self._procs.append(proc)
            self._conns.append(parent_conn)
        deadline = time.monotonic() + startup_timeout
        for sid, conn in enumerate(self._conns):
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not conn.poll(remaining):
                self.stop_all()
                raise RuntimeError("shard %d failed to start" % sid)
            self.addrs.append(conn.recv())
        return list(self.addrs)

    def kill(self, sid: int) -> None:
        """SIGKILL a shard: abrupt death, in-flight connections reset."""
        proc = self._procs[sid]
        if proc is not None:
            proc.kill()
            proc.join(timeout=10)
            self._procs[sid] = None
        conn = self._conns[sid]
        if conn is not None:
            conn.close()
            self._conns[sid] = None

    def alive(self) -> Dict[int, bool]:
        return {
            sid: (p is not None and p.is_alive())
            for sid, p in enumerate(self._procs)
        }

    def stop_all(self) -> None:
        for conn in self._conns:
            if conn is None:
                continue
            try:
                conn.send("stop")
            except (OSError, BrokenPipeError):
                pass  # child already gone; the join below reaps it
        for proc in self._procs:
            if proc is None:
                continue
            proc.join(timeout=10)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=10)
        for conn in self._conns:
            if conn is not None:
                conn.close()
        self._procs = []
        self._conns = []
        self.addrs = []
