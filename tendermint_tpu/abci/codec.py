"""Wire codec for the ABCI socket transport.

The reference frames varint-delimited protobuf Request/Response unions
(abci/client/socket_client.go:417, abci/server/socket_server.go:317).
Here frames are 4-byte big-endian length + a JSON document
`{"type": <method>, "body": {...}}`; message bodies are encoded by
dataclass reflection (bytes as base64, nested dataclasses recursively,
`object`-typed params fields via an override table). Same transport
semantics — ordered request/response streams per connection with
`flush` — with a self-describing encoding in place of generated protos.
"""

from __future__ import annotations

import base64
import json
import struct
import typing
from dataclasses import fields, is_dataclass
from typing import Any, Optional, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.types.params import ConsensusParams, ConsensusParamsUpdate

FRAME_HDR = struct.Struct(">I")
MAX_FRAME = 64 << 20

# method name -> (request class, response class); echo/flush are special.
METHODS = {
    "info": (abci.RequestInfo, abci.ResponseInfo),
    "query": (abci.RequestQuery, abci.ResponseQuery),
    "check_tx": (abci.RequestCheckTx, abci.ResponseCheckTx),
    "init_chain": (abci.RequestInitChain, abci.ResponseInitChain),
    "prepare_proposal": (abci.RequestPrepareProposal, abci.ResponsePrepareProposal),
    "process_proposal": (abci.RequestProcessProposal, abci.ResponseProcessProposal),
    "extend_vote": (abci.RequestExtendVote, abci.ResponseExtendVote),
    "verify_vote_extension": (
        abci.RequestVerifyVoteExtension,
        abci.ResponseVerifyVoteExtension,
    ),
    "finalize_block": (abci.RequestFinalizeBlock, abci.ResponseFinalizeBlock),
    "commit": (type(None), abci.ResponseCommit),
    "list_snapshots": (abci.RequestListSnapshots, abci.ResponseListSnapshots),
    "offer_snapshot": (abci.RequestOfferSnapshot, abci.ResponseOfferSnapshot),
    "load_snapshot_chunk": (
        abci.RequestLoadSnapshotChunk,
        abci.ResponseLoadSnapshotChunk,
    ),
    "apply_snapshot_chunk": (
        abci.RequestApplySnapshotChunk,
        abci.ResponseApplySnapshotChunk,
    ),
}

# (class, field) -> concrete type for fields hinted `object` in types.py.
_FIELD_OVERRIDES = {
    (abci.RequestInitChain, "consensus_params"): ConsensusParams,
    (abci.ResponseInitChain, "consensus_params"): ConsensusParams,
    (abci.ResponsePrepareProposal, "consensus_param_updates"): ConsensusParamsUpdate,
    (abci.ResponseFinalizeBlock, "consensus_param_updates"): ConsensusParamsUpdate,
}


def encode_obj(v: Any) -> Any:
    if isinstance(v, bytes):
        return {"__b": base64.b64encode(v).decode()}
    if is_dataclass(v) and not isinstance(v, type):
        return {f.name: encode_obj(getattr(v, f.name)) for f in fields(v)}
    if isinstance(v, (list, tuple)):
        return [encode_obj(x) for x in v]
    return v


def _resolve_hint(cls, name: str, hint: Any) -> Any:
    override = _FIELD_OVERRIDES.get((cls, name))
    if override is not None:
        return override
    return hint


def decode_obj(tp: Any, v: Any) -> Any:
    if v is None:
        return None
    origin = typing.get_origin(tp)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return decode_obj(args[0], v) if args else v
    if tp is bytes:
        return base64.b64decode(v["__b"]) if isinstance(v, dict) else b""
    if origin in (list, tuple):
        (arg,) = typing.get_args(tp) or (Any,)
        return [decode_obj(arg, x) for x in v]
    if isinstance(tp, type) and is_dataclass(tp):
        hints = typing.get_type_hints(tp)
        kwargs = {}
        for f in fields(tp):
            if f.name not in v:
                continue
            kwargs[f.name] = decode_obj(
                _resolve_hint(tp, f.name, hints.get(f.name, Any)), v[f.name]
            )
        return tp(**kwargs)
    if isinstance(v, dict) and "__b" in v:
        return base64.b64decode(v["__b"])
    return v


def encode_frame(kind: str, type_: str, body: Any) -> bytes:
    doc = json.dumps({"kind": kind, "type": type_, "body": encode_obj(body)})
    raw = doc.encode()
    if len(raw) > MAX_FRAME:
        raise ValueError("abci frame too large")
    return FRAME_HDR.pack(len(raw)) + raw


def decode_frame(raw: bytes) -> Tuple[str, str, Any]:
    doc = json.loads(raw.decode())
    return doc["kind"], doc["type"], doc.get("body")


def read_frame(sock) -> Optional[bytes]:
    hdr = _read_exact(sock, FRAME_HDR.size)
    if hdr is None:
        return None
    (n,) = FRAME_HDR.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError("abci frame too large")
    return _read_exact(sock, n)


def _read_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
