"""Batched Ed25519 ZIP-215 verification on TPU (f32 limb engine).

The device kernel verifies, for each lane i, the cofactored equation

    [8]([s_i]B - R_i - [k_i]A_i) == identity

with a shared-doubling (Straus) double-scalar multiplication: 64
*signed* 4-bit windows (digits in [-8, 8)), per-window additions from a
constant Niels basepoint table of [1..8]B (7-mul mixed adds plus a
conditional negation at select) and a per-lane table of [1..8](-A_i).
Signed windows halve both the per-lane table build (7 chained adds
instead of 14) and the broadcast-select bandwidth of the window loop —
the per-window memory hot spot. All lanes execute the same 64-step
loop, so the computation is pure SIMD over the batch — the TPU analog
of the reference's CPU multi-scalar batch verify
(crypto/ed25519/ed25519.go:198-233, types/validation.go:154).

Two kernel entry points: :func:`verify_kernel` decompresses A and
builds the lane tables on device; :func:`verify_kernel_tables` accepts
a gathered ``(8, 4, 32, N)`` table input from the validator-set-aware
precompute cache (ops/precompute.py) and skips both. verify_batch
partitions lanes between them, consults the digest-keyed result cache
first, and double-buffers chunk dispatch (host prep of chunk i+1
overlaps the kernel of chunk i).

Layout is transfer-minimal: the host uploads only the raw 32-byte
strings (A, R, S, and the SHA-512 challenge k reduced mod L) as uint8;
limb conversion, sign-bit stripping, and 4-bit windowing all happen on
device, where radix 2^8 f32 limbs make a 32-byte string its own limb
vector (see :mod:`field32`). Host work is the SHA-512 challenge hash
(batched in the C extension when available), the s < L canonicity
check (vectorized byte compare), and padding.

Large batches are split into fixed-size chunks whose kernel calls are
enqueued back-to-back: JAX's async dispatch overlaps each chunk's H2D
transfer with the previous chunk's compute.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto.hashing import L, sha512_batch_mod_l
from tendermint_tpu.libs import tracing
from tendermint_tpu.ops import curve32 as curve, field32 as field

_L_BYTES_BE = np.frombuffer(L.to_bytes(32, "big"), dtype=np.uint8)

NWINDOWS = 64  # 256 bits / 4

# Chunk size for pipelined dispatch; also the largest compiled kernel.
CHUNK = 4096
_BUCKETS = [64, 256, 1024, CHUNK]


# --- constant basepoint table (host precompute, Niels form) -----------------


def _build_b_niels_table(width: int = 8) -> np.ndarray:
    """(width, 3, 32) f32: [1..width]B as (Y+X, Y-X, 2dT), Z=1.

    Signed windows select |digit| from the positive multiples and
    negate at select time; digit 0 is an identity fixup, so no row is
    spent on it.
    """
    from tendermint_tpu.crypto import ed25519_ref as ref

    out = np.zeros((width, 3, field.NLIMBS), dtype=np.float32)
    p_mod = field.P

    def affine(pt):
        x_, y_, z_, _ = pt
        zinv = pow(z_, p_mod - 2, p_mod)
        return (x_ * zinv % p_mod, y_ * zinv % p_mod)

    acc = ref.B_POINT
    for i in range(width):
        if i:
            acc = ref.pt_add(acc, ref.B_POINT)
        x, y = affine(acc)
        out[i, 0] = field.int_to_limbs((y + x) % p_mod)
        out[i, 1] = field.int_to_limbs((y - x) % p_mod)
        out[i, 2] = field.int_to_limbs(2 * field.D * x * y % p_mod)
    return out


B_NIELS = _build_b_niels_table()


# --- device kernel ----------------------------------------------------------


def _bytes_to_fe(raw: jnp.ndarray) -> jnp.ndarray:
    """(N, 32) uint8 -> (32, N) f32 limbs (radix 2^8 == raw bytes)."""
    return raw.astype(jnp.float32).T


def _strip_sign(y: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(32, N) limbs with bit 255 set-or-not -> (limbs, sign (N,))."""
    sign = jnp.floor(y[31] * (1.0 / 128.0))
    y = y.at[31].add(-128.0 * sign)
    return y, sign


def _to_windows(raw: jnp.ndarray) -> jnp.ndarray:
    """(N, 32) uint8 scalars (LE) -> (64, N) f32 4-bit digits, MSB first.

    Unsigned digit split; the window loop itself runs on the signed
    recode (:func:`_to_windows_signed`) — this stays as the layout
    primitive and documentation of the MSB-first interleave.
    """
    b = raw.astype(jnp.float32).T  # (32, N)
    hi = jnp.floor(b * (1.0 / 16.0))
    lo = b - 16.0 * hi
    # MSB-first interleave: hi[31], lo[31], hi[30], ...
    return jnp.stack([hi[::-1], lo[::-1]], axis=1).reshape(2 * field.NLIMBS, -1)


def _to_windows_signed(raw: jnp.ndarray) -> jnp.ndarray:
    """(N, 32) uint8 scalars (LE) -> (64, N) f32 signed digits in [-8, 8).

    Recoding: z = x + 0x88...88 (add 136 to every byte, ripple the
    carries), then digit_i = window_i(z) - 8, so x = sum d_i 16^i with
    every d_i in [-8, 7] — no carry chain inside the window loop. Exact
    for x < 2^253 (both s and the reduced challenge k are < L < 2^253);
    a non-canonical s >= 2^253 drops its carry-out and yields a
    wrong-but-well-defined verdict that the host-side s < L check
    already rejects. All intermediates stay exact in f32 (<= 392).
    """
    b = raw.astype(jnp.float32).T  # (32, N)
    carry = jnp.zeros_like(b[0])
    z = []
    for i in range(field.NLIMBS):  # 32-step ripple, unrolled at trace
        t = b[i] + 136.0 + carry
        carry = jnp.floor(t * (1.0 / 256.0))
        z.append(t - 256.0 * carry)
    zb = jnp.stack(z)  # (32, N), carry-out dropped
    hi = jnp.floor(zb * (1.0 / 16.0))
    lo = zb - 16.0 * hi
    win = jnp.stack([hi[::-1], lo[::-1]], axis=1).reshape(
        2 * field.NLIMBS, -1
    )
    return win - 8.0


def _select_b_niels(digit: jnp.ndarray, table: jnp.ndarray) -> curve.NielsPoint:
    """digit: (N,) f32 in [-8, 8); table: (8, 3, 32) const [1..8]B.

    One-hot on |digit| against half the rows of the unsigned scheme,
    identity fixup for digit 0 (Niels identity is (1, 1, 0): add the
    miss mask into limb 0), conditional negation for digit < 0.
    """
    absd = jnp.abs(digit)
    onehot = (
        jnp.arange(1.0, 9.0, dtype=jnp.float32)[:, None] == absd[None, :]
    ).astype(jnp.float32)  # (8, N)
    sel = jnp.einsum("tn,tcl->cln", onehot, table)
    miss = (absd == 0.0).astype(jnp.float32)
    yplusx = sel[0].at[0].add(miss)
    yminusx = sel[1].at[0].add(miss)
    return curve.niels_cneg(digit < 0.0, (yplusx, yminusx, sel[2]))


def _select_lane_cached(digit: jnp.ndarray, table: jnp.ndarray) -> curve.CachedPoint:
    """digit: (N,) in [-8, 8); table: (8, 4, 32, N) cached [1..8]p.

    The broadcast select over the per-lane table is the window loop's
    memory hot spot — signed digits halve the rows it reads. Cached
    identity is (1, 1, 1, 0), restored via the digit-0 fixup.
    """
    absd = jnp.abs(digit)
    onehot = (
        jnp.arange(1.0, 9.0, dtype=jnp.float32)[:, None] == absd[None, :]
    ).astype(jnp.float32)
    sel = (onehot[:, None, None, :] * table).sum(axis=0)
    miss = (absd == 0.0).astype(jnp.float32)
    yplusx = sel[0].at[0].add(miss)
    yminusx = sel[1].at[0].add(miss)
    z = sel[2].at[0].add(miss)
    return curve.cached_cneg(digit < 0.0, (yplusx, yminusx, z, sel[3]))


TABLE_WIDTH = 8  # rows of the per-lane signed-window table: [1..8](-A)


def _build_lane_table(p: curve.Point) -> jnp.ndarray:
    """(8, 4, 32, N) cached-form table of [1..8]p.

    Chained complete additions build the extended multiples (lax.scan
    keeps the traced graph to one pt_add); the conversion to cached form
    (Y+X, Y-X, Z, 2dT) batches the 2d pre-scale of all 8 entries into a
    single wide multiply so the window loop's adds need none. Signed
    windows spend no rows on 0 or the negative multiples, halving the
    14-add chain of the unsigned scheme.
    """
    n = p[0].shape[1]
    w = TABLE_WIDTH
    cached_p = curve.pt_to_cached(p)
    p_stacked = jnp.stack(p)

    def step(acc, _):
        nxt = jnp.stack(
            curve.pt_add_cached((acc[0], acc[1], acc[2], acc[3]), cached_p)
        )
        return nxt, nxt

    _, rows = jax.lax.scan(step, p_stacked, None, length=w - 1)
    ext = jnp.concatenate([p_stacked[None], rows], axis=0)
    # (8, 4, 32, N) extended
    x, y, z, t = ext[:, 0], ext[:, 1], ext[:, 2], ext[:, 3]
    # one wide 2d*T multiply across all 8 entries (lanes folded in)
    t_flat = t.transpose(1, 0, 2).reshape(field.NLIMBS, w * n)
    td2 = field.fe_mul_const(t_flat, field.D2_FE).reshape(field.NLIMBS, w, n)
    td2 = td2.transpose(1, 0, 2)
    yplusx = field.fe_add(
        y.transpose(1, 0, 2).reshape(field.NLIMBS, w * n),
        x.transpose(1, 0, 2).reshape(field.NLIMBS, w * n),
    ).reshape(field.NLIMBS, w, n).transpose(1, 0, 2)
    yminusx = field.fe_sub(
        y.transpose(1, 0, 2).reshape(field.NLIMBS, w * n),
        x.transpose(1, 0, 2).reshape(field.NLIMBS, w * n),
    ).reshape(field.NLIMBS, w, n).transpose(1, 0, 2)
    return jnp.stack([yplusx, yminusx, z, td2], axis=1)


def _dbl_step(_, acc_stacked):
    return jnp.stack(
        curve.pt_double(
            (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])
        )
    )


def _straus_core(
    a_table: jnp.ndarray, s_win: jnp.ndarray, k_win: jnp.ndarray
) -> curve.Point:
    """64-step shared-doubling window loop over a prebuilt lane table.

    a_table: (8, 4, 32, N) cached-form [1..8](-A) — either built on
    device (:func:`straus_sb_minus_ka`) or gathered from the host-side
    precompute cache (:func:`verify_kernel_tables`).
    """
    nn = a_table.shape[3]
    b_table = jnp.asarray(B_NIELS)
    init = jnp.stack(curve.pt_identity(nn))

    def body(i, acc_stacked):
        acc_stacked = jax.lax.fori_loop(0, 4, _dbl_step, acc_stacked)
        acc = (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])
        sd = jax.lax.dynamic_index_in_dim(s_win, i, keepdims=False)
        kd = jax.lax.dynamic_index_in_dim(k_win, i, keepdims=False)
        acc = curve.pt_madd(acc, _select_b_niels(sd, b_table))
        acc = curve.pt_add_cached(acc, _select_lane_cached(kd, a_table))
        return jnp.stack(acc)

    acc_stacked = jax.lax.fori_loop(0, NWINDOWS, body, init)
    return (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])


def straus_sb_minus_ka(
    a_pt: curve.Point, s_win: jnp.ndarray, k_win: jnp.ndarray
) -> curve.Point:
    """Shared-doubling double-scalar core: [s]B - [k]A per lane.

    The same 64-step window loop serves both signature schemes on this
    curve — ed25519 (below) and the schnorrkel/ristretto verifier
    (ops/sr25519_batch.py): their verification equations are both
    instances of [s]B - [k]A - R == identity-class. s_win/k_win are
    signed digits from :func:`_to_windows_signed`.
    """
    neg_a = curve.pt_neg(a_pt)
    return _straus_core(_build_lane_table(neg_a), s_win, k_win)


def _finish_verify(
    acc: curve.Point, r_pt: curve.Point, ok: jnp.ndarray
) -> jnp.ndarray:
    """[s]B - [k]A computed; subtract R, multiply by cofactor 8, test
    identity, mask structurally-invalid lanes."""
    acc = curve.pt_add(acc, curve.pt_neg(r_pt))
    acc_stacked = jax.lax.fori_loop(0, 3, _dbl_step, jnp.stack(acc))
    acc = (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])
    return curve.pt_is_identity(acc) & ok


def verify_kernel(
    pk_bytes: jnp.ndarray,
    r_bytes: jnp.ndarray,
    s_bytes: jnp.ndarray,
    k_bytes: jnp.ndarray,
) -> jnp.ndarray:
    """(N,32)x4 uint8 -> (N,) bool."""
    a_y, a_sign = _strip_sign(_bytes_to_fe(pk_bytes))
    r_y, r_sign = _strip_sign(_bytes_to_fe(r_bytes))
    s_win = _to_windows_signed(s_bytes)
    k_win = _to_windows_signed(k_bytes)

    # Decompress A and R as one 2N batch: halves the decompression HLO
    # and doubles its SIMD width.
    nn = a_y.shape[1]
    both_pt, both_ok = curve.pt_decompress(
        jnp.concatenate([a_y, r_y], axis=1),
        jnp.concatenate([a_sign, r_sign], axis=0),
    )
    a_pt = tuple(c[:, :nn] for c in both_pt)
    r_pt = tuple(c[:, nn:] for c in both_pt)
    a_ok, r_ok = both_ok[:nn], both_ok[nn:]

    acc = straus_sb_minus_ka(a_pt, s_win, k_win)
    return _finish_verify(acc, r_pt, a_ok & r_ok)


def verify_kernel_tables(
    a_table: jnp.ndarray,
    a_ok: jnp.ndarray,
    r_bytes: jnp.ndarray,
    s_bytes: jnp.ndarray,
    k_bytes: jnp.ndarray,
) -> jnp.ndarray:
    """Cache-hit entry point: the lane tables arrive prebuilt.

    a_table: (8, 4, 32, N) uint8 — gathered [1..8](-A) cached-form
    columns from ops/precompute.py (canonical limbs, so uint8 on the
    wire: 1/4 the H2D bytes of f32). a_ok: (N,) uint8 decompression
    verdicts from the same cache. Skips pt_decompress-of-A and
    _build_lane_table entirely; only R is decompressed on device.
    """
    r_y, r_sign = _strip_sign(_bytes_to_fe(r_bytes))
    s_win = _to_windows_signed(s_bytes)
    k_win = _to_windows_signed(k_bytes)
    r_pt, r_ok = curve.pt_decompress(r_y, r_sign)
    acc = _straus_core(a_table.astype(jnp.float32), s_win, k_win)
    return _finish_verify(acc, r_pt, (a_ok != 0) & r_ok)


def verify_kernel_resident(
    tab_store: jnp.ndarray,
    idx: jnp.ndarray,
    a_ok: jnp.ndarray,
    r_bytes: jnp.ndarray,
    s_bytes: jnp.ndarray,
    k_bytes: jnp.ndarray,
) -> jnp.ndarray:
    """Device-resident entry point: tables stay on device across calls.

    tab_store: (8, 4, 32, K) uint8 — the resident store's device tensor
    (ops/resident.py), uploaded once per validator-set activation.
    idx: (N,) int32 per-lane column indices into it. The gather runs on
    device, so steady-state batches ship 4 bytes per lane where the
    gathered path ships ~1 KiB. Under the mesh the store is replicated
    and ``idx`` lane-sharded, so the take is device-local and the
    gathered table tensor comes out lane-sharded exactly like
    :func:`verify_kernel_tables` always saw it.
    """
    tab = jnp.take(tab_store, idx, axis=3)
    return verify_kernel_tables(tab, a_ok, r_bytes, s_bytes, k_bytes)


def _enable_persistent_cache() -> None:
    """First compilation of the verifier is expensive; persist it across
    processes (driver, tests, bench) in a repo-local cache dir."""
    import os

    cache_dir = os.environ.get(
        "TENDERMINT_TPU_JAX_CACHE",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))), ".jax_cache"
        ),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover - cache is best-effort
        pass


_enable_persistent_cache()


@lru_cache(maxsize=16)
def _compiled_kernel(n: int, backend: Optional[str], mul_impl: str = "vpu"):
    """One compiled verifier per (padded size, backend, field-mul impl).

    The field-mul impl ("vpu" f32 shifts vs "mxu" int8 dot_general —
    see ops/field_mxu.py) is a trace-time switch on field32, so it is
    pinned here around the trace — under field32's trace lock, so
    concurrent first compilations can't interleave their set/restore —
    and must be part of the cache key.
    """

    def run(pk, r, s, k):
        with field.pinned_mul_impl(mul_impl):
            return verify_kernel(pk, r, s, k)

    from tendermint_tpu.ops import introspect

    return introspect.traced_first_call(
        jax.jit(run, backend=backend), "ed25519", "verify", n
    )


@lru_cache(maxsize=16)
def _compiled_kernel_tables(n: int, backend: Optional[str], mul_impl: str = "vpu"):
    """Compiled table-input verifier (cache-hit lanes); same keying
    rules as :func:`_compiled_kernel`."""

    def run(tab, ok, r, s, k):
        with field.pinned_mul_impl(mul_impl):
            return verify_kernel_tables(tab, ok, r, s, k)

    from tendermint_tpu.ops import introspect

    return introspect.traced_first_call(
        jax.jit(run, backend=backend), "ed25519", "verify_tables", n
    )


@lru_cache(maxsize=16)
def _compiled_kernel_resident(n: int, backend: Optional[str], mul_impl: str = "vpu"):
    """Compiled resident-store verifier; jit re-traces per store width K
    internally, the lru key pins (lane count, backend, mul impl)."""

    def run(tab_store, idx, ok, r, s, k):
        with field.pinned_mul_impl(mul_impl):
            return verify_kernel_resident(tab_store, idx, ok, r, s, k)

    from tendermint_tpu.ops import introspect

    return introspect.traced_first_call(
        jax.jit(run, backend=backend), "ed25519", "verify_resident", n
    )


# --- implementation dispatch (XLA graph vs Pallas kernel) -------------------
#
# The Pallas kernel (ops/pallas_verify.py) keeps every field-op
# intermediate in VMEM; the XLA graph materializes them to HBM. On TPU
# backends the Pallas path is the default; CPU stays on the XLA graph
# (Pallas interpret mode is a test vehicle, far too slow for real
# batches). TENDERMINT_TPU_VERIFY_IMPL=pallas|xla|mxu|auto overrides;
# "mxu" is the XLA graph with field multiplies as int8 dot_general
# contractions (ops/field_mxu.py) instead of f32 VPU shifts.

_IMPL_ENV = "TENDERMINT_TPU_VERIFY_IMPL"
_PALLAS_BROKEN = False  # sticky per-process fallback after a failure
# Device-vs-host fallback state lives in ops/device_policy.py, shared
# with the sr25519 engine so a broken backend is broken once.


def _platform(backend: Optional[str]) -> str:
    try:
        if backend:
            return jax.local_devices(backend=backend)[0].platform
        return jax.default_backend()
    except Exception:
        return "unknown"


def active_impl(backend: Optional[str] = None) -> str:
    """Which verifier implementation verify_batch will dispatch to."""
    import os

    mode = os.environ.get(_IMPL_ENV, "auto").lower()
    if mode == "mxu":
        return "mxu"
    if mode == "xla" or _PALLAS_BROKEN:
        return "xla"
    if mode == "pallas":
        return "pallas"
    return "pallas" if _platform(backend) in ("tpu", "axon") else "xla"


def _mul_impl_for_chunk(impl: str, backend: Optional[str], lanes: int) -> str:
    """Field-mul impl for one padded chunk: the explicit ``mxu`` verify
    impl forces the contraction; otherwise the autotuner's measured
    winner for (platform, bucket) — which degrades to the plain
    ``field32.get_mul_impl()`` default whenever the tuner is off,
    overridden by env, or cannot time this backend."""
    if impl == "mxu":
        return "mxu"
    from tendermint_tpu.ops import autotune

    return autotune.mul_impl_for(backend, lanes)


def _run_chunk(inputs: dict, backend: Optional[str], plan=None):
    """Dispatch one padded legacy chunk, preferring Pallas on TPU.

    Returns ``(result, plan_used)``: ``plan_used`` is the (possibly
    degraded) mesh plan when the chunk went out lane-sharded, else
    None. With a plan, a mesh that loses all usable devices falls
    through to the single-device dispatch below — never to host."""
    global _PALLAS_BROKEN
    from tendermint_tpu.ops import fault_injection

    # TENDERMINT_TPU_VERIFY_IMPL=mxu forces the int8 contraction; the
    # autotuned (or field-level default) impl is honored otherwise.
    impl = active_impl(backend)
    mul_impl = _mul_impl_for_chunk(impl, backend, inputs["pk"].shape[0])
    if plan is not None:
        from tendermint_tpu.parallel import sharding as mesh_sharding

        try:
            return mesh_sharding.run_chunk_mesh(
                "ed25519", inputs, mul_impl, plan, "ed25519.chunk"
            )
        except mesh_sharding.MeshUnavailableError:
            # Every device excluded: degrade to THIS backend's single-
            # device dispatch below; host fallback stays with the caller.
            pass
    fault_injection.fire("ed25519.chunk")
    args = (
        jnp.asarray(inputs["pk"]),
        jnp.asarray(inputs["r"]),
        jnp.asarray(inputs["s"]),
        jnp.asarray(inputs["k"]),
    )
    m = inputs["pk"].shape[0]
    if impl == "pallas":
        try:
            from tendermint_tpu.ops import pallas_verify

            return pallas_verify.compiled_verify(m)(*args), None
        except Exception as exc:  # compile/runtime failure -> XLA graph
            _PALLAS_BROKEN = True
            import warnings

            warnings.warn(
                f"pallas verifier failed ({exc!r}); falling back to XLA graph"
            )
    return _compiled_kernel(m, backend, mul_impl)(*args), None


def _run_chunk_tables(inputs: dict, backend: Optional[str], plan=None):
    """Dispatch one padded cache-hit chunk through the table kernel.
    Same ``(result, plan_used)`` contract as :func:`_run_chunk`."""
    global _PALLAS_BROKEN
    from tendermint_tpu.ops import fault_injection

    impl = active_impl(backend)
    mul_impl = _mul_impl_for_chunk(impl, backend, inputs["r"].shape[0])
    if plan is not None:
        from tendermint_tpu.parallel import sharding as mesh_sharding

        try:
            return mesh_sharding.run_chunk_mesh(
                "tables", inputs, mul_impl, plan, "ed25519.chunk"
            )
        except mesh_sharding.MeshUnavailableError:
            # Every device excluded: single-device path, not host.
            pass
    fault_injection.fire("ed25519.chunk")
    args = (
        jnp.asarray(inputs["tab"]),
        jnp.asarray(inputs["ok"]),
        jnp.asarray(inputs["r"]),
        jnp.asarray(inputs["s"]),
        jnp.asarray(inputs["k"]),
    )
    m = inputs["r"].shape[0]
    if impl == "pallas":
        try:
            from tendermint_tpu.ops import pallas_verify

            return pallas_verify.compiled_verify_tables(m)(*args), None
        except Exception as exc:  # compile/runtime failure -> XLA graph
            _PALLAS_BROKEN = True
            import warnings

            warnings.warn(
                f"pallas table verifier failed ({exc!r}); falling back to XLA graph"
            )
    return _compiled_kernel_tables(m, backend, mul_impl)(*args), None


def _run_chunk_resident(inputs: dict, backend: Optional[str], plan=None):
    """Dispatch one padded resident-store chunk: only gather indices
    ship per batch, the table tensor already lives on device. Same
    ``(result, plan_used)`` contract as :func:`_run_chunk`.

    The store tensor is committed to the context it was uploaded for
    (one mesh, or one single device). When that context is gone —
    mesh degraded mid-batch, or run_chunk_mesh gave up — the chunk
    falls back to the gathered-table kernel: the store's columns are
    pulled to host, gathered per lane, and shipped the old way (rare,
    and still device compute).
    """
    from tendermint_tpu.ops import fault_injection, resident

    impl = active_impl(backend)
    mul_impl = _mul_impl_for_chunk(impl, backend, inputs["r"].shape[0])
    mesh_ok = plan is not None and inputs.get("mesh_key") == tuple(
        plan.device_ids
    )
    if mesh_ok:
        from tendermint_tpu.parallel import sharding as mesh_sharding

        try:
            return mesh_sharding.run_chunk_mesh(
                "resident", inputs, mul_impl, plan, "ed25519.chunk"
            )
        except mesh_sharding.MeshUnavailableError:
            # The store is committed to the dead mesh; gathered-table
            # fallback below re-ships this chunk's columns explicitly.
            pass
    fault_injection.fire("ed25519.chunk")
    m = inputs["r"].shape[0]
    if plan is None and inputs.get("mesh_key") is None:
        args = (
            inputs["store"],
            jnp.asarray(inputs["idx"]),
            jnp.asarray(inputs["ok"]),
            jnp.asarray(inputs["r"]),
            jnp.asarray(inputs["s"]),
            jnp.asarray(inputs["k"]),
        )
        return _compiled_kernel_resident(m, backend, mul_impl)(*args), None
    # Context mismatch: materialize the needed columns and take the
    # gathered-table kernel (counted as real per-batch table H2D).
    tab_host = np.asarray(inputs["store"])
    tab = np.ascontiguousarray(tab_host[:, :, :, np.asarray(inputs["idx"])])
    resident.note_table_h2d(tab.nbytes)
    ginputs = dict(
        tab=tab, ok=inputs["ok"], r=inputs["r"], s=inputs["s"], k=inputs["k"]
    )
    return _run_chunk_tables(ginputs, backend, None)


# --- host-side preparation --------------------------------------------------


def _bucket(n: int) -> int:
    """Padded size for n lanes: next bucket, or the next CHUNK multiple
    above CHUNK (large batches are dispatched CHUNK at a time)."""
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + CHUNK - 1) // CHUNK) * CHUNK


def _mesh_bucket(n: int, n_dev: int) -> int:
    """Padded size for n lanes sharded over n_dev devices: the
    per-device slab stays in the bucket table so the sharded compile
    cache hits (512 lanes on 8 devices -> 64-lane slabs -> 512)."""
    return _bucket(max(1, -(-n // n_dev))) * n_dev


def _mesh_plan(lanes: int):
    """A mesh plan (parallel/mesh.MeshPlan) when the sharded path
    should serve this batch, else None. Any trouble building one —
    parallel package unavailable, no backend — means 'unsharded',
    never a verification error."""
    try:
        from tendermint_tpu.parallel import mesh as mesh_mod

        return mesh_mod.plan_for_lanes(lanes)
    except Exception:  # sharding is an optimization; never block verify
        return None


def _mesh_on_success(plan) -> None:
    try:
        from tendermint_tpu.parallel import mesh as mesh_mod

        mesh_mod.manager.on_success(plan)
    except Exception:  # health bookkeeping must never fail verification
        pass


def _mesh_abandon(plan) -> None:
    try:
        from tendermint_tpu.parallel import mesh as mesh_mod

        mesh_mod.manager.abandon(plan)
    except Exception:  # health bookkeeping must never fail verification
        pass


# A known-good padding triple so padded lanes verify true and never mask
# real failures (they are sliced off anyway).
def _make_pad_entry() -> Tuple[bytes, bytes, bytes]:
    from tendermint_tpu.crypto import ed25519_ref as ref

    priv, pub = ref.keypair_from_seed(b"\x42" * 32)
    msg = b"tendermint-tpu-pad"
    return pub, msg, ref.sign(priv, msg)


_PAD_PK, _PAD_MSG, _PAD_SIG = _make_pad_entry()
_PAD_K: Optional[bytes] = None


def _pad_k() -> bytes:
    global _PAD_K
    if _PAD_K is None:
        _PAD_K = sha512_batch_mod_l(
            [_PAD_SIG[:32] + _PAD_PK + _PAD_MSG]
        )[0]
    return _PAD_K


# Padding rows as ready-made (1, 32) uint8 arrays, decoded once instead
# of np.frombuffer over the pad triple on every padded prepare call.
_PAD_ROWS: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]] = None
_PAD_TABLE: Optional[np.ndarray] = None


def _pad_rows() -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    global _PAD_ROWS
    if _PAD_ROWS is None:
        _PAD_ROWS = tuple(
            np.frombuffer(b, dtype=np.uint8).reshape(1, 32).copy()
            for b in (_PAD_PK, _PAD_SIG[:32], _PAD_SIG[32:], _pad_k())
        )
    return _PAD_ROWS


def _pad_table() -> np.ndarray:
    """(8, 4, 32) uint8 signed-window table of the pad pubkey."""
    global _PAD_TABLE
    if _PAD_TABLE is None:
        from tendermint_tpu.ops import precompute

        _PAD_TABLE = precompute.build_table(_PAD_PK)[0]
    return _PAD_TABLE


def canonical_lt(arr_le: np.ndarray, bound_be: np.ndarray) -> np.ndarray:
    """(N, 32) little-endian values -> (N,) bool value < bound, no
    Python loop (shared by the ed25519 s < L and the ristretto
    encoding < p checks; equality is non-canonical -> False)."""
    be = arr_le[:, ::-1].astype(np.int16)
    diff = be - bound_be.astype(np.int16)[None, :]
    nz = diff != 0
    first = np.argmax(nz, axis=1)
    rows = np.arange(arr_le.shape[0])
    val = diff[rows, first]
    return np.where(nz.any(axis=1), val < 0, False)


def _s_canonical(s_arr: np.ndarray) -> np.ndarray:
    """(N, 32) little-endian s -> (N,) bool s < L."""
    return canonical_lt(s_arr, _L_BYTES_BE)


def _challenge_k(
    prefix: np.ndarray,
    msgs: Sequence[bytes],
    backend: Optional[str],
    stage_times: Optional[dict] = None,
) -> np.ndarray:
    """Challenge scalars k = SHA-512(R‖A‖M) mod L for well-formed lanes.

    Prefers the fused on-device kernel (ops/hash512) — fixed-width vote
    batches hash on the accelerator and the host's share of prep shrinks
    to byte packing — with the hashlib/C-extension host path as exact
    fallback. ``stage_times`` (bench) accumulates the hashing wall time
    under ``hash_ms`` plus which path ran, so prep_ms can be split into
    hash vs pack.
    """
    import time as _time

    from tendermint_tpu.crypto.hashing import reduce_mod_l, sha512_batch_prefixed
    from tendermint_tpu.ops import hash512

    t0 = _time.perf_counter()
    k_dev = hash512.try_challenge_device(prefix, msgs, backend)
    if k_dev is not None:
        k_arr = np.asarray(k_dev)
        device = True
    else:
        k_arr = reduce_mod_l(sha512_batch_prefixed(prefix, list(msgs)))
        device = False
    if stage_times is not None:
        stage_times["hash_ms"] = stage_times.get("hash_ms", 0.0) + (
            _time.perf_counter() - t0
        ) * 1000.0
        stage_times["hash_device"] = device
    return k_arr


def prepare_batch(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    pad_to: Optional[int] = None,
    backend: Optional[str] = None,
    stage_times: Optional[dict] = None,
) -> Tuple[dict, np.ndarray]:
    """Host prep: batch-hash challenges, stack raw bytes, pad to bucket.

    Returns (device inputs dict of (M,32) uint8 arrays, host_ok (N,)
    bool of structural checks: lengths and s < L canonicity)."""
    n = len(pubkeys)
    len_ok = all(len(pk) == 32 and len(sg) == 64 for pk, sg in zip(pubkeys, sigs))
    if len_ok:
        # Fast path (every batch from commit verification): two joins +
        # one prefixed C hash call — no per-signature Python work.
        pk_arr = np.frombuffer(b"".join(pubkeys), dtype=np.uint8).reshape(n, 32)
        sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
        r_arr, s_arr = sig_arr[:, :32], sig_arr[:, 32:]
        host_ok = _s_canonical(s_arr)
        prefix = np.concatenate([r_arr, pk_arr], axis=1)  # (n, 64) = R || A
        k_arr = _challenge_k(prefix, msgs, backend, stage_times)
    else:
        host_ok = np.ones(n, dtype=bool)
        pk_arr = np.zeros((n, 32), dtype=np.uint8)
        r_arr = np.zeros((n, 32), dtype=np.uint8)
        s_arr = np.zeros((n, 32), dtype=np.uint8)
        hash_inputs: List[bytes] = []
        hash_rows: List[int] = []
        for i, (pk, msg, sig) in enumerate(zip(pubkeys, msgs, sigs)):
            if len(pk) != 32 or len(sig) != 64:
                host_ok[i] = False
                continue
            pk_arr[i] = np.frombuffer(pk, dtype=np.uint8)
            r_arr[i] = np.frombuffer(sig[:32], dtype=np.uint8)
            s_arr[i] = np.frombuffer(sig[32:], dtype=np.uint8)
            hash_inputs.append(sig[:32] + pk + msg)
            hash_rows.append(i)
        host_ok &= _s_canonical(s_arr)
        k_arr = np.zeros((n, 32), dtype=np.uint8)
        if hash_inputs:
            k_list = sha512_batch_mod_l(hash_inputs)
            rows = np.asarray(hash_rows)
            k_arr[rows] = np.frombuffer(b"".join(k_list), dtype=np.uint8).reshape(
                -1, 32
            )

    m = pad_to if pad_to is not None else _bucket(n)
    if m > n:
        pk_row, r_row, s_row, k_row = _pad_rows()
        reps = (m - n, 1)
        pk_arr = np.concatenate([pk_arr, np.tile(pk_row, reps)])
        r_arr = np.concatenate([r_arr, np.tile(r_row, reps)])
        s_arr = np.concatenate([s_arr, np.tile(s_row, reps)])
        k_arr = np.concatenate([k_arr, np.tile(k_row, reps)])

    inputs = dict(pk=pk_arr, r=r_arr, s=s_arr, k=k_arr)
    return inputs, host_ok


def _prep_table_chunk(
    pks: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    tabs: Sequence[np.ndarray],
    oks: Sequence[bool],
    pad_to: int,
    backend: Optional[str] = None,
    stage_times: Optional[dict] = None,
) -> Tuple[dict, np.ndarray]:
    """Host prep for a cache-hit chunk: hash challenges, stack the
    gathered per-key table columns into the kernel's (8, 4, 32, M)
    uint8 input. Lengths are pre-validated by the caller (ill-formed
    lanes stay on the legacy path)."""
    n = len(pks)
    pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(n, 32)
    sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
    r_arr, s_arr = sig_arr[:, :32], sig_arr[:, 32:]
    host_ok = _s_canonical(s_arr)
    prefix = np.concatenate([r_arr, pk_arr], axis=1)  # (n, 64) = R || A
    k_arr = _challenge_k(prefix, msgs, backend, stage_times)
    tab = np.stack(tabs)  # (n, 8, 4, 32) uint8
    a_ok = np.fromiter(oks, dtype=bool, count=n).astype(np.uint8)
    if pad_to > n:
        _, r_row, s_row, k_row = _pad_rows()
        reps = (pad_to - n, 1)
        r_arr = np.concatenate([r_arr, np.tile(r_row, reps)])
        s_arr = np.concatenate([s_arr, np.tile(s_row, reps)])
        k_arr = np.concatenate([k_arr, np.tile(k_row, reps)])
        tab = np.concatenate(
            [tab, np.broadcast_to(_pad_table()[None], (pad_to - n, TABLE_WIDTH, 4, 32))]
        )
        a_ok = np.concatenate([a_ok, np.ones(pad_to - n, dtype=np.uint8)])
    tab = np.ascontiguousarray(tab.transpose(1, 2, 3, 0))  # (8, 4, 32, M)
    # every gathered chunk re-ships its table tensor; the resident store
    # accounts it so benches can prove the steady-state delta
    from tendermint_tpu.ops import resident

    resident.note_table_h2d(tab.nbytes)
    inputs = dict(tab=tab, ok=a_ok, r=r_arr, s=s_arr, k=k_arr)
    return inputs, host_ok


def _prep_resident_chunk(
    pks: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    idxs: np.ndarray,
    oks: np.ndarray,
    store_tab,
    mesh_key,
    pad_to: int,
    backend: Optional[str] = None,
    stage_times: Optional[dict] = None,
) -> Tuple[dict, np.ndarray]:
    """Host prep for a resident-store chunk: the table tensor is already
    on device, so the per-batch payload is the (M,) int32 gather index
    vector plus the usual r/s/k rows. Pad lanes index column 0 — the
    pad-key table reserved at upload."""
    n = len(pks)
    pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(n, 32)
    sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
    r_arr, s_arr = sig_arr[:, :32], sig_arr[:, 32:]
    host_ok = _s_canonical(s_arr)
    prefix = np.concatenate([r_arr, pk_arr], axis=1)  # (n, 64) = R || A
    k_arr = _challenge_k(prefix, msgs, backend, stage_times)
    idx = np.asarray(idxs, dtype=np.int32)
    a_ok = np.asarray(oks, dtype=np.uint8)
    if pad_to > n:
        _, r_row, s_row, k_row = _pad_rows()
        reps = (pad_to - n, 1)
        r_arr = np.concatenate([r_arr, np.tile(r_row, reps)])
        s_arr = np.concatenate([s_arr, np.tile(s_row, reps)])
        k_arr = np.concatenate([k_arr, np.tile(k_row, reps)])
        idx = np.concatenate([idx, np.zeros(pad_to - n, dtype=np.int32)])
        a_ok = np.concatenate([a_ok, np.ones(pad_to - n, dtype=np.uint8)])
    inputs = dict(
        store=store_tab,
        mesh_key=mesh_key,
        idx=idx,
        ok=a_ok,
        r=r_arr,
        s=s_arr,
        k=k_arr,
    )
    return inputs, host_ok


def _host_verify_lanes(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    lo: int,
    hi: int,
) -> np.ndarray:
    """CPU oracle over lanes [lo, hi) of the original (unpadded) batch."""
    return _host_verify_rows(pubkeys, msgs, sigs, range(lo, hi))


def _host_verify_rows(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    rows,
) -> np.ndarray:
    """CPU oracle over an arbitrary row subset of the original batch."""
    from tendermint_tpu.crypto.ed25519_ref import verify_zip215

    return np.array(
        [verify_zip215(pubkeys[i], msgs[i], sigs[i]) for i in rows],
        dtype=bool,
    )


class _Job:
    """One padded chunk of the batch: either legacy (build tables on
    device) or cache-hit (gathered table input). ``rows`` are original
    batch indices; the padded tail is sliced off at scatter time."""

    __slots__ = ("kind", "rows", "prepped", "out", "plan")

    def __init__(self, kind: str, rows: np.ndarray):
        self.kind = kind
        self.rows = rows
        self.prepped = None  # (inputs dict, host_ok) once prep ran
        self.out = None  # in-flight device result
        self.plan = None  # mesh plan this chunk dispatched on (or None)


def _chunk_rows(rows: np.ndarray, span: int = CHUNK) -> List[np.ndarray]:
    return [rows[lo : lo + span] for lo in range(0, len(rows), span)]


def _mesh_collect_retry(job: "_Job", backend: Optional[str], exc: Exception):
    """A sharded chunk died at materialization. If the failure is
    attributable to one device, exclude it, rebuild a smaller mesh, and
    re-dispatch THIS chunk on it — 'a sick chip degrades the mesh, not
    to host' holds for collect-time failures too. Returns the chunk's
    verdict array, or None so the caller keeps its ordinary host
    fallback (unattributed failure, or the retry failed as well)."""
    try:
        from tendermint_tpu.parallel import mesh as mesh_mod
        from tendermint_tpu.parallel import sharding as mesh_sharding

        culprit = mesh_mod.manager.on_failure(job.plan, exc)
        if culprit is None:
            return None
        nxt = mesh_mod.manager.replan(job.plan)
        if nxt is None:
            return None
        import warnings

        warnings.warn(
            f"sharded chunk ({job.kind}) failed at collect ({exc!r}); "
            f"device {culprit} excluded, retrying on a {nxt.n_dev}-device mesh"
        )
        inputs, _ = job.prepped
        if job.kind == "tables":
            runner = _run_chunk_tables
        elif job.kind == "resident":
            runner = _run_chunk_resident
        else:
            runner = _run_chunk
        out, used = runner(inputs, backend, nxt)
        ok = (
            mesh_sharding.collect_sharded(out, "ed25519")
            if used is not None
            else np.asarray(out)
        )
        if used is not None:
            _mesh_on_success(used)
        job.plan = used
        return ok
    except Exception:  # retry is best-effort; host fallback covers the chunk
        return None


def verify_batch(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    backend: Optional[str] = None,
) -> List[bool]:
    """Batch ZIP-215 verification; returns per-entry validity.

    The entry point behind crypto.Ed25519BatchVerifier — reference
    contract crypto/crypto.go:58-76 / crypto/ed25519/ed25519.go:198-233.

    The amortized pipeline (ops/precompute.py):

    1. The digest-keyed result cache answers lanes verified before
       (identical last-commit votes at height H+1, vote floods).
    2. Remaining lanes are partitioned: keys with a cached (or
       eligible-to-build) signed-window table take the table kernel,
       which skips per-lane decompression and table building; the rest
       take the legacy build-on-device kernel.
    3. Chunks are double-buffered: the kernel for chunk i is enqueued
       (JAX async dispatch), then chunk i+1's host prep — challenge
       hashing and table gather — runs while the device crunches
       chunk i, so host prep and H2D overlap device compute.

    Device failures degrade per chunk, not per process: a chunk whose
    dispatch or materialization fails is re-verified on the CPU oracle
    while the rest of the batch stays on the device (if the health
    state machine — ops/device_policy.py — still admits it). A batch
    that completes on the device re-promotes a degraded path; the
    state machine alone decides when the device is cooling down or
    disabled, and it recovers via half-open probe batches.
    """
    from tendermint_tpu.ops import precompute

    n = len(pubkeys)
    if n == 0:
        return []
    with tracing.span("verify_batch", engine="ed25519", lanes=n):
        if not precompute.result_cache_enabled():
            return [
                bool(v) for v in _verify_uncached(pubkeys, msgs, sigs, backend)
            ]
        verdicts = np.zeros(n, dtype=bool)
        pending = []
        with tracing.span(
            "cache_lookup", stage="cache_lookup", engine="ed25519", lanes=n
        ) as csp:
            for i in range(n):
                v = precompute.results.get(pubkeys[i], msgs[i], sigs[i])
                if v is None:
                    pending.append(i)
                else:
                    verdicts[i] = v
            csp.set(hits=n - len(pending))
        if pending:
            if len(pending) == n:
                sub = (pubkeys, msgs, sigs)
            else:
                sub = (
                    [pubkeys[i] for i in pending],
                    [msgs[i] for i in pending],
                    [sigs[i] for i in pending],
                )
            out = _verify_uncached(sub[0], sub[1], sub[2], backend)
            for j, i in enumerate(pending):
                verdicts[i] = out[j]
                precompute.results.put(
                    pubkeys[i], msgs[i], sigs[i], bool(out[j])
                )
        return [bool(v) for v in verdicts]


def _verify_uncached(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    backend: Optional[str] = None,
) -> np.ndarray:
    """Device verification of lanes the result cache could not answer."""
    from tendermint_tpu.ops import fault_injection, precompute
    from tendermint_tpu.ops.device_policy import shared as health

    n = len(pubkeys)
    attempt = health.begin_attempt("ed25519")
    if attempt is None:
        # DISABLED, or cooling down (another caller may hold the probe
        # slot). Instant answer — the circuit breaker never blocks.
        health.count_fallback("ed25519", n)
        with tracing.span(
            "host_fallback", stage="fallback", engine="ed25519", lanes=n
        ):
            return _host_verify_lanes(pubkeys, msgs, sigs, 0, n)

    # Partition: lanes whose key has a cached (or eligible, host-built)
    # table take the table kernel; ill-formed lanes must stay on the
    # legacy path, whose slow-path prep handles bad lengths.
    try:
        entries, has_table = precompute.tables.gather(pubkeys)
    except Exception:  # cache trouble never blocks verification
        entries, has_table = None, np.zeros(n, dtype=bool)
    if entries is not None:
        well_formed = np.fromiter(
            (len(pk) == 32 and len(sg) == 64 for pk, sg in zip(pubkeys, sigs)),
            dtype=bool,
            count=n,
        )
        has_table &= well_formed
    if entries is None or not has_table.any():
        has_table = np.zeros(n, dtype=bool)
        entries = None

    # Mesh plan for this batch: when one exists, chunks span all its
    # devices — span and padding scale by the device count so each chip
    # still sees bucket-size slabs. A plan degraded mid-batch replaces
    # `plan` so later chunks ride the smaller mesh.
    plan = _mesh_plan(n)
    span = CHUNK * plan.n_dev if plan is not None else CHUNK
    mesh_used = False

    # Resident routing: lanes whose key already lives in the device-
    # resident store ship only gather indices — zero per-batch table
    # H2D. Any trouble leaves every cached lane on the gathered path.
    res_idx = res_ok_cols = res_tab = res_mesh_key = None
    res_mask = np.zeros(n, dtype=bool)
    if entries is not None:
        try:
            from tendermint_tpu.ops import resident

            res = resident.acquire(
                pubkeys, has_table, plan=plan, backend=backend
            )
        except Exception:  # resident path is an optimization, never a gate
            res = None
        if res is not None:
            res_mask, res_idx, res_ok_cols, res_tab, res_mesh_key = res
    table_mask = has_table & ~res_mask

    jobs = [
        _Job("resident", rows)
        for rows in _chunk_rows(np.nonzero(res_mask)[0], span)
    ]
    jobs += [
        _Job("tables", rows) for rows in _chunk_rows(np.nonzero(table_mask)[0], span)
    ]
    jobs += [
        _Job("legacy", rows) for rows in _chunk_rows(np.nonzero(~has_table)[0], span)
    ]

    def prep_job(job: _Job) -> Tuple[dict, np.ndarray]:
        with tracing.span(
            "prep_chunk",
            stage="prep",
            engine="ed25519",
            kind=job.kind,
            lanes=len(job.rows),
        ):
            pks = [pubkeys[i] for i in job.rows]
            ms = [msgs[i] for i in job.rows]
            sgs = [sigs[i] for i in job.rows]
            pad_to = (
                _mesh_bucket(len(job.rows), plan.n_dev)
                if plan is not None
                else _bucket(len(job.rows))
            )
            if job.kind == "resident":
                idxs = res_idx[job.rows]
                return _prep_resident_chunk(
                    pks,
                    ms,
                    sgs,
                    idxs,
                    res_ok_cols[idxs],
                    res_tab,
                    res_mesh_key,
                    pad_to,
                    backend=backend,
                )
            if job.kind == "tables":
                return _prep_table_chunk(
                    pks,
                    ms,
                    sgs,
                    [entries[i][0] for i in job.rows],
                    [entries[i][1] for i in job.rows],
                    pad_to,
                    backend=backend,
                )
            return prepare_batch(pks, ms, sgs, pad_to=pad_to, backend=backend)

    results = np.ones(n, dtype=bool)
    host_ok_all = np.ones(n, dtype=bool)

    def note_prep_failure(job: _Job, exc: Exception) -> None:
        nonlocal attempt
        # Host prep failed before any device work for this job. Never
        # take the node down over infrastructure — its lanes degrade to
        # the host oracle at collect time.
        health.record_failure(exc, attempt)
        attempt = None
        import warnings

        warnings.warn(
            f"chunk prepare failed ({exc!r}); CPU fallback for "
            f"{len(job.rows)} lanes (device state={health.state})"
        )

    # Double-buffered dispatch: enqueue job j's kernel (async), then run
    # job j+1's host prep while the device crunches job j.
    for j, job in enumerate(jobs):
        if j == 0:
            try:
                job.prepped = prep_job(job)
            except Exception as exc:
                note_prep_failure(job, exc)
        if job.prepped is not None:
            inputs, host_ok = job.prepped
            host_ok_all[job.rows] = host_ok[: len(job.rows)]
            if attempt is None:
                attempt = health.begin_attempt("ed25519")
            if attempt is not None:
                try:
                    if job.kind == "tables":
                        runner = _run_chunk_tables
                    elif job.kind == "resident":
                        runner = _run_chunk_resident
                    else:
                        runner = _run_chunk
                    with tracing.span(
                        "dispatch_chunk",
                        stage="dispatch",
                        engine="ed25519",
                        kind=job.kind,
                        lanes=len(job.rows),
                    ):
                        job.out, job.plan = runner(inputs, backend, plan)
                    if job.plan is not None:
                        mesh_used = True
                        if job.plan is not plan:
                            plan = job.plan  # degraded: later chunks follow
                    health.note_inflight("ed25519", len(job.rows))
                except Exception as exc:
                    health.record_failure(exc, attempt)
                    attempt = None
                    import warnings

                    warnings.warn(
                        f"device chunk ({job.kind}, {len(job.rows)} lanes) "
                        f"dispatch failed ({exc!r}); CPU fallback for the "
                        f"chunk (device state={health.state})"
                    )
        if j + 1 < len(jobs):
            nxt = jobs[j + 1]
            try:
                nxt.prepped = prep_job(nxt)
            except Exception as exc:
                note_prep_failure(nxt, exc)

    if plan is not None and not mesh_used:
        # Planned but never dispatched sharded (e.g. the shared health
        # machine denied every chunk): release probe reservations.
        _mesh_abandon(plan)

    # Collect phase: JAX dispatch is async, so runtime errors can
    # surface at materialization; those too degrade per chunk.
    fallback_lanes = 0
    device_chunks_ok = 0
    for job in jobs:
        ok = None
        if job.out is not None:
            try:
                with tracing.span(
                    "collect_chunk",
                    stage="collect",
                    engine="ed25519",
                    kind=job.kind,
                    lanes=len(job.rows),
                ):
                    fault_injection.fire("ed25519.collect")
                    if job.plan is not None:
                        from tendermint_tpu.parallel import (
                            sharding as mesh_sharding,
                        )

                        ok = mesh_sharding.collect_sharded(job.out, "ed25519")
                    else:
                        ok = np.asarray(job.out)
                device_chunks_ok += 1
                if job.plan is not None:
                    _mesh_on_success(job.plan)
            except Exception as exc:
                if job.plan is not None:
                    ok = _mesh_collect_retry(job, backend, exc)
                if ok is not None:
                    device_chunks_ok += 1
                else:
                    health.record_failure(exc, attempt)
                    attempt = None
                    import warnings

                    warnings.warn(
                        f"device chunk ({job.kind}, {len(job.rows)} lanes) "
                        f"failed at collect ({exc!r}); CPU fallback for the "
                        f"chunk (device state={health.state})"
                    )
            finally:
                health.note_inflight("ed25519", -len(job.rows))
        if not len(job.rows):
            continue
        if ok is None:
            fallback_lanes += len(job.rows)
            with tracing.span(
                "host_fallback",
                stage="fallback",
                engine="ed25519",
                lanes=len(job.rows),
            ):
                results[job.rows] = _host_verify_rows(
                    pubkeys, msgs, sigs, job.rows
                )
            host_ok_all[job.rows] = True  # oracle verdicts are final
        else:
            results[job.rows] = ok[: len(job.rows)]

    if fallback_lanes:
        health.count_fallback("ed25519", fallback_lanes)
    if attempt is not None and device_chunks_ok:
        # No failure consumed the attempt and device work round-tripped:
        # re-promote (clears DEGRADED, completes a half-open probe).
        health.record_success(attempt)
    return np.logical_and(results, host_ok_all)
