"""gRPC remote signer (privval/grpc/client.go, privval/grpc/server.go).

Direction matches the reference's gRPC flavor: the NODE is the gRPC
client dialing the signer's server (the socket flavor is inverted — the
signer dials in; both now exist here). Unary methods on
``/tendermint.privval.PrivValidatorAPI/``:

- GetPubKey  {chain_id} -> {key_type, pub_key}
- SignVote   {chain_id, vote} -> {vote} | {error}
- SignProposal {chain_id, proposal} -> {proposal} | {error}

Payloads are JSON with proto-encoded vote/proposal bytes in base64 —
the same bodies the socket remote signer exchanges (privval/remote.py),
so the two transports stay behaviorally identical: the wrapped FilePV's
last-sign-state double-sign guard refuses conflicting requests and the
refusal surfaces as a remote signer error on the node.
"""

from __future__ import annotations

import base64
import json
import threading
from typing import Optional

from tendermint_tpu.crypto.keys import PubKey, pubkey_from_type_and_bytes
from tendermint_tpu.libs.grpc import (
    GRPC_INTERNAL,
    GrpcChannel,
    GrpcError,
    GrpcServer,
)
from tendermint_tpu.privval.base import PrivValidator
from tendermint_tpu.privval.remote import RemoteSignerError
from tendermint_tpu.types.block import Proposal, Vote

SERVICE = "/tendermint.privval.PrivValidatorAPI/"


class GrpcSignerClient(PrivValidator):
    """types.PrivValidator backed by a remote gRPC signer
    (privval/grpc/client.go:1)."""

    def __init__(self, host: str, port: int, chain_id: str,
                 timeout: float = 10.0):
        self._chan = GrpcChannel(host, port, timeout=timeout)
        self._chain_id = chain_id
        self._cached_pubkey: Optional[PubKey] = None

    def close(self) -> None:
        self._chan.close()

    def _call(self, method: str, body: dict) -> dict:
        try:
            raw = self._chan.unary(
                SERVICE + method, json.dumps(body).encode()
            )
        except GrpcError as e:
            raise RemoteSignerError(e.message or str(e)) from e
        resp = json.loads(raw.decode()) if raw else {}
        if resp.get("error"):
            raise RemoteSignerError(resp["error"])
        return resp

    def get_pub_key(self) -> PubKey:
        if self._cached_pubkey is not None:
            return self._cached_pubkey
        body = self._call("GetPubKey", {"chain_id": self._chain_id})
        pub = pubkey_from_type_and_bytes(
            body["key_type"], base64.b64decode(body["pub_key"])
        )
        self._cached_pubkey = pub
        return pub

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        body = self._call(
            "SignVote",
            {
                "chain_id": chain_id,
                "vote": base64.b64encode(vote.to_proto_bytes()).decode(),
            },
        )
        signed = Vote.from_proto_bytes(base64.b64decode(body["vote"]))
        vote.signature = signed.signature
        vote.timestamp = signed.timestamp
        vote.extension_signature = signed.extension_signature

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        body = self._call(
            "SignProposal",
            {
                "chain_id": chain_id,
                "proposal": base64.b64encode(proposal.to_proto_bytes()).decode(),
            },
        )
        signed = Proposal.from_proto_bytes(base64.b64decode(body["proposal"]))
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp


class GrpcSignerServer:
    """Signer-side gRPC service wrapping a local PrivValidator (usually
    FilePV — its HRS guard is the double-sign protection;
    privval/grpc/server.go:1)."""

    def __init__(self, priv_validator: PrivValidator, chain_id: str,
                 host: str = "127.0.0.1", port: int = 0):
        self._pv = priv_validator
        self._chain_id = chain_id
        self._mtx = threading.Lock()
        self._server = GrpcServer(
            {
                SERVICE + "GetPubKey": self._get_pub_key,
                SERVICE + "SignVote": self._sign_vote,
                SERVICE + "SignProposal": self._sign_proposal,
            },
            host,
            port,
        )

    @property
    def address(self):
        return self._server.address

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()

    def _check_chain(self, body: dict) -> Optional[bytes]:
        if body.get("chain_id") != self._chain_id:
            return json.dumps(
                {"error": f"chain id mismatch: {body.get('chain_id')!r}"}
            ).encode()
        return None

    def _get_pub_key(self, payload: bytes) -> bytes:
        body = json.loads(payload.decode() or "{}")
        err = self._check_chain(body)
        if err is not None:
            return err
        pub = self._pv.get_pub_key()
        return json.dumps(
            {
                "key_type": pub.type,
                "pub_key": base64.b64encode(pub.bytes()).decode(),
            }
        ).encode()

    def _sign_vote(self, payload: bytes) -> bytes:
        body = json.loads(payload.decode() or "{}")
        err = self._check_chain(body)
        if err is not None:
            return err
        try:
            vote = Vote.from_proto_bytes(base64.b64decode(body["vote"]))
            with self._mtx:
                self._pv.sign_vote(body["chain_id"], vote)
            return json.dumps(
                {"vote": base64.b64encode(vote.to_proto_bytes()).decode()}
            ).encode()
        except Exception as exc:  # double-sign refusal etc. -> error body
            return json.dumps({"error": str(exc)}).encode()

    def _sign_proposal(self, payload: bytes) -> bytes:
        body = json.loads(payload.decode() or "{}")
        err = self._check_chain(body)
        if err is not None:
            return err
        try:
            proposal = Proposal.from_proto_bytes(
                base64.b64decode(body["proposal"])
            )
            with self._mtx:
                self._pv.sign_proposal(body["chain_id"], proposal)
            return json.dumps(
                {
                    "proposal": base64.b64encode(
                        proposal.to_proto_bytes()
                    ).decode()
                }
            ).encode()
        except Exception as exc:
            return json.dumps({"error": str(exc)}).encode()


def main(argv=None) -> int:
    """Run a serving gRPC signer around a FilePV (the node dials us —
    privval/grpc/server.go's process shape)."""
    import argparse

    from tendermint_tpu.privval.file_pv import FilePV

    ap = argparse.ArgumentParser(
        prog="python -m tendermint_tpu.privval.grpc",
        description="out-of-process validator signer (gRPC server; node dials)",
    )
    ap.add_argument("--addr", required=True, help="host:port to serve on")
    ap.add_argument("--chain-id", required=True)
    ap.add_argument("--key-file", required=True)
    ap.add_argument("--state-file", required=True)
    args = ap.parse_args(argv)

    pv = FilePV.load_or_generate(args.key_file, args.state_file)
    host, _, port = args.addr.rpartition(":")
    server = GrpcSignerServer(
        pv, args.chain_id, host or "127.0.0.1", int(port)
    )
    server.start()
    print(
        f"grpc signer serving on {server.address[0]}:{server.address[1]}",
        flush=True,
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
