"""verifyd: standalone accelerator verification service.

One resident device, many clients: nodes, light clients, and RPC
front-ends send pk/msg/sig lanes over the wire; the daemon funnels every
connection into one shared ``VerifyScheduler`` so batches form ACROSS
clients — the same dynamic-batching/deadline/backpressure shape as an
inference server, applied to Ed25519/sr25519 verification.

- ``protocol`` — compact varint-framed request/response codec
- ``server`` — the daemon (priority classes, deadlines, admission)
- ``client`` — pooled client + remote-backend plumbing for the node
- ``shm`` — same-host slab-ring transport (negotiated, TCP fallback)
- ``federation`` — N-shard fleet: client-side consistent-hash routing
  keyed by validator-set digest, shard failover, fleet stats merge
"""
