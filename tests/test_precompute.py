"""Validator-set precompute cache + digest-keyed result cache
(ops/precompute.py) and their wiring into the verify hot path.

Covers: host table builds vs the big-int oracle, auto-mode eligibility
gating, LRU eviction, rotation invalidation, thread safety, result-cache
verdict caching, and the headline amortization property — the second
verification of the same commit builds ZERO tables (counter-asserted).
"""

import threading

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.crypto.keys import Ed25519PrivKey
from tendermint_tpu.ops import ed25519_batch, precompute, verify_batch
from tests.helpers import CHAIN_ID, make_block_id, make_commit, make_validators


@pytest.fixture(autouse=True)
def fresh_caches():
    precompute.reset()
    yield
    precompute.reset()


def keypair(i):
    return ref.keypair_from_seed(bytes([i + 1]) * 32)


def make_batch(n, start=0):
    pks, msgs, sigs = [], [], []
    for i in range(start, start + n):
        priv, pub = keypair(i)
        msg = b"precompute msg %d" % i
        pks.append(pub)
        msgs.append(msg)
        sigs.append(ref.sign(priv, msg))
    return pks, msgs, sigs


def _vset(offset, n=3):
    """Validator set with keys disjoint from every other offset."""
    return make_validators(
        n,
        key_factory=lambda i: Ed25519PrivKey.from_seed(
            (100_000 * offset + i).to_bytes(32, "big")
        ),
    )


# --- host-side table builder ------------------------------------------------


def test_build_table_matches_oracle_multiples():
    pk = keypair(1)[1]
    tab, ok = precompute.build_table(pk)
    assert ok and tab.shape == (8, 4, 32) and tab.dtype == np.uint8
    p = ref.P
    neg_a = ref.pt_neg(ref.pt_decompress_liberal(pk))
    for i in range(8):
        m = ref.pt_mul(i + 1, neg_a)
        zinv = pow(m[2], p - 2, p)
        x, y = m[0] * zinv % p, m[1] * zinv % p
        assert int.from_bytes(tab[i, 0].tobytes(), "little") == (y + x) % p
        assert int.from_bytes(tab[i, 1].tobytes(), "little") == (y - x) % p
        assert int.from_bytes(tab[i, 2].tobytes(), "little") == 1
        assert (
            int.from_bytes(tab[i, 3].tobytes(), "little")
            == 2 * ref.D * x * y % p
        )


def test_build_table_invalid_pubkey_masks_lane():
    for bad in (bytes([2] + [0] * 31), b"short", b""):  # off-curve / malformed
        tab, ok = precompute.build_table(bad)
        assert not ok
        assert (tab == precompute._identity_table()).all()


# --- eligibility + lifecycle ------------------------------------------------


def test_auto_mode_gates_on_eligibility(monkeypatch):
    monkeypatch.delenv(precompute._MODE_ENV, raising=False)
    pk = keypair(1)[1]
    entries, has = precompute.tables.gather([pk])
    assert entries is None and not has.any()
    assert precompute.tables.stats()["builds"] == 0
    precompute.pin_pubkeys([pk])
    entries, has = precompute.tables.gather([pk])
    assert has.all() and entries[0][1] is True
    assert precompute.tables.stats()["builds"] == 1
    precompute.tables.gather([pk])
    s = precompute.tables.stats()
    assert s["builds"] == 1 and s["hits"] == 1


def test_activate_validator_set_makes_keys_eligible():
    privs, vset = _vset(1)
    assert precompute.activate_validator_set(vset) is True
    assert precompute.activate_validator_set(vset) is False  # LRU touch
    pks = [v.pub_key.bytes() for v in vset.validators]
    entries, has = precompute.tables.gather(pks)
    assert has.all()
    assert precompute.tables.stats()["builds"] == len(pks)


def test_rotation_invalidates_dropped_keys():
    _, v0 = _vset(1)
    precompute.activate_validator_set(v0)
    pk0 = v0.validators[0].pub_key.bytes()
    precompute.tables.gather([pk0])
    assert len(precompute.tables) == 1
    # Enough newer sets to retire v0 from the live window; its cached
    # table must drop with it (committee rotation).
    for off in range(2, 2 + precompute._ACTIVE_SETS_CAP):
        precompute.activate_validator_set(_vset(off)[1])
    assert len(precompute.tables) == 0
    assert precompute.tables.stats()["invalidations"] == 1
    assert precompute.tables.lookup(pk0) is None


def test_lru_eviction_bound(monkeypatch):
    monkeypatch.setenv(precompute._MODE_ENV, "all")
    monkeypatch.setenv(precompute._CAP_ENV, "4")
    pks = [keypair(i)[1] for i in range(6)]
    for pk in pks:
        precompute.tables.gather([pk])
    assert len(precompute.tables) == 4
    assert precompute.tables.stats()["evictions"] == 2
    assert precompute.tables.lookup(pks[0]) is None
    assert precompute.tables.lookup(pks[5]) is not None


def test_gather_duplicate_lanes_one_build(monkeypatch):
    monkeypatch.setenv(precompute._MODE_ENV, "all")
    pk = keypair(1)[1]
    entries, has = precompute.tables.gather([pk, pk, pk])
    assert has.all()
    s = precompute.tables.stats()
    assert s["builds"] == 1
    assert all(e is not None for e in entries)


def test_concurrent_gather_is_threadsafe(monkeypatch):
    monkeypatch.setenv(precompute._MODE_ENV, "all")
    pks = [keypair(i)[1] for i in range(8)]
    errors = []

    def worker():
        try:
            for _ in range(10):
                entries, has = precompute.tables.gather(pks)
                assert has.all()
                assert all(e is not None and e[1] for e in entries)
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    # every key built exactly once, ever (gather serializes on the lock)
    assert precompute.tables.stats()["builds"] == len(pks)


# --- result cache -----------------------------------------------------------


def test_result_cache_caches_both_verdicts(monkeypatch):
    monkeypatch.setenv(precompute._RESULT_ENV, "1")
    rc = precompute.results
    pk, msg = b"k" * 32, b"msg"
    assert rc.get(pk, msg, b"s" * 64) is None
    rc.put(pk, msg, b"s" * 64, True)
    rc.put(pk, msg, b"t" * 64, False)
    assert rc.get(pk, msg, b"s" * 64) is True
    assert rc.get(pk, msg, b"t" * 64) is False
    s = rc.stats()
    assert s["hits"] == 2 and s["misses"] == 1


def test_result_cache_respects_cap(monkeypatch):
    monkeypatch.setenv(precompute._RESULT_ENV, "1")
    monkeypatch.setenv(precompute._RESULT_CAP_ENV, "3")
    rc = precompute.results
    for i in range(5):
        rc.put(b"k" * 32, b"m%d" % i, b"s" * 64, True)
    assert len(rc) == 3


def test_result_cache_disabled_is_inert(monkeypatch):
    monkeypatch.setenv(precompute._RESULT_ENV, "0")
    rc = precompute.results
    rc.put(b"k" * 32, b"m", b"s" * 64, True)
    assert len(rc) == 0
    assert rc.get(b"k" * 32, b"m", b"s" * 64) is None
    assert rc.stats()["misses"] == 0  # disabled lookups don't count


def test_verify_batch_answers_repeats_from_result_cache(monkeypatch):
    monkeypatch.setenv(precompute._RESULT_ENV, "1")
    pks, msgs, sigs = make_batch(20)
    sigs[4] = sigs[4][:33] + bytes([sigs[4][33] ^ 1]) + sigs[4][34:]
    want = [ref.verify_zip215(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    assert verify_batch(pks, msgs, sigs) == want

    calls = []
    orig = ed25519_batch._verify_uncached

    def spy(pks_, msgs_, sigs_, backend=None):
        calls.append(len(pks_))
        return orig(pks_, msgs_, sigs_, backend)

    monkeypatch.setattr(ed25519_batch, "_verify_uncached", spy)
    assert verify_batch(pks, msgs, sigs) == want  # all 20 lanes cached
    assert calls == []
    # a new lane among repeats only re-verifies the new lane
    pks2, msgs2, sigs2 = make_batch(1, start=40)
    assert verify_batch(pks + pks2, msgs + msgs2, sigs + sigs2) == want + [True]
    assert calls == [1]


# --- the headline amortization property -------------------------------------


def test_second_commit_verification_builds_zero_tables(monkeypatch):
    """ISSUE acceptance: a 100-validator commit verified twice performs
    zero table builds on the second call — every lane gathers its
    precomputed column. Result cache disabled so the kernel path (not a
    verdict replay) is what's exercised twice."""
    monkeypatch.setenv(precompute._RESULT_ENV, "0")
    from tendermint_tpu.types import validation

    privs, vset = make_validators(100)
    block_id = make_block_id()
    commit = make_commit(block_id, 5, 0, vset, privs)

    validation.verify_commit(CHAIN_ID, vset, block_id, 5, commit)
    s1 = precompute.tables.stats()
    assert s1["builds"] == 100  # one host build per distinct validator

    validation.verify_commit(CHAIN_ID, vset, block_id, 5, commit)
    s2 = precompute.tables.stats()
    assert s2["builds"] == s1["builds"]  # ZERO builds on the 2nd pass
    assert s2["hits"] >= s1["hits"] + 100


def test_table_path_agrees_with_oracle(monkeypatch):
    """XLA table kernel vs oracle, including a masked-invalid-key lane
    and a corrupted signature, with every lane eligible."""
    monkeypatch.setenv(precompute._MODE_ENV, "all")
    monkeypatch.setenv(precompute._RESULT_ENV, "0")
    pks, msgs, sigs = make_batch(20)
    pks[0] = bytes([2] + [0] * 31)  # off-curve: identity table + ok=False
    pks[1] = (ref.P + 1).to_bytes(32, "little")  # non-canonical encoding
    sigs[2] = sigs[2][:32] + bytes(32)  # zeroed s
    sigs[3] = bytes(32) + sigs[3][32:]  # R replaced (y=0 IS on curve)
    want = [ref.verify_zip215(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    got = verify_batch(pks, msgs, sigs)
    assert got == want
    # all lanes rode the table path (eligible in "all" mode)
    s = precompute.tables.stats()
    assert s["builds"] == len(set(pks))
