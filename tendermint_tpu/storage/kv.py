"""Key-value store abstraction (the tm-db seam).

The reference selects among goleveldb/cleveldb/rocksdb/badger/bolt/memdb
behind one interface (config/db.go:29); here the same seam is a small
ABC with an in-memory default. Keys iterate in ascending byte order;
iterators see a snapshot of the keys at creation (matches tm-db's
guarantees closely enough for the stores built on top).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterator, List, Optional, Tuple


class KVStore:
    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def iterator(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]:
        """Ascending [start, end) iteration."""
        raise NotImplementedError

    def reverse_iterator(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]:
        """Descending iteration over [start, end)."""
        raise NotImplementedError

    def new_batch(self) -> "Batch":
        return Batch(self)

    def apply_batch(self, ops) -> None:
        for op, key, value in ops:
            if op == "set":
                self.set(key, value)
            else:
                self.delete(key)

    def close(self) -> None:
        pass


class Batch:
    """Write batch applied atomically on write() (tm-db Batch)."""

    def __init__(self, db: KVStore):
        self._db = db
        self._ops: List[Tuple[str, bytes, Optional[bytes]]] = []

    def set(self, key: bytes, value: bytes) -> "Batch":
        self._ops.append(("set", bytes(key), bytes(value)))
        return self

    def delete(self, key: bytes) -> "Batch":
        self._ops.append(("del", bytes(key), None))
        return self

    def write(self) -> None:
        self._db.apply_batch(self._ops)
        self._ops = []


class MemDB(KVStore):
    """Sorted in-memory store (tm-db memdb)."""

    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []  # sorted
        self._lock = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            key = bytes(key)
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            if key in self._data:
                del self._data[key]
                idx = bisect.bisect_left(self._keys, key)
                del self._keys[idx]

    def apply_batch(self, ops) -> None:
        with self._lock:
            for op, key, value in ops:
                if op == "set":
                    self.set(key, value)
                else:
                    self.delete(key)

    def _range(self, start: Optional[bytes], end: Optional[bytes]) -> List[bytes]:
        with self._lock:
            lo = 0 if start is None else bisect.bisect_left(self._keys, start)
            hi = len(self._keys) if end is None else bisect.bisect_left(self._keys, end)
            return self._keys[lo:hi]

    def iterator(self, start=None, end=None):
        for k in self._range(start, end):
            v = self.get(k)
            if v is not None:
                yield k, v

    def reverse_iterator(self, start=None, end=None):
        for k in reversed(self._range(start, end)):
            v = self.get(k)
            if v is not None:
                yield k, v


def prefix_end(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every key with this prefix."""
    out = bytearray(prefix)
    while out:
        if out[-1] < 0xFF:
            out[-1] += 1
            return bytes(out)
        out.pop()
    return None


def ordered_key(prefix: int, *parts: int) -> bytes:
    """Height-ordered key: one prefix byte + big-endian uint64 parts, so
    byte order == numeric order (the role of orderedcode in
    internal/store/store.go:651-737)."""
    out = bytearray([prefix])
    for p in parts:
        out += p.to_bytes(8, "big")
    return bytes(out)
