"""Core block types: BlockID, CommitSig, Commit, Vote, Header, Block, Proposal.

Mirrors types/block.go, types/vote.go, types/proposal.go. Wire encoding is
hand-rolled gogoproto-compatible bytes (ascending field order, proto3
zero-omission, non-nullable embedded messages always serialized) so hashes
and sign-bytes are byte-exact with the reference without a protoc step.

Time is represented as :class:`Timestamp` (seconds, nanos); the Go zero
time (year 1) is ``GO_ZERO_TIME`` and is what gogo's StdTime marshals for
an unset time.Time.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field as dc_field
from typing import List, Optional

from tendermint_tpu.crypto import merkle
from tendermint_tpu.crypto.keys import ADDRESS_LEN, PubKey
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    SIGNED_MSG_TYPE_PROPOSAL,
    Timestamp,
    proposal_sign_bytes,
    vote_extension_sign_bytes,
    vote_sign_bytes,
)
from tendermint_tpu.encoding.proto import (
    Reader,
    encode_bytes_field,
    encode_message_field,
    encode_varint_field,
)

HASH_SIZE = 32
MAX_CHAIN_ID_LEN = 50
BLOCK_PART_SIZE_BYTES = 65536  # types/params.go:21
MAX_VOTE_EXTENSION_SIZE = 1024 * 1024  # types/vote.go:20

# Go's time.Time{} (January 1, year 1 UTC) in Unix seconds.
GO_ZERO_SECONDS = -62135596800
GO_ZERO_TIME = Timestamp(GO_ZERO_SECONDS, 0)

# BlockIDFlag (types/block.go:583-592)
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3


def is_zero_time(ts: Timestamp) -> bool:
    return ts == GO_ZERO_TIME or ts == Timestamp(0, 0)


def validate_hash(h: bytes) -> None:
    """types/validation.go ValidateHash: empty or exactly 32 bytes."""
    if h and len(h) != HASH_SIZE:
        raise ValueError(f"expected hash size {HASH_SIZE}, got {len(h)}")


def _encode_time_field(field_no: int, ts: Timestamp) -> bytes:
    """Non-nullable stdtime field: always serialized (gogo marshaller)."""
    return encode_message_field(field_no, ts.encode(), always=True)


def _decode_time(data: bytes) -> Timestamp:
    r = Reader(data)
    seconds = nanos = 0
    for f, w in r.fields():
        if f == 1 and w == 0:
            seconds = r.read_svarint()
        elif f == 2 and w == 0:
            nanos = r.read_svarint()
        else:
            r.skip(w)
    return Timestamp(seconds, nanos)


def cdc_encode_bytes(b: bytes) -> bytes:
    """gogotypes.BytesValue wrapper (types/encoding_helper.go:11)."""
    if not b:
        return b""
    return encode_bytes_field(1, b)


def cdc_encode_string(s: str) -> bytes:
    if not s:
        return b""
    return encode_bytes_field(1, s.encode("utf-8"))


def cdc_encode_int64(n: int) -> bytes:
    if n == 0:
        return b""
    return encode_varint_field(1, n)


# --- Version ----------------------------------------------------------------

BLOCK_PROTOCOL = 11  # version/version.go BlockProtocol


@dataclass(frozen=True)
class Consensus:
    """tendermint.version.Consensus {block=1, app=2}."""

    block: int = BLOCK_PROTOCOL
    app: int = 0

    def to_proto_bytes(self) -> bytes:
        return encode_varint_field(1, self.block) + encode_varint_field(2, self.app)

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Consensus":
        r = Reader(data)
        block = app = 0
        for f, w in r.fields():
            if f == 1 and w == 0:
                block = r.read_varint()
            elif f == 2 and w == 0:
                app = r.read_varint()
            else:
                r.skip(w)
        return cls(block, app)


# --- PartSetHeader / BlockID ------------------------------------------------


@dataclass(frozen=True)
class PartSetHeader:
    """types/part_set.go PartSetHeader {total=1 uint32, hash=2 bytes}."""

    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def validate_basic(self) -> None:
        if self.total < 0:
            raise ValueError("negative Total")
        validate_hash(self.hash)

    def to_proto_bytes(self) -> bytes:
        return encode_varint_field(1, self.total) + encode_bytes_field(2, self.hash)

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "PartSetHeader":
        r = Reader(data)
        total, hash_ = 0, b""
        for f, w in r.fields():
            if f == 1 and w == 0:
                total = r.read_varint()
            elif f == 2 and w == 2:
                hash_ = r.read_bytes()
            else:
                r.skip(w)
        return cls(total, hash_)


@dataclass(frozen=True)
class BlockID:
    """types/block.go BlockID {hash=1, part_set_header=2 non-nullable}."""

    hash: bytes = b""
    part_set_header: PartSetHeader = dc_field(default_factory=PartSetHeader)

    def is_nil(self) -> bool:
        return not self.hash and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (
            len(self.hash) == HASH_SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == HASH_SIZE
        )

    def validate_basic(self) -> None:
        validate_hash(self.hash)
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Map key: hash + psh proto (types/block.go BlockID.Key)."""
        return self.hash + self.part_set_header.to_proto_bytes()

    def to_proto_bytes(self) -> bytes:
        return encode_bytes_field(1, self.hash) + encode_message_field(
            2, self.part_set_header.to_proto_bytes(), always=True
        )

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "BlockID":
        r = Reader(data)
        hash_, psh = b"", PartSetHeader()
        for f, w in r.fields():
            if f == 1 and w == 2:
                hash_ = r.read_bytes()
            elif f == 2 and w == 2:
                psh = PartSetHeader.from_proto_bytes(r.read_bytes())
            else:
                r.skip(w)
        return cls(hash_, psh)


NIL_BLOCK_ID = BlockID()


# --- CommitSig / Commit -----------------------------------------------------


@dataclass
class CommitSig:
    """types/block.go:604-615."""

    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp: Timestamp = GO_ZERO_TIME
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls()

    @classmethod
    def for_block(
        cls, address: bytes, timestamp: Timestamp, signature: bytes
    ) -> "CommitSig":
        return cls(BLOCK_ID_FLAG_COMMIT, address, timestamp, signature)

    def is_absent(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def is_commit(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this signature signed over (types/block.go:641-653)."""
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            return NIL_BLOCK_ID
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        if self.block_id_flag == BLOCK_ID_FLAG_NIL:
            return NIL_BLOCK_ID
        raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")

    def validate_basic(self) -> None:
        if self.block_id_flag not in (
            BLOCK_ID_FLAG_ABSENT,
            BLOCK_ID_FLAG_COMMIT,
            BLOCK_ID_FLAG_NIL,
        ):
            raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            if self.validator_address:
                raise ValueError("validator address is present for absent CommitSig")
            if not is_zero_time(self.timestamp):
                raise ValueError("time is present for absent CommitSig")
            if self.signature:
                raise ValueError("signature is present for absent CommitSig")
        else:
            if len(self.validator_address) != ADDRESS_LEN:
                raise ValueError(
                    f"expected ValidatorAddress size {ADDRESS_LEN}, got "
                    f"{len(self.validator_address)}"
                )
            if not self.signature:
                raise ValueError("signature is missing")
            if len(self.signature) > MAX_SIGNATURE_SIZE:
                raise ValueError("signature is too big")

    def to_proto_bytes(self) -> bytes:
        return (
            encode_varint_field(1, self.block_id_flag)
            + encode_bytes_field(2, self.validator_address)
            + _encode_time_field(3, self.timestamp)
            + encode_bytes_field(4, self.signature)
        )

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "CommitSig":
        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 0:
                out.block_id_flag = r.read_varint()
            elif f == 2 and w == 2:
                out.validator_address = r.read_bytes()
            elif f == 3 and w == 2:
                out.timestamp = _decode_time(r.read_bytes())
            elif f == 4 and w == 2:
                out.signature = r.read_bytes()
            else:
                r.skip(w)
        return out


MAX_SIGNATURE_SIZE = 64  # ed25519/sr25519; secp256k1 is also 64 here


@dataclass
class Commit:
    """types/block.go:815-828; signatures ordered by validator index."""

    height: int = 0
    round: int = 0
    block_id: BlockID = dc_field(default_factory=BlockID)
    signatures: List[CommitSig] = dc_field(default_factory=list)
    _hash: Optional[bytes] = dc_field(default=None, repr=False, compare=False)

    def size(self) -> int:
        return len(self.signatures)

    def get_vote(self, val_idx: int) -> "Vote":
        """types/block.go:836-849 (no vote extensions in commits)."""
        cs = self.signatures[val_idx]
        return Vote(
            type=SIGNED_MSG_TYPE_PRECOMMIT,
            height=self.height,
            round=self.round,
            block_id=cs.block_id(self.block_id),
            timestamp=cs.timestamp,
            validator_address=cs.validator_address,
            validator_index=val_idx,
            signature=cs.signature,
        )

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        """types/block.go:851-868: canonical sign-bytes for signature i."""
        cs = self.signatures[val_idx]
        bid = cs.block_id(self.block_id)
        return vote_sign_bytes(
            chain_id,
            SIGNED_MSG_TYPE_PRECOMMIT,
            self.height,
            self.round,
            bid.hash,
            bid.part_set_header.total,
            bid.part_set_header.hash,
            cs.timestamp,
        )

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for i, cs in enumerate(self.signatures):
                try:
                    cs.validate_basic()
                except ValueError as e:
                    raise ValueError(f"wrong CommitSig #{i}: {e}") from e

    def hash(self) -> bytes:
        """Merkle root of the proto-encoded CommitSigs (types/block.go:901)."""
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [cs.to_proto_bytes() for cs in self.signatures]
            )
        return self._hash

    def to_proto_bytes(self) -> bytes:
        out = encode_varint_field(1, self.height)
        out += encode_varint_field(2, self.round)
        out += encode_message_field(3, self.block_id.to_proto_bytes(), always=True)
        for cs in self.signatures:
            out += encode_message_field(4, cs.to_proto_bytes(), always=True)
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Commit":
        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 0:
                out.height = r.read_svarint()
            elif f == 2 and w == 0:
                out.round = r.read_svarint()
            elif f == 3 and w == 2:
                out.block_id = BlockID.from_proto_bytes(r.read_bytes())
            elif f == 4 and w == 2:
                out.signatures.append(CommitSig.from_proto_bytes(r.read_bytes()))
            else:
                r.skip(w)
        return out


# --- ExtendedCommit (ABCI++ vote extensions) --------------------------------


@dataclass
class ExtendedCommitSig:
    """types/block.go:728-744: CommitSig + extension + extension sig."""

    commit_sig: CommitSig = dc_field(default_factory=CommitSig)
    extension: bytes = b""
    extension_signature: bytes = b""

    def validate_basic(self) -> None:
        self.commit_sig.validate_basic()
        if self.commit_sig.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            if len(self.extension) > MAX_VOTE_EXTENSION_SIZE:
                raise ValueError("vote extension is too big")
            if not self.extension_signature:
                raise ValueError("vote extension signature is missing")
            if len(self.extension_signature) > MAX_SIGNATURE_SIZE:
                raise ValueError("vote extension signature is too big")
        elif self.extension_signature or self.extension:
            raise ValueError(
                "vote extension and signature must be empty for non-commit sig"
            )

    def ensure_extension(self) -> None:
        """types/block.go:766-779: commit sigs must carry an extension sig."""
        if (
            self.commit_sig.block_id_flag == BLOCK_ID_FLAG_COMMIT
            and not self.extension_signature
        ):
            raise ValueError("vote extension data is missing")

    def to_proto_bytes(self) -> bytes:
        cs = self.commit_sig
        return (
            encode_varint_field(1, cs.block_id_flag)
            + encode_bytes_field(2, cs.validator_address)
            + _encode_time_field(3, cs.timestamp)
            + encode_bytes_field(4, cs.signature)
            + encode_bytes_field(5, self.extension)
            + encode_bytes_field(6, self.extension_signature)
        )

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "ExtendedCommitSig":
        r = Reader(data)
        cs = CommitSig()
        ext = ext_sig = b""
        for f, w in r.fields():
            if f == 1 and w == 0:
                cs.block_id_flag = r.read_varint()
            elif f == 2 and w == 2:
                cs.validator_address = r.read_bytes()
            elif f == 3 and w == 2:
                cs.timestamp = _decode_time(r.read_bytes())
            elif f == 4 and w == 2:
                cs.signature = r.read_bytes()
            elif f == 5 and w == 2:
                ext = r.read_bytes()
            elif f == 6 and w == 2:
                ext_sig = r.read_bytes()
            else:
                r.skip(w)
        return cls(cs, ext, ext_sig)


@dataclass
class ExtendedCommit:
    """types/block.go ExtendedCommit: commit + vote extensions."""

    height: int = 0
    round: int = 0
    block_id: BlockID = dc_field(default_factory=BlockID)
    extended_signatures: List[ExtendedCommitSig] = dc_field(default_factory=list)

    def size(self) -> int:
        return len(self.extended_signatures)

    def to_commit(self) -> Commit:
        return Commit(
            height=self.height,
            round=self.round,
            block_id=self.block_id,
            signatures=[e.commit_sig for e in self.extended_signatures],
        )

    @classmethod
    def wrap_commit(cls, commit: Commit) -> "ExtendedCommit":
        return cls(
            height=commit.height,
            round=commit.round,
            block_id=commit.block_id,
            extended_signatures=[ExtendedCommitSig(s) for s in commit.signatures],
        )

    def get_extended_vote(self, val_idx: int) -> "Vote":
        """The precommit this entry came from, WITH its extension —
        catch-up gossip must serve these when vote extensions are
        enabled, or a lagging peer (which requires extensions on every
        non-nil precommit) rejects the reconstruction and deadlocks.
        Built directly from the entry (no O(n) Commit rebuild)."""
        e = self.extended_signatures[val_idx]
        cs = e.commit_sig
        return Vote(
            type=SIGNED_MSG_TYPE_PRECOMMIT,
            height=self.height,
            round=self.round,
            block_id=cs.block_id(self.block_id),
            timestamp=cs.timestamp,
            validator_address=cs.validator_address,
            validator_index=val_idx,
            signature=cs.signature,
            extension=e.extension,
            extension_signature=e.extension_signature,
        )

    def ensure_extensions(self) -> None:
        for e in self.extended_signatures:
            e.ensure_extension()

    def strip_extensions(self) -> bool:
        stripped = any(
            e.extension or e.extension_signature for e in self.extended_signatures
        )
        for e in self.extended_signatures:
            e.extension = b""
            e.extension_signature = b""
        return stripped

    def to_proto_bytes(self) -> bytes:
        out = encode_varint_field(1, self.height)
        out += encode_varint_field(2, self.round)
        out += encode_message_field(3, self.block_id.to_proto_bytes(), always=True)
        for e in self.extended_signatures:
            out += encode_message_field(4, e.to_proto_bytes(), always=True)
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "ExtendedCommit":
        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 0:
                out.height = r.read_svarint()
            elif f == 2 and w == 0:
                out.round = r.read_svarint()
            elif f == 3 and w == 2:
                out.block_id = BlockID.from_proto_bytes(r.read_bytes())
            elif f == 4 and w == 2:
                out.extended_signatures.append(
                    ExtendedCommitSig.from_proto_bytes(r.read_bytes())
                )
            else:
                r.skip(w)
        return out


# --- Vote -------------------------------------------------------------------


@dataclass
class Vote:
    """types/vote.go:55-66."""

    type: int = 0
    height: int = 0
    round: int = 0
    block_id: BlockID = dc_field(default_factory=BlockID)
    timestamp: Timestamp = GO_ZERO_TIME
    validator_address: bytes = b""
    validator_index: int = 0
    signature: bytes = b""
    extension: bytes = b""
    extension_signature: bytes = b""
    # Pre-verification tags set by the reactor's scheduler-batched vote
    # path (consensus/reactor.py VotePreverifier): the (chain_id, pubkey
    # bytes) this vote's signature(s) were already verified against via
    # the device batch. verify() honors a matching tag and re-verifies
    # inline otherwise, so a stale or wrong tag only costs the
    # optimization, never correctness.
    _pre_verified: Optional[tuple] = dc_field(
        default=None, compare=False, repr=False
    )
    _pre_verified_ext: Optional[tuple] = dc_field(
        default=None, compare=False, repr=False
    )

    def mark_pre_verified(
        self,
        chain_id: str,
        pub_key_bytes: bytes,
        extension_too: bool = False,
        sign_bytes_digest: Optional[bytes] = None,
        extension_digest: Optional[bytes] = None,
    ) -> None:
        """Record that a batch path already verified this vote.

        The tag is self-validating: it carries a digest of the sign-bytes
        that were actually verified, and :meth:`verify` recomputes the
        digest before honoring the tag — so mutating any signed field
        after pre-verification silently demotes the vote to a full
        signature check instead of skipping it. Callers that verified
        specific bytes (the preverifier) pass their digest; otherwise it
        is computed here from the vote's current content.
        """
        if sign_bytes_digest is None:
            sign_bytes_digest = hashlib.sha256(self.sign_bytes(chain_id)).digest()
        self._pre_verified = (chain_id, pub_key_bytes, sign_bytes_digest)
        if extension_too:
            if extension_digest is None:
                extension_digest = hashlib.sha256(
                    self.extension_sign_bytes(chain_id)
                ).digest()
            self._pre_verified_ext = (chain_id, pub_key_bytes, extension_digest)

    def is_nil_vote(self) -> bool:
        return self.block_id.is_nil()

    def sign_bytes(self, chain_id: str) -> bytes:
        return vote_sign_bytes(
            chain_id,
            self.type,
            self.height,
            self.round,
            self.block_id.hash,
            self.block_id.part_set_header.total,
            self.block_id.part_set_header.hash,
            self.timestamp,
        )

    def extension_sign_bytes(self, chain_id: str) -> bytes:
        return vote_extension_sign_bytes(
            chain_id, self.extension, self.height, self.round
        )

    def commit_sig(self) -> CommitSig:
        """types/vote.go:95-115."""
        if self.block_id.is_complete():
            flag = BLOCK_ID_FLAG_COMMIT
        elif self.block_id.is_nil():
            flag = BLOCK_ID_FLAG_NIL
        else:
            raise ValueError(f"invalid vote BlockID {self.block_id}")
        return CommitSig(flag, self.validator_address, self.timestamp, self.signature)

    def extended_commit_sig(self) -> ExtendedCommitSig:
        return ExtendedCommitSig(
            self.commit_sig(), self.extension, self.extension_signature
        )

    def strip_extension(self) -> bool:
        stripped = bool(self.extension or self.extension_signature)
        self.extension = b""
        self.extension_signature = b""
        return stripped

    def verify(self, chain_id: str, pub_key: PubKey) -> None:
        """types/vote.go Verify: address match + signature over sign-bytes."""
        if pub_key.address() != self.validator_address:
            raise VoteError("invalid validator address")
        sb = self.sign_bytes(chain_id)
        if self._pre_verified == (
            chain_id,
            pub_key.bytes(),
            hashlib.sha256(sb).digest(),
        ):
            return  # batch-verified this key over these EXACT sign-bytes
        if not pub_key.verify_signature(sb, self.signature):
            raise VoteError("invalid signature")

    def verify_vote_and_extension(self, chain_id: str, pub_key: PubKey) -> None:
        """types/vote.go:258-274: also checks the extension signature for
        non-nil precommits."""
        self.verify(chain_id, pub_key)
        if (
            self.type == SIGNED_MSG_TYPE_PRECOMMIT
            and not self.block_id.is_nil()
        ):
            self.verify_extension(chain_id, pub_key)

    def verify_extension(self, chain_id: str, pub_key: PubKey) -> None:
        if self.type != SIGNED_MSG_TYPE_PRECOMMIT or self.block_id.is_nil():
            return
        esb = self.extension_sign_bytes(chain_id)
        if self._pre_verified_ext == (
            chain_id,
            pub_key.bytes(),
            hashlib.sha256(esb).digest(),
        ):
            return
        if not pub_key.verify_signature(esb, self.extension_signature):
            raise VoteError("invalid extension signature")

    def validate_basic(self) -> None:
        if self.type not in (SIGNED_MSG_TYPE_PREVOTE, SIGNED_MSG_TYPE_PRECOMMIT):
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if not self.block_id.is_nil():
            self.block_id.validate_basic()
            if not self.block_id.is_complete():
                raise ValueError(f"blockID must be either empty or complete")
        if len(self.validator_address) != ADDRESS_LEN:
            raise ValueError(
                f"expected ValidatorAddress size {ADDRESS_LEN}, got "
                f"{len(self.validator_address)}"
            )
        if self.validator_index < 0:
            raise ValueError("negative ValidatorIndex")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("signature is too big")
        if self.type != SIGNED_MSG_TYPE_PRECOMMIT and (
            self.extension or self.extension_signature
        ):
            raise ValueError("extension only allowed on precommits")
        if len(self.extension) > MAX_VOTE_EXTENSION_SIZE:
            raise ValueError("vote extension is too big")
        if self.extension and not self.extension_signature:
            raise ValueError("vote extension signature absent on vote with extension")
        if len(self.extension_signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("vote extension signature is too big")

    def to_proto_bytes(self) -> bytes:
        out = encode_varint_field(1, self.type)
        out += encode_varint_field(2, self.height)
        out += encode_varint_field(3, self.round)
        out += encode_message_field(4, self.block_id.to_proto_bytes(), always=True)
        out += _encode_time_field(5, self.timestamp)
        out += encode_bytes_field(6, self.validator_address)
        out += encode_varint_field(7, self.validator_index)
        out += encode_bytes_field(8, self.signature)
        out += encode_bytes_field(9, self.extension)
        out += encode_bytes_field(10, self.extension_signature)
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Vote":
        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 0:
                out.type = r.read_varint()
            elif f == 2 and w == 0:
                out.height = r.read_svarint()
            elif f == 3 and w == 0:
                out.round = r.read_svarint()
            elif f == 4 and w == 2:
                out.block_id = BlockID.from_proto_bytes(r.read_bytes())
            elif f == 5 and w == 2:
                out.timestamp = _decode_time(r.read_bytes())
            elif f == 6 and w == 2:
                out.validator_address = r.read_bytes()
            elif f == 7 and w == 0:
                out.validator_index = r.read_svarint()
            elif f == 8 and w == 2:
                out.signature = r.read_bytes()
            elif f == 9 and w == 2:
                out.extension = r.read_bytes()
            elif f == 10 and w == 2:
                out.extension_signature = r.read_bytes()
            else:
                r.skip(w)
        return out


class VoteError(ValueError):
    pass


# --- Proposal ---------------------------------------------------------------


@dataclass
class Proposal:
    """types/proposal.go: a proposed block at (height, round) with POL round."""

    type: int = SIGNED_MSG_TYPE_PROPOSAL
    height: int = 0
    round: int = 0
    pol_round: int = -1
    block_id: BlockID = dc_field(default_factory=BlockID)
    timestamp: Timestamp = GO_ZERO_TIME
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return proposal_sign_bytes(
            chain_id,
            self.height,
            self.round,
            self.pol_round,
            self.block_id.hash,
            self.block_id.part_set_header.total,
            self.block_id.part_set_header.hash,
            self.timestamp,
        )

    def validate_basic(self) -> None:
        if self.type != SIGNED_MSG_TYPE_PROPOSAL:
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.pol_round < -1 or (
            self.pol_round >= 0 and self.pol_round >= self.round
        ):
            raise ValueError("invalid POLRound")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError("expected a complete, non-empty BlockID")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("signature is too big")

    def to_proto_bytes(self) -> bytes:
        out = encode_varint_field(1, self.type)
        out += encode_varint_field(2, self.height)
        out += encode_varint_field(3, self.round)
        # pol_round is int32; -1 encodes as 10-byte two's-complement varint
        out += encode_varint_field(4, self.pol_round)
        out += encode_message_field(5, self.block_id.to_proto_bytes(), always=True)
        out += _encode_time_field(6, self.timestamp)
        out += encode_bytes_field(7, self.signature)
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Proposal":
        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 0:
                out.type = r.read_varint()
            elif f == 2 and w == 0:
                out.height = r.read_svarint()
            elif f == 3 and w == 0:
                out.round = r.read_svarint()
            elif f == 4 and w == 0:
                v = r.read_svarint()
                out.pol_round = v
            elif f == 5 and w == 2:
                out.block_id = BlockID.from_proto_bytes(r.read_bytes())
            elif f == 6 and w == 2:
                out.timestamp = _decode_time(r.read_bytes())
            elif f == 7 and w == 2:
                out.signature = r.read_bytes()
            else:
                r.skip(w)
        return out


# --- Data / Block -----------------------------------------------------------


def tx_hash(tx: bytes) -> bytes:
    """types/tx.go Tx.Hash: SHA256 of the raw bytes."""
    import hashlib

    return hashlib.sha256(tx).digest()


@dataclass
class Data:
    """types/block.go Data: the transactions."""

    txs: List[bytes] = dc_field(default_factory=list)
    _hash: Optional[bytes] = dc_field(default=None, repr=False, compare=False)

    def hash(self) -> bytes:
        """Merkle root over per-tx SHA-256 hashes (types/tx.go Txs.Hash:
        leaf_i = sha256(tx_i), then HashFromByteSlices)."""
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [tx_hash(tx) for tx in self.txs]
            )
        return self._hash

    def to_proto_bytes(self) -> bytes:
        out = b""
        for tx in self.txs:
            out += encode_bytes_field(1, tx) if tx else encode_message_field(1, b"", always=True)
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Data":
        r = Reader(data)
        txs: List[bytes] = []
        for f, w in r.fields():
            if f == 1 and w == 2:
                txs.append(r.read_bytes())
            else:
                r.skip(w)
        return cls(txs)


@dataclass
class Header:
    """types/block.go:332-358."""

    version: Consensus = dc_field(default_factory=Consensus)
    chain_id: str = ""
    height: int = 0
    time: Timestamp = GO_ZERO_TIME
    last_block_id: BlockID = dc_field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    def hash(self) -> bytes:
        """Merkle tree over the 14 encoded fields (types/block.go:447-490)."""
        if not self.validators_hash:
            return b""
        return merkle.hash_from_byte_slices(
            [
                self.version.to_proto_bytes(),
                cdc_encode_string(self.chain_id),
                cdc_encode_int64(self.height),
                self.time.encode(),
                self.last_block_id.to_proto_bytes(),
                cdc_encode_bytes(self.last_commit_hash),
                cdc_encode_bytes(self.data_hash),
                cdc_encode_bytes(self.validators_hash),
                cdc_encode_bytes(self.next_validators_hash),
                cdc_encode_bytes(self.consensus_hash),
                cdc_encode_bytes(self.app_hash),
                cdc_encode_bytes(self.last_results_hash),
                cdc_encode_bytes(self.evidence_hash),
                cdc_encode_bytes(self.proposer_address),
            ]
        )

    def validate_basic(self) -> None:
        if self.version.block != BLOCK_PROTOCOL:
            raise ValueError(
                f"block protocol is incorrect: got {self.version.block}, "
                f"want {BLOCK_PROTOCOL}"
            )
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError("chainID is too long")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.height == 0:
            raise ValueError("zero Height")
        self.last_block_id.validate_basic()
        for name in (
            "last_commit_hash",
            "data_hash",
            "evidence_hash",
            "validators_hash",
            "next_validators_hash",
            "consensus_hash",
            "last_results_hash",
        ):
            try:
                validate_hash(getattr(self, name))
            except ValueError as e:
                raise ValueError(f"wrong {name}: {e}") from e
        if len(self.proposer_address) != ADDRESS_LEN:
            raise ValueError("invalid ProposerAddress length")

    def to_proto_bytes(self) -> bytes:
        out = encode_message_field(1, self.version.to_proto_bytes(), always=True)
        out += encode_bytes_field(2, self.chain_id.encode("utf-8"))
        out += encode_varint_field(3, self.height)
        out += _encode_time_field(4, self.time)
        out += encode_message_field(5, self.last_block_id.to_proto_bytes(), always=True)
        out += encode_bytes_field(6, self.last_commit_hash)
        out += encode_bytes_field(7, self.data_hash)
        out += encode_bytes_field(8, self.validators_hash)
        out += encode_bytes_field(9, self.next_validators_hash)
        out += encode_bytes_field(10, self.consensus_hash)
        out += encode_bytes_field(11, self.app_hash)
        out += encode_bytes_field(12, self.last_results_hash)
        out += encode_bytes_field(13, self.evidence_hash)
        out += encode_bytes_field(14, self.proposer_address)
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Header":
        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 2:
                out.version = Consensus.from_proto_bytes(r.read_bytes())
            elif f == 2 and w == 2:
                out.chain_id = r.read_bytes().decode("utf-8")
            elif f == 3 and w == 0:
                out.height = r.read_svarint()
            elif f == 4 and w == 2:
                out.time = _decode_time(r.read_bytes())
            elif f == 5 and w == 2:
                out.last_block_id = BlockID.from_proto_bytes(r.read_bytes())
            elif f == 6 and w == 2:
                out.last_commit_hash = r.read_bytes()
            elif f == 7 and w == 2:
                out.data_hash = r.read_bytes()
            elif f == 8 and w == 2:
                out.validators_hash = r.read_bytes()
            elif f == 9 and w == 2:
                out.next_validators_hash = r.read_bytes()
            elif f == 10 and w == 2:
                out.consensus_hash = r.read_bytes()
            elif f == 11 and w == 2:
                out.app_hash = r.read_bytes()
            elif f == 12 and w == 2:
                out.last_results_hash = r.read_bytes()
            elif f == 13 and w == 2:
                out.evidence_hash = r.read_bytes()
            elif f == 14 and w == 2:
                out.proposer_address = r.read_bytes()
            else:
                r.skip(w)
        return out


@dataclass
class Block:
    """types/block.go Block = Header + Data + EvidenceList + LastCommit."""

    header: Header = dc_field(default_factory=Header)
    data: Data = dc_field(default_factory=Data)
    evidence: List[object] = dc_field(default_factory=list)  # Evidence objects
    last_commit: Optional[Commit] = None
    _hash: Optional[bytes] = dc_field(default=None, repr=False, compare=False)

    def hash(self) -> bytes:
        if self._hash is None:
            self.fill_header()
            self._hash = self.header.hash()
        return self._hash

    def evidence_hash(self) -> bytes:
        hashes = [ev.hash() for ev in self.evidence]
        return merkle.hash_from_byte_slices(hashes)

    def fill_header(self) -> None:
        """types/block.go:133-148: derive the data-dependent header hashes."""
        if not self.header.last_commit_hash and self.last_commit is not None:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash()
        if not self.header.evidence_hash:
            self.header.evidence_hash = self.evidence_hash()

    def validate_basic(self) -> None:
        """types/block.go:55-93."""
        self.header.validate_basic()
        if self.last_commit is None:
            raise ValueError("nil LastCommit")
        try:
            self.last_commit.validate_basic()
        except ValueError as e:
            raise ValueError(f"wrong LastCommit: {e}") from e
        if self.header.last_commit_hash != self.last_commit.hash():
            raise ValueError("wrong Header.LastCommitHash")
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong Header.DataHash")
        for i, ev in enumerate(self.evidence):
            ev.validate_basic()
        if self.header.evidence_hash != self.evidence_hash():
            raise ValueError("wrong Header.EvidenceHash")

    def make_block_id(self, part_set_header: Optional[PartSetHeader] = None) -> BlockID:
        return BlockID(self.hash(), part_set_header or PartSetHeader())

    def to_proto_bytes(self) -> bytes:
        out = encode_message_field(1, self.header.to_proto_bytes(), always=True)
        out += encode_message_field(2, self.data.to_proto_bytes(), always=True)
        ev_payload = b""
        for ev in self.evidence:
            ev_payload += encode_message_field(1, ev.to_proto_bytes(), always=True)
        out += encode_message_field(3, ev_payload, always=True)
        if self.last_commit is not None:
            out += encode_message_field(4, self.last_commit.to_proto_bytes(), always=True)
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Block":
        from tendermint_tpu.types import evidence as ev_mod

        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 2:
                out.header = Header.from_proto_bytes(r.read_bytes())
            elif f == 2 and w == 2:
                out.data = Data.from_proto_bytes(r.read_bytes())
            elif f == 3 and w == 2:
                ev_list = r.read_bytes()
                er = Reader(ev_list)
                for ef, ew in er.fields():
                    if ef == 1 and ew == 2:
                        out.evidence.append(
                            ev_mod.evidence_from_proto_bytes(er.read_bytes())
                        )
                    else:
                        er.skip(ew)
            elif f == 4 and w == 2:
                out.last_commit = Commit.from_proto_bytes(r.read_bytes())
            else:
                r.skip(w)
        return out


def make_block(
    height: int,
    txs: List[bytes],
    last_commit: Optional[Commit],
    evidence: Optional[List[object]] = None,
) -> Block:
    """types/block.go MakeBlock."""
    block = Block(
        header=Header(height=height),
        data=Data(txs=list(txs)),
        evidence=list(evidence or []),
        last_commit=last_commit,
    )
    block.fill_header()
    return block
