"""Stateless light-client verification (light/verifier.go).

Both the adjacent and non-adjacent (skipping) paths end in batched commit
verification (types/validation.py), so bisection over long header ranges
rides the device batch verifier — the reference's hot path at
light/verifier.go:70,85,149.
"""

from __future__ import annotations

from typing import Optional

from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.types import Fraction, NotEnoughVotingPowerError
from tendermint_tpu.types.block import Header
from tendermint_tpu.types.light import SignedHeader
from tendermint_tpu.types.validation import (
    verify_commit_light,
    verify_commit_light_trusting,
)
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.verifyd.client import classify as _classify
from tendermint_tpu.verifyd.protocol import CLASS_LIGHT as _CLASS_LIGHT

DEFAULT_TRUST_LEVEL = Fraction(1, 3)


class InvalidHeaderError(ValueError):
    pass


class HeaderExpiredError(ValueError):
    pass


class NewValSetCantBeTrustedError(ValueError):
    """< trustLevel of the trusted valset signed the new header."""


def validate_trust_level(lvl: Fraction) -> None:
    """light/verifier.go:176-186: trustLevel in [1/3, 1)."""
    if (
        lvl.numerator * 3 < lvl.denominator
        or lvl.numerator >= lvl.denominator
        or lvl.denominator == 0
    ):
        raise ValueError(f"trustLevel must be within [1/3, 1], given {lvl}")


def header_expired(
    h: SignedHeader, trusting_period: float, now: Timestamp
) -> bool:
    """light/verifier.go:189-192."""
    expiration_ns = h.header.time.to_unix_ns() + int(trusting_period * 1e9)
    return expiration_ns <= now.to_unix_ns()


def _check_required_header_fields(h: SignedHeader) -> None:
    if h.header is None:
        raise InvalidHeaderError("missing header")
    if not h.header.chain_id or h.header.height == 0 or not h.header.next_validators_hash:
        raise InvalidHeaderError("trusted header missing required fields")


def _verify_new_header_and_vals(
    untrusted: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusted: SignedHeader,
    now: Timestamp,
    max_clock_drift: float,
) -> None:
    """light/verifier.go:236-292."""
    untrusted.validate_basic(trusted.chain_id)
    if untrusted.header.height <= trusted.header.height:
        raise InvalidHeaderError(
            f"expected new header height {untrusted.header.height} to be greater "
            f"than one of old header {trusted.header.height}"
        )
    if untrusted.header.time.to_unix_ns() <= trusted.header.time.to_unix_ns():
        raise InvalidHeaderError(
            "expected new header time to be after old header time"
        )
    if untrusted.header.time.to_unix_ns() >= now.to_unix_ns() + int(
        max_clock_drift * 1e9
    ):
        raise InvalidHeaderError(
            "new header has a time from the future"
        )
    if untrusted.header.validators_hash != untrusted_vals.hash():
        raise InvalidHeaderError(
            "expected new header validators to match those that were supplied"
        )


def verify_non_adjacent(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """light/verifier.go:33-91: trustLevel of old valset + 2/3 of new."""
    _check_required_header_fields(trusted_header)
    if untrusted_header.height == trusted_header.height + 1:
        raise InvalidHeaderError("headers must be non adjacent in height")
    validate_trust_level(trust_level)
    # the TRUSTED header's age gates verification (verifier.go:47): an
    # expired trust root must not anchor new updates, however fresh the
    # untrusted header looks — that is the long-range-attack window
    if header_expired(trusted_header, trusting_period, now):
        raise HeaderExpiredError("old header has expired")
    _verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift
    )
    # Light-client workload class (outermost wins over validation's
    # blocksync default): a verifyd remote may shed this under load.
    with _classify(_CLASS_LIGHT):
        try:
            verify_commit_light_trusting(
                trusted_header.chain_id, trusted_vals, untrusted_header.commit, trust_level
            )
        except NotEnoughVotingPowerError as e:
            raise NewValSetCantBeTrustedError(str(e)) from e
        except ValueError as e:
            raise InvalidHeaderError(str(e)) from e
        try:
            verify_commit_light(
                trusted_header.chain_id,
                untrusted_vals,
                untrusted_header.commit.block_id,
                untrusted_header.height,
                untrusted_header.commit,
            )
        except ValueError as e:
            raise InvalidHeaderError(str(e)) from e


def verify_adjacent(
    trusted_header: SignedHeader,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
) -> None:
    """light/verifier.go:106-152: valhash chain link + 2/3 of new valset."""
    _check_required_header_fields(trusted_header)
    if untrusted_header.height != trusted_header.height + 1:
        raise InvalidHeaderError("headers must be adjacent in height")
    # trusted-header expiry, as above (verifier.go:116)
    if header_expired(trusted_header, trusting_period, now):
        raise HeaderExpiredError("old header has expired")
    _verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift
    )
    if untrusted_header.header.validators_hash != trusted_header.header.next_validators_hash:
        raise InvalidHeaderError(
            "expected old header's next validators to match those from new header"
        )
    with _classify(_CLASS_LIGHT):
        try:
            verify_commit_light(
                trusted_header.chain_id,
                untrusted_vals,
                untrusted_header.commit.block_id,
                untrusted_header.height,
                untrusted_header.commit,
            )
        except ValueError as e:
            raise InvalidHeaderError(str(e)) from e


def verify(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """light/verifier.go:158-174."""
    if untrusted_header.height != trusted_header.height + 1:
        verify_non_adjacent(
            trusted_header,
            trusted_vals,
            untrusted_header,
            untrusted_vals,
            trusting_period,
            now,
            max_clock_drift,
            trust_level,
        )
    else:
        verify_adjacent(
            trusted_header,
            untrusted_header,
            untrusted_vals,
            trusting_period,
            now,
            max_clock_drift,
        )


def verify_backwards(untrusted_header: Header, trusted_header: Header) -> None:
    """light/verifier.go:195-233: hash-chain link going backwards."""
    untrusted_header.validate_basic()
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise InvalidHeaderError("new header belongs to a different chain")
    if untrusted_header.time.to_unix_ns() >= trusted_header.time.to_unix_ns():
        raise InvalidHeaderError(
            "expected older header time to be before new header time"
        )
    if untrusted_header.hash() != trusted_header.last_block_id.hash:
        raise InvalidHeaderError(
            "older header hash does not match trusted header's last block"
        )
