"""EvidencePool: persist, verify, and expire byzantine-behavior evidence.

Mirrors internal/evidence/pool.go:42-411: pending evidence is KV-persisted
(survives restarts), pruned when expired by the consensus params' age
limits, fed to block proposals, marked committed after blocks land, and
populated from consensus's conflicting-vote reports.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.evidence.verify import (
    InvalidEvidenceError,
    verify_duplicate_vote,
    verify_light_client_attack,
)
from tendermint_tpu.state.state import State
from tendermint_tpu.storage.blockstore import BlockStore
from tendermint_tpu.storage.kv import KVStore, MemDB, ordered_key, prefix_end
from tendermint_tpu.types.block import Vote
from tendermint_tpu.types.evidence import (
    DuplicateVoteEvidence,
    Evidence,
    LightClientAttackEvidence,
    evidence_from_proto_bytes,
)
from tendermint_tpu.types.light import SignedHeader

PREFIX_PENDING = 9
PREFIX_COMMITTED = 10


def _pending_key(ev: Evidence) -> bytes:
    return ordered_key(PREFIX_PENDING, ev.height()) + ev.hash()


def _committed_key(ev: Evidence) -> bytes:
    return ordered_key(PREFIX_COMMITTED, ev.height()) + ev.hash()


class EvidencePool:
    def __init__(
        self,
        db: Optional[KVStore] = None,
        state_store=None,
        block_store: Optional[BlockStore] = None,
    ):
        self._db = db or MemDB()
        self.state_store = state_store
        self.block_store = block_store
        self._mtx = threading.Lock()
        self.state: Optional[State] = None
        # Conflicting-vote pairs from consensus, held until the height they
        # belong to commits (pool.go consensusBuffer: evidence can only be
        # verified once the header at its height exists in the store).
        self._consensus_buffer: List[Tuple[Vote, Vote]] = []

    def set_state(self, state: State) -> None:
        self.state = state

    # --- queries -------------------------------------------------------------

    def pending_evidence(self, max_bytes: int) -> Tuple[List[Evidence], int]:
        """pool.go PendingEvidence: size-capped, height order."""
        out: List[Evidence] = []
        total = 0
        for _, v in self._db.iterator(
            ordered_key(PREFIX_PENDING, 0), prefix_end(bytes([PREFIX_PENDING]))
        ):
            ev = evidence_from_proto_bytes(v)
            size = len(v)
            if max_bytes >= 0 and total + size > max_bytes:
                break
            out.append(ev)
            total += size
        return out, total

    def is_pending(self, ev: Evidence) -> bool:
        return self._db.has(_pending_key(ev))

    def is_committed(self, ev: Evidence) -> bool:
        return self._db.has(_committed_key(ev))

    # --- ingestion -----------------------------------------------------------

    def add_evidence(self, ev: Evidence) -> None:
        """pool.go AddEvidence: dedupe, verify, persist."""
        with self._mtx:
            if self.is_pending(ev) or self.is_committed(ev):
                return
            self.verify(ev)
            self._db.set(_pending_key(ev), ev.to_proto_bytes())

    def report_conflicting_votes(self, vote_a: Vote, vote_b: Vote) -> None:
        """pool.go ReportConflictingVotes: buffer the pair; evidence is
        built in update() once the offending height has committed (the
        header at that height must exist for verification)."""
        with self._mtx:
            self._consensus_buffer.append((vote_a, vote_b))

    def _process_consensus_buffer(self, state: State) -> None:
        """pool.go processConsensusBuffer (on Update)."""
        with self._mtx:
            buffered, keep = self._consensus_buffer, []
            self._consensus_buffer = []
        for vote_a, vote_b in buffered:
            if vote_a.height > state.last_block_height:
                keep.append((vote_a, vote_b))  # its height hasn't committed yet
                continue
            try:
                val_set = (
                    self.state_store.load_validators(vote_a.height)
                    if self.state_store is not None
                    else state.validators
                )
                ev = DuplicateVoteEvidence.new(
                    vote_a, vote_b, state.last_block_time, val_set
                )
                self.add_evidence(ev)
            except (ValueError, LookupError, InvalidEvidenceError):
                pass
        if keep:
            with self._mtx:
                self._consensus_buffer.extend(keep)

    # --- verification --------------------------------------------------------

    def verify(self, ev: Evidence) -> None:
        """pool.go verify (abridged): age window + type-specific checks."""
        if self.state is None:
            raise InvalidEvidenceError("evidence pool has no state")
        state = self.state
        ev_params = state.consensus_params.evidence
        # Age by duration is measured against OUR header time at the
        # evidence height (verify.go:39-60) — the evidence's own timestamp
        # field is attacker-controlled and must not gate expiry.
        ev_time = ev.time()
        if self.block_store is not None:
            meta = self.block_store.load_block_meta(ev.height())
            if meta is None:
                raise InvalidEvidenceError(
                    f"don't have block meta at height {ev.height()}"
                )
            ev_time = meta.header.time
        age_blocks = state.last_block_height - ev.height()
        age_ns = state.last_block_time.to_unix_ns() - ev_time.to_unix_ns()
        if (
            age_blocks > ev_params.max_age_num_blocks
            and age_ns > ev_params.max_age_duration * 1e9
        ):
            raise InvalidEvidenceError(
                f"evidence from height {ev.height()} is too old"
            )
        if isinstance(ev, DuplicateVoteEvidence):
            val_set = self._validators_at(ev.height())
            verify_duplicate_vote(ev, state.chain_id, val_set)
            # ABCI fields must match our records (verify.go:120-135).
            _, val = val_set.get_by_address(ev.vote_a.validator_address)
            if ev.validator_power != val.voting_power:
                raise InvalidEvidenceError("validator power mismatch")
            if ev.total_voting_power != val_set.total_voting_power():
                raise InvalidEvidenceError("total voting power mismatch")
        elif isinstance(ev, LightClientAttackEvidence):
            common = self._signed_header_at(ev.common_height)
            trusted = self._signed_header_at(ev.conflicting_block.height)
            if common is None or trusted is None:
                raise InvalidEvidenceError(
                    "don't have headers to verify the light client attack"
                )
            common_vals = self._validators_at(ev.common_height)
            verify_light_client_attack(ev, common, trusted, common_vals)
            # ABCI fields must match our records (verify.go:135-141 /
            # ValidateABCI) — same policy as the duplicate-vote branch.
            if ev.total_voting_power != common_vals.total_voting_power():
                raise InvalidEvidenceError(
                    "total voting power from the evidence and our validator "
                    "set does not match"
                )
            if ev.timestamp != common.header.time:
                raise InvalidEvidenceError(
                    "evidence has a different time to the block it is "
                    "associated with"
                )
        else:
            raise InvalidEvidenceError(f"unknown evidence type {type(ev)}")

    def _validators_at(self, height: int):
        if self.state_store is None:
            raise InvalidEvidenceError("no state store to load validators")
        return self.state_store.load_validators(height)

    def _signed_header_at(self, height: int) -> Optional[SignedHeader]:
        if self.block_store is None:
            return None
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if meta is None or commit is None:
            return None
        return SignedHeader(header=meta.header, commit=commit)

    # --- consensus hooks -----------------------------------------------------

    def check_evidence(self, evidence: List[Evidence]) -> None:
        """pool.go CheckEvidence: verify block evidence, dedupe committed."""
        seen = set()
        for ev in evidence:
            key = ev.hash()
            if key in seen:
                raise InvalidEvidenceError("duplicate evidence in block")
            seen.add(key)
            if self.is_committed(ev):
                raise InvalidEvidenceError("evidence was already committed")
            if not self.is_pending(ev):
                self.verify(ev)

    def update(self, state: State, block_evidence: List[Evidence]) -> None:
        """pool.go Update: mark committed, prune expired, drain buffered
        conflicting votes now that their height is in the store."""
        self.state = state
        with self._mtx:
            for ev in block_evidence:
                self._db.set(_committed_key(ev), b"\x01")
                self._db.delete(_pending_key(ev))
            self._prune_expired(state)
        self._process_consensus_buffer(state)

    def _prune_expired(self, state: State) -> None:
        ev_params = state.consensus_params.evidence
        batch = self._db.new_batch()
        for k, v in self._db.iterator(
            ordered_key(PREFIX_PENDING, 0), prefix_end(bytes([PREFIX_PENDING]))
        ):
            ev = evidence_from_proto_bytes(v)
            age_blocks = state.last_block_height - ev.height()
            age_ns = state.last_block_time.to_unix_ns() - ev.time().to_unix_ns()
            if (
                age_blocks > ev_params.max_age_num_blocks
                and age_ns > ev_params.max_age_duration * 1e9
            ):
                batch.delete(k)
        batch.write()
