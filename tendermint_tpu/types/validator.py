"""Validator and sort orders (types/validator.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from tendermint_tpu.crypto import PubKey, pubkey_to_proto
from tendermint_tpu.encoding.proto import encode_message_field, encode_varint_field

INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)


def safe_add_clip(a: int, b: int) -> int:
    """int64 addition clipped at the bounds (libs math safe ops)."""
    return max(INT64_MIN, min(INT64_MAX, a + b))


def safe_sub_clip(a: int, b: int) -> int:
    return max(INT64_MIN, min(INT64_MAX, a - b))


def go_div(a: int, b: int) -> int:
    """Go int64 division: truncation toward zero (vs Python's floor)."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


@dataclass
class Validator:
    pub_key: PubKey
    voting_power: int
    proposer_priority: int = 0
    address: bytes = field(default=b"")

    def __post_init__(self):
        if not self.address:
            self.address = self.pub_key.address()

    def copy(self) -> "Validator":
        return Validator(
            self.pub_key, self.voting_power, self.proposer_priority, self.address
        )

    def bytes(self) -> bytes:
        """SimpleValidator proto {pub_key=1, voting_power=2} — the merkle
        leaf of the validator-set hash (types/validator.go:154-170)."""
        pk = pubkey_to_proto(self.pub_key)
        return encode_message_field(1, pk) + encode_varint_field(
            2, self.voting_power
        )

    def compare_proposer_priority(self, other: Optional["Validator"]) -> "Validator":
        """Higher priority wins; ties go to the lower address
        (types/validator.go:101-121)."""
        if other is None:
            return self
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValueError("cannot compare identical validators")

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator has nil pubkey")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != 20:
            raise ValueError(f"validator address must be 20 bytes: {self.address.hex()}")

    def to_proto_bytes(self) -> bytes:
        """tendermint.types.Validator {address=1, pub_key=2 non-nullable,
        voting_power=3, proposer_priority=4} (types/validator.go ToProto)."""
        from tendermint_tpu.encoding.proto import encode_bytes_field

        return (
            encode_bytes_field(1, self.address)
            + encode_message_field(2, pubkey_to_proto(self.pub_key), always=True)
            + encode_varint_field(3, self.voting_power)
            + encode_varint_field(4, self.proposer_priority)
        )

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Validator":
        from tendermint_tpu.crypto import pubkey_from_proto
        from tendermint_tpu.encoding.proto import Reader

        r = Reader(data)
        address = b""
        pub_key = None
        voting_power = proposer_priority = 0
        for f, w in r.fields():
            if f == 1 and w == 2:
                address = r.read_bytes()
            elif f == 2 and w == 2:
                pub_key = pubkey_from_proto(r.read_bytes())
            elif f == 3 and w == 0:
                voting_power = r.read_svarint()
            elif f == 4 and w == 0:
                proposer_priority = r.read_svarint()
            else:
                r.skip(w)
        if pub_key is None:
            raise ValueError("validator proto missing pubkey")
        out = cls(pub_key, voting_power, proposer_priority, address or b"\x00")
        # Preserve the wire address verbatim (even empty) so re-serialization
        # is byte-identical; __post_init__ would otherwise derive it
        # (reference keeps vp.GetAddress() as-is, validator.go:205).
        out.address = address
        return out


def sort_key_by_voting_power(v: Validator):
    """ValidatorsByVotingPower: power descending, address ascending
    (types/validator.go:745-760)."""
    return (-v.voting_power, v.address)


def sort_key_by_address(v: Validator):
    return v.address
