"""SignedHeader and LightBlock (types/light.go).

The light client's unit of verification: a header plus the commit that
signed it, and the validator set that produced the commit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from tendermint_tpu.encoding.proto import Reader, encode_message_field, encode_varint_field
from tendermint_tpu.types.block import Commit, Header
from tendermint_tpu.types.validator_set import ValidatorSet


@dataclass
class SignedHeader:
    """types/light.go SignedHeader {header=1, commit=2}."""

    header: Optional[Header] = None
    commit: Optional[Commit] = None

    @property
    def height(self) -> int:
        return self.header.height if self.header else 0

    @property
    def chain_id(self) -> str:
        return self.header.chain_id if self.header else ""

    def hash(self) -> bytes:
        return self.header.hash() if self.header else b""

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go SignedHeader.ValidateBasic."""
        if self.header is None:
            raise ValueError("missing header")
        if self.commit is None:
            raise ValueError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header belongs to another chain {self.header.chain_id!r}, "
                f"not {chain_id!r}"
            )
        if self.commit.height != self.header.height:
            raise ValueError(
                f"header and commit height mismatch: {self.header.height} vs "
                f"{self.commit.height}"
            )
        if self.header.hash() != self.commit.block_id.hash:
            raise ValueError("commit signs a different block than the header")

    def to_proto_bytes(self) -> bytes:
        out = b""
        if self.header is not None:
            out += encode_message_field(1, self.header.to_proto_bytes(), always=True)
        if self.commit is not None:
            out += encode_message_field(2, self.commit.to_proto_bytes(), always=True)
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "SignedHeader":
        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 2:
                out.header = Header.from_proto_bytes(r.read_bytes())
            elif f == 2 and w == 2:
                out.commit = Commit.from_proto_bytes(r.read_bytes())
            else:
                r.skip(w)
        return out


@dataclass
class LightBlock:
    """types/light.go LightBlock {signed_header=1, validator_set=2}."""

    signed_header: Optional[SignedHeader] = None
    validator_set: Optional[ValidatorSet] = None

    @property
    def height(self) -> int:
        return self.signed_header.height if self.signed_header else 0

    @property
    def header(self) -> Optional[Header]:
        return self.signed_header.header if self.signed_header else None

    def hash(self) -> bytes:
        return self.signed_header.hash() if self.signed_header else b""

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go LightBlock.ValidateBasic."""
        if self.signed_header is None:
            raise ValueError("missing signed header")
        if self.validator_set is None:
            raise ValueError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        if self.signed_header.header.validators_hash != self.validator_set.hash():
            raise ValueError(
                "expected validator hash of header to match validator set hash"
            )

    def to_proto_bytes(self) -> bytes:
        out = b""
        if self.signed_header is not None:
            out += encode_message_field(
                1, self.signed_header.to_proto_bytes(), always=True
            )
        if self.validator_set is not None:
            out += encode_message_field(
                2, self.validator_set.to_proto_bytes(), always=True
            )
        return out

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "LightBlock":
        r = Reader(data)
        out = cls()
        for f, w in r.fields():
            if f == 1 and w == 2:
                out.signed_header = SignedHeader.from_proto_bytes(r.read_bytes())
            elif f == 2 and w == 2:
                out.validator_set = ValidatorSet.from_proto_bytes(r.read_bytes())
            else:
                r.skip(w)
        return out
