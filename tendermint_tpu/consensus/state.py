"""The BFT consensus state machine (internal/consensus/state.go).

One single-threaded ``receive_routine`` drains a queue of peer messages,
internal (own) messages, and timeouts; every input is WAL-logged before
processing (own messages fsync'd — state.go:956-970). Round transitions
follow the reference's enterX graph exactly:

    NewHeight -> NewRound -> Propose -> Prevote -> [PrevoteWait]
              -> Precommit -> [PrecommitWait] -> Commit -> NewHeight

Gossip I/O is abstracted behind a ``Broadcaster``; the node wires it to
the p2p reactor, tests wire the validators' queues to each other.
"""

from __future__ import annotations

import queue
import threading
import time as _time
from typing import Callable, List, Optional

from tendermint_tpu.consensus import cstypes
from tendermint_tpu.consensus.cstypes import HeightVoteSet, RoundStep
from tendermint_tpu.consensus.ticker import TimeoutTicker
from tendermint_tpu.consensus.wal import (
    WAL,
    BlockPartInfo,
    EndHeightMessage,
    MsgInfo,
    NilWAL,
    TimeoutInfo,
)
from tendermint_tpu.encoding.canonical import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Timestamp,
)
from tendermint_tpu.libs import tracing
from tendermint_tpu.privval.base import PrivValidator
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import State as SMState
from tendermint_tpu.storage.blockstore import BlockStore
from tendermint_tpu.types.block import (
    BLOCK_PART_SIZE_BYTES,
    Block,
    BlockID,
    ExtendedCommit,
    PartSetHeader,
    Proposal,
    Vote,
)
from tendermint_tpu.types.part_set import Part, PartSet
from tendermint_tpu.types.vote_set import (
    ConflictingVotesError,
    VoteSet,
    vote_set_from_commit,
)


class DoubleSigningRiskError(RuntimeError):
    """state.go ErrSignatureFoundInPastBlocks: our key signed a recent
    commit — joining consensus now risks equivocation."""


class Broadcaster:
    """Outbound gossip seam (the consensus reactor implements this)."""

    def broadcast_proposal(self, proposal: Proposal) -> None: ...

    def broadcast_block_part(self, height: int, round_: int, part: Part) -> None: ...

    def broadcast_vote(self, vote: Vote) -> None: ...

    def broadcast_has_vote(
        self, height: int, round_: int, type_: int, index: int
    ) -> None: ...

    def broadcast_new_round_step(self, rs) -> None: ...


class ConsensusState:
    """internal/consensus/state.go State."""

    def __init__(
        self,
        sm_state: SMState,
        block_exec: BlockExecutor,
        block_store: BlockStore,
        priv_validator: Optional[PrivValidator] = None,
        wal: Optional[WAL] = None,
        broadcaster: Optional[Broadcaster] = None,
        now: Optional[Callable[[], Timestamp]] = None,
        on_committed: Optional[Callable[[int], None]] = None,
        metrics=None,
        logger=None,
        double_sign_check_height: int = 0,
    ):
        from tendermint_tpu.libs.log import NOP_LOGGER
        from tendermint_tpu.libs.metrics import ConsensusMetrics

        self.block_exec = block_exec
        self.block_store = block_store
        self.priv_validator = priv_validator
        self.priv_pub_key = priv_validator.get_pub_key() if priv_validator else None
        self.wal = wal or NilWAL()
        self._wal_is_real = not isinstance(self.wal, NilWAL)
        self.broadcaster = broadcaster or Broadcaster()
        self.event_bus = None  # set by the node (node.go wires eventbus)
        self._now = now or (lambda: Timestamp.from_unix_ns(_time.time_ns()))
        self.on_committed = on_committed
        self.metrics = metrics or ConsensusMetrics.nop()
        self.logger = (logger or NOP_LOGGER).with_fields(module="consensus")
        self._last_commit_walltime: Optional[float] = None
        # Double-signing risk reduction lookback (config.go:961
        # double-sign-check-height; 0 disables).
        self.double_sign_check_height = double_sign_check_height
        self._ds_cleared_height: Optional[int] = None

        self.rs = cstypes.RoundState()
        self.state = SMState()  # set by _update_to_state

        self.peer_queue: "queue.Queue" = queue.Queue(maxsize=1000)
        self.internal_queue: "queue.Queue" = queue.Queue(maxsize=1000)
        self.timeout_queue: "queue.Queue" = queue.Queue(maxsize=100)
        self.ticker = TimeoutTicker(self.timeout_queue.put)

        self._thread: Optional[threading.Thread] = None
        self._stop_flag = threading.Event()
        self._mtx = threading.RLock()
        self.decide_proposal = self._default_decide_proposal  # test override seam

        self._reconstruct_and_update(sm_state)

    # --- lifecycle ----------------------------------------------------------

    def _reconstruct_and_update(self, sm_state: SMState) -> None:
        if (
            sm_state.last_block_height > 0
            and sm_state.last_block_height >= sm_state.initial_height
        ):
            seen = self.block_store.load_seen_commit()
            if seen is None or seen.height != sm_state.last_block_height:
                raise RuntimeError(
                    f"failed to reconstruct last commit; seen commit missing "
                    f"for height {sm_state.last_block_height}"
                )
            self.rs.last_commit = vote_set_from_commit(
                sm_state.chain_id, seen, sm_state.last_validators
            )
        self._update_to_state(sm_state)

    def start(self) -> None:
        """OnStart (state.go:399): WAL + replay + double-sign risk check
        + receive routine + round 0."""
        self.wal.start()
        self._catchup_replay()
        self.check_double_signing_risk()
        self._stop_flag.clear()
        self._thread = threading.Thread(
            target=self._receive_routine, name="consensus-receive", daemon=True
        )
        self._thread.start()
        self._schedule_round_0()

    def check_double_signing_risk(self, height: Optional[int] = None) -> None:
        """state.go checkDoubleSigningRisk:2663 — before joining
        consensus, look back ``double_sign_check_height`` blocks for a
        commit signature from OUR key. Finding one means another process
        with this key signed recently (or we restarted into rounds we
        already signed): refuse to start rather than risk equivocating.
        0 disables (config.go:961 default).

        Public: the Node calls it eagerly at start so the common restart
        case fails the whole process; start() calls it again in case the
        height moved (blocksync), and a height already cleared is not
        re-scanned."""
        if height is None:
            height = self.rs.height
        if (
            self.priv_validator is None
            or self.priv_pub_key is None
            or self.double_sign_check_height <= 0
            or height <= 0
            or self._ds_cleared_height == height
        ):
            return
        from tendermint_tpu.types.block import BLOCK_ID_FLAG_COMMIT

        val_addr = self.priv_pub_key.address()
        lookback = min(self.double_sign_check_height, height)
        for i in range(1, lookback):
            commit = self.block_store.load_block_commit(height - i)
            if commit is None:
                commit = self.block_store.load_seen_commit()
                if commit is None or commit.height != height - i:
                    continue
            for sig_idx, s in enumerate(commit.signatures):
                if (
                    s.block_id_flag == BLOCK_ID_FLAG_COMMIT
                    and s.validator_address == val_addr
                ):
                    raise DoubleSigningRiskError(
                        f"signature from this validator's key found "
                        f"{i} block(s) back (height {height - i}, sig "
                        f"#{sig_idx}); refusing to join consensus"
                    )
        self._ds_cleared_height = height

    def stop(self) -> None:
        self._stop_flag.set()
        self.ticker.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.wal.stop()

    # --- external inputs ----------------------------------------------------

    def add_vote_from_peer(self, vote: Vote, peer_id: str) -> None:
        self.peer_queue.put(MsgInfo(vote, peer_id))

    def add_proposal_from_peer(self, proposal: Proposal, peer_id: str) -> None:
        self.peer_queue.put(MsgInfo(proposal, peer_id))

    def add_block_part_from_peer(
        self, height: int, round_: int, part: Part, peer_id: str
    ) -> None:
        self.peer_queue.put(MsgInfo(BlockPartInfo(height, round_, part), peer_id))

    def _send_internal(self, msg_info: MsgInfo) -> None:
        self.internal_queue.put(msg_info)

    # --- the single-threaded loop -------------------------------------------

    def _receive_routine(self) -> None:
        """state.go:888-991: WAL-before-process; internal msgs fsync'd.
        Timeouts are drained every iteration so peer traffic cannot starve
        round progression (the Go select is fair across all channels)."""
        while not self._stop_flag.is_set():
            processed = False
            # Timeouts first: rare, cheap, and liveness-critical.
            try:
                while True:
                    ti = self.timeout_queue.get_nowait()
                    with self._mtx:
                        self.wal.write(ti)
                        if self._wal_is_real:
                            self.metrics.wal_writes.inc()
                        self._handle_timeout(ti)
                    processed = True
            except queue.Empty:
                pass
            try:
                mi = self.internal_queue.get_nowait()
                with self._mtx:
                    self.wal.write_sync(mi)  # fsync own messages (state.go:964)
                    if self._wal_is_real:
                        self.metrics.wal_writes.inc()
                    self._handle_msg(mi)
                processed = True
            except queue.Empty:
                pass
            if not processed:
                try:
                    mi = self.peer_queue.get_nowait()
                    with self._mtx:
                        self.wal.write(mi)
                        if self._wal_is_real:
                            self.metrics.wal_writes.inc()
                        # Peer input must never kill the loop: malformed
                        # messages are dropped (state.go handleMsg logs
                        # and continues).
                        try:
                            self._handle_msg(mi)
                        except Exception:
                            pass
                    processed = True
                except queue.Empty:
                    pass
            if not processed:
                _time.sleep(0.002)

    def _handle_msg(self, mi: MsgInfo) -> None:
        msg = mi.msg
        if isinstance(msg, Proposal):
            self._set_proposal(msg, self._now())
        elif isinstance(msg, BlockPartInfo):
            added = self._add_proposal_block_part(msg, mi.peer_id)
            if added and self.rs.proposal_block_parts.is_complete():
                self._handle_complete_proposal()
        elif isinstance(msg, Vote):
            self._try_add_vote(msg, mi.peer_id)
        else:
            raise TypeError(f"unknown msg type {type(msg)}")

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """state.go:1035-1090: stale filter + dispatch."""
        rs = self.rs
        if (
            ti.height != rs.height
            or ti.round < rs.round
            or (ti.round == rs.round and ti.step < rs.step)
        ):
            return
        if ti.step == RoundStep.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == RoundStep.NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif ti.step == RoundStep.PROPOSE:
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == RoundStep.PREVOTE_WAIT:
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == RoundStep.PRECOMMIT_WAIT:
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)

    # --- state update -------------------------------------------------------

    def _update_to_state(self, sm_state: SMState) -> None:
        """state.go updateToState (abridged faithfully)."""
        rs = self.rs
        if not self.state.is_empty() and (
            sm_state.last_block_height <= self.state.last_block_height
        ):
            self._new_step()
            return

        if sm_state.last_block_height == 0:
            rs.last_commit = None
        elif rs.commit_round > -1 and rs.votes is not None:
            precommits = rs.votes.precommits(rs.commit_round)
            if precommits is None or not precommits.has_two_thirds_majority():
                raise RuntimeError(
                    "wanted to form a commit, but precommits didn't have 2/3+"
                )
            rs.last_commit = precommits

        height = sm_state.last_block_height + 1
        if height == 1:
            height = sm_state.initial_height

        rs.height = height
        rs.round = 0
        rs.step = RoundStep.NEW_HEIGHT
        commit_base = (
            rs.commit_time
            if rs.commit_time.to_unix_ns() and rs.commit_time != cstypes.GO_ZERO_TIME
            else self._now()
        )
        rs.start_time = Timestamp.from_unix_ns(
            commit_base.to_unix_ns()
            + int(sm_state.consensus_params.timeout.commit * 1e9)
        )
        rs.validators = sm_state.validators
        rs.proposal = None
        rs.proposal_receive_time = cstypes.GO_ZERO_TIME
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        if sm_state.consensus_params.abci.vote_extensions_enabled(height):
            rs.votes = HeightVoteSet.extended(
                sm_state.chain_id, height, sm_state.validators
            )
        else:
            rs.votes = HeightVoteSet(sm_state.chain_id, height, sm_state.validators)
        rs.commit_round = -1
        rs.last_validators = sm_state.last_validators
        rs.triggered_timeout_precommit = False
        self.state = sm_state
        self._new_step()

    def _new_step(self) -> None:
        self.broadcaster.broadcast_new_round_step(self.rs)
        self._publish_event(
            "publish_event_new_round_step",
            lambda eb: eb.EventDataRoundState(
                height=self.rs.height,
                round=self.rs.round,
                step=self.rs.step.name,
            ),
        )

    def _publish_event(self, publisher: str, build) -> None:
        """Fire a consensus event onto the node's bus (state.go fires
        NewRound/NewRoundStep/CompleteProposal/Vote via its eventbus).
        The bus is optional — tests drive the SM without a node."""
        bus = self.event_bus
        if bus is None:
            return
        try:
            from tendermint_tpu import eventbus as eb

            getattr(bus, publisher)(build(eb))
        except Exception:
            pass

    def _schedule_round_0(self) -> None:
        delay = max(
            0.0, (self.rs.start_time.to_unix_ns() - self._now().to_unix_ns()) / 1e9
        )
        self.ticker.schedule_timeout(
            delay, self.rs.height, 0, RoundStep.NEW_HEIGHT
        )

    # --- round transitions ---------------------------------------------------

    def _enter_new_round(self, height: int, round_: int) -> None:
        """state.go:1178-1253."""
        rs = self.rs
        if (
            rs.height != height
            or round_ < rs.round
            or (rs.round == round_ and rs.step != RoundStep.NEW_HEIGHT)
        ):
            return
        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)
        rs.round = round_
        rs.step = RoundStep.NEW_ROUND
        rs.validators = validators
        if round_ != 0:
            rs.proposal = None
            rs.proposal_receive_time = cstypes.GO_ZERO_TIME
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)  # track next round for round-skipping
        rs.triggered_timeout_precommit = False
        self.metrics.height.set(height)
        self.metrics.rounds.set(round_)
        self.metrics.validators.set(len(validators.validators))
        self.logger.with_fields(height=height, round=round_).debug(
            "entering new round"
        )
        tracing.instant("new_round", height=height, round=round_)
        self._publish_event(
            "publish_event_new_round",
            lambda eb: eb.EventDataNewRound(
                height=height,
                round=round_,
                step=rs.step.name,
                proposer_address=validators.get_proposer().address,
            ),
        )
        self._enter_propose(height, round_)

    def _enter_propose(self, height: int, round_: int) -> None:
        """state.go:1273-1351."""
        rs = self.rs
        if (
            rs.height != height
            or round_ < rs.round
            or (rs.round == round_ and rs.step >= RoundStep.PROPOSE)
        ):
            return
        log = self.logger.with_fields(height=height, round=round_)
        log.debug("entering propose step")
        with tracing.span("propose", step="propose", height=height, round=round_):
            try:
                # Schedule prevote-on-timeout before doing anything slow.
                self.ticker.schedule_timeout(
                    self.state.consensus_params.timeout.propose_timeout(round_),
                    height,
                    round_,
                    RoundStep.PROPOSE,
                )
                if self.priv_validator is None or self.priv_pub_key is None:
                    return
                addr = self.priv_pub_key.address()
                if not rs.validators.has_address(addr):
                    return
                if self._is_proposer(addr):
                    self.decide_proposal(height, round_)
            finally:
                rs.round = round_
                rs.step = RoundStep.PROPOSE
                self._new_step()
                if self._is_proposal_complete():
                    self._enter_prevote(height, rs.round)

    def _is_proposer(self, address: bytes) -> bool:
        return self.rs.validators.get_proposer().address == address

    def _default_decide_proposal(self, height: int, round_: int) -> None:
        """state.go:1353-1409."""
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            block = self._create_proposal_block()
            if block is None:
                return
            block_parts = PartSet.from_data(
                block.to_proto_bytes(), BLOCK_PART_SIZE_BYTES
            )
        self.wal.flush_and_sync()
        prop_block_id = BlockID(block.hash(), block_parts.header())
        proposal = Proposal(
            height=height,
            round=round_,
            pol_round=rs.valid_round,
            block_id=prop_block_id,
            timestamp=block.header.time,
        )
        try:
            self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception:
            return
        self._send_internal(MsgInfo(proposal, ""))
        for i in range(block_parts.total):
            self._send_internal(
                MsgInfo(BlockPartInfo(rs.height, rs.round, block_parts.get_part(i)), "")
            )
        self.broadcaster.broadcast_proposal(proposal)
        for i in range(block_parts.total):
            self.broadcaster.broadcast_block_part(
                rs.height, rs.round, block_parts.get_part(i)
            )

    def _create_proposal_block(self) -> Optional[Block]:
        """state.go:1428-1477."""
        rs = self.rs
        if rs.height == self.state.initial_height:
            last_ext_commit = ExtendedCommit()
        elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            last_ext_commit = rs.last_commit.make_extended_commit()
        else:
            return None
        proposer_addr = self.priv_pub_key.address()
        return self.block_exec.create_proposal_block(
            rs.height, self.state, last_ext_commit, proposer_addr
        )

    def _is_proposal_complete(self) -> bool:
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _enter_prevote(self, height: int, round_: int) -> None:
        """state.go:1478-1510."""
        rs = self.rs
        if (
            rs.height != height
            or round_ < rs.round
            or (rs.round == round_ and rs.step >= RoundStep.PREVOTE)
        ):
            return
        self.logger.with_fields(height=height, round=round_).debug(
            "entering prevote step"
        )
        with tracing.span("prevote", step="prevote", height=height, round=round_):
            self._do_prevote(height, round_)
            rs.round = round_
            rs.step = RoundStep.PREVOTE
            self._new_step()

    def _proposal_is_timely(self) -> bool:
        rs = self.rs
        sp = self.state.consensus_params.synchrony.in_round(rs.round)
        ts = rs.proposal.timestamp.to_unix_ns()
        recv = rs.proposal_receive_time.to_unix_ns()
        lhs = ts - int(sp.precision * 1e9)
        rhs = ts + int(sp.message_delay * 1e9) + int(sp.precision * 1e9)
        return lhs <= recv <= rhs

    def _do_prevote(self, height: int, round_: int) -> None:
        """state.go defaultDoPrevote:1512-1645 (PBTS checks included)."""
        rs = self.rs
        if rs.proposal_block is None or rs.proposal is None:
            self._sign_add_vote(SIGNED_MSG_TYPE_PREVOTE, b"", PartSetHeader())
            return
        if rs.proposal.timestamp != rs.proposal_block.header.time:
            self._sign_add_vote(SIGNED_MSG_TYPE_PREVOTE, b"", PartSetHeader())
            return
        if (
            rs.proposal.pol_round == -1
            and rs.locked_round == -1
            and not self._proposal_is_timely()
        ):
            self._sign_add_vote(SIGNED_MSG_TYPE_PREVOTE, b"", PartSetHeader())
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except ValueError:
            self._sign_add_vote(SIGNED_MSG_TYPE_PREVOTE, b"", PartSetHeader())
            return
        if not self.block_exec.process_proposal(rs.proposal_block, self.state):
            self._sign_add_vote(SIGNED_MSG_TYPE_PREVOTE, b"", PartSetHeader())
            return

        if rs.proposal.pol_round == -1:
            if rs.locked_round == -1 or (
                rs.locked_block is not None
                and rs.proposal_block.hash() == rs.locked_block.hash()
            ):
                self._sign_add_vote(
                    SIGNED_MSG_TYPE_PREVOTE,
                    rs.proposal_block.hash(),
                    rs.proposal_block_parts.header(),
                )
                return
        else:
            prevotes = rs.votes.prevotes(rs.proposal.pol_round)
            if prevotes is not None:
                block_id, ok = prevotes.two_thirds_majority()
                if (
                    ok
                    and rs.proposal_block.hash() == block_id.hash
                    and 0 <= rs.proposal.pol_round < rs.round
                ):
                    if rs.locked_round <= rs.proposal.pol_round or (
                        rs.locked_block is not None
                        and rs.proposal_block.hash() == rs.locked_block.hash()
                    ):
                        self._sign_add_vote(
                            SIGNED_MSG_TYPE_PREVOTE,
                            rs.proposal_block.hash(),
                            rs.proposal_block_parts.header(),
                        )
                        return
        self._sign_add_vote(SIGNED_MSG_TYPE_PREVOTE, b"", PartSetHeader())

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if (
            rs.height != height
            or round_ < rs.round
            or (rs.round == round_ and rs.step >= RoundStep.PREVOTE_WAIT)
        ):
            return
        rs.round = round_
        rs.step = RoundStep.PREVOTE_WAIT
        self._new_step()
        self.ticker.schedule_timeout(
            self.state.consensus_params.timeout.vote_timeout(round_),
            height,
            round_,
            RoundStep.PREVOTE_WAIT,
        )

    def _enter_precommit(self, height: int, round_: int) -> None:
        """state.go:1682-1798."""
        rs = self.rs
        if (
            rs.height != height
            or round_ < rs.round
            or (rs.round == round_ and rs.step >= RoundStep.PRECOMMIT)
        ):
            return
        self.logger.with_fields(height=height, round=round_).debug(
            "entering precommit step"
        )
        with tracing.span("precommit", step="precommit", height=height, round=round_):
            try:
                prevotes = rs.votes.prevotes(round_)
                block_id, ok = (
                    prevotes.two_thirds_majority() if prevotes else (BlockID(), False)
                )
                if not ok:
                    self._sign_add_vote(SIGNED_MSG_TYPE_PRECOMMIT, b"", PartSetHeader())
                    return
                if block_id.is_nil():
                    self._sign_add_vote(SIGNED_MSG_TYPE_PRECOMMIT, b"", PartSetHeader())
                    return
                if rs.proposal is None or rs.proposal_block is None:
                    self._sign_add_vote(SIGNED_MSG_TYPE_PRECOMMIT, b"", PartSetHeader())
                    return
                if rs.proposal.timestamp != rs.proposal_block.header.time:
                    self._sign_add_vote(SIGNED_MSG_TYPE_PRECOMMIT, b"", PartSetHeader())
                    return
                if (
                    rs.locked_block is not None
                    and rs.locked_block.hash() == block_id.hash
                ):
                    rs.locked_round = round_
                    self._sign_add_vote(
                        SIGNED_MSG_TYPE_PRECOMMIT, block_id.hash, block_id.part_set_header
                    )
                    return
                if rs.proposal_block.hash() == block_id.hash:
                    self.block_exec.validate_block(self.state, rs.proposal_block)
                    rs.locked_round = round_
                    rs.locked_block = rs.proposal_block
                    rs.locked_block_parts = rs.proposal_block_parts
                    self._sign_add_vote(
                        SIGNED_MSG_TYPE_PRECOMMIT, block_id.hash, block_id.part_set_header
                    )
                    return
                # Polka for a block we don't have: fetch it, precommit nil.
                if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                    block_id.part_set_header
                ):
                    rs.proposal_block = None
                    rs.proposal_block_parts = PartSet(block_id.part_set_header)
                self._sign_add_vote(SIGNED_MSG_TYPE_PRECOMMIT, b"", PartSetHeader())
            finally:
                rs.round = round_
                rs.step = RoundStep.PRECOMMIT
                self._new_step()

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            round_ == rs.round and rs.triggered_timeout_precommit
        ):
            return
        rs.triggered_timeout_precommit = True
        self._new_step()
        self.ticker.schedule_timeout(
            self.state.consensus_params.timeout.vote_timeout(round_),
            height,
            round_,
            RoundStep.PRECOMMIT_WAIT,
        )

    def _enter_commit(self, height: int, commit_round: int) -> None:
        """state.go:1837-1902."""
        rs = self.rs
        if rs.height != height or rs.step >= RoundStep.COMMIT:
            return
        self.logger.with_fields(height=height, round=commit_round).debug(
            "entering commit step"
        )
        with tracing.span("commit", step="commit", height=height, round=commit_round):
            try:
                precommits = rs.votes.precommits(commit_round)
                block_id, ok = precommits.two_thirds_majority()
                if not ok:
                    raise RuntimeError("enterCommit expects +2/3 precommits")
                if (
                    rs.locked_block is not None
                    and rs.locked_block.hash() == block_id.hash
                ):
                    rs.proposal_block = rs.locked_block
                    rs.proposal_block_parts = rs.locked_block_parts
                if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
                    if (
                        rs.proposal_block_parts is None
                        or not rs.proposal_block_parts.has_header(block_id.part_set_header)
                    ):
                        rs.proposal_block = None
                        rs.proposal_block_parts = PartSet(block_id.part_set_header)
            finally:
                rs.step = RoundStep.COMMIT
                rs.commit_round = commit_round
                rs.commit_time = self._now()
                self._new_step()
                self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok or block_id.is_nil():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """state.go:1931-2040."""
        rs = self.rs
        if rs.height != height or rs.step != RoundStep.COMMIT:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        if not ok:
            raise RuntimeError("cannot finalize commit; no 2/3 majority")
        if not block_parts.has_header(block_id.part_set_header):
            raise RuntimeError("expected ProposalBlockParts header to match commit")
        if block.hash() != block_id.hash:
            raise RuntimeError("cannot finalize commit; block hash mismatch")
        self.block_exec.validate_block(self.state, block)

        if self.block_store.height() < block.header.height:
            seen_ec = precommits.make_extended_commit()
            if self.state.consensus_params.abci.vote_extensions_enabled(
                block.header.height
            ):
                self.block_store.save_block_with_extended_commit(
                    block, block_parts, seen_ec
                )
            else:
                self.block_store.save_block(block, block_parts, seen_ec.to_commit())

        # WAL end-height marker AFTER the block is durably stored.
        self.wal.write_sync(EndHeightMessage(height))

        state_copy = self.state.copy()
        state_copy = self.block_exec.apply_block(
            state_copy, BlockID(block.hash(), block_parts.header()), block
        )
        self._update_to_state(state_copy)

        now_wall = _time.monotonic()
        if self._last_commit_walltime is not None:
            self.metrics.block_interval_seconds.observe(
                now_wall - self._last_commit_walltime
            )
        self._last_commit_walltime = now_wall
        self.metrics.num_txs.set(len(block.data.txs))
        # block_parts carries the serialized block; don't re-encode under
        # the consensus mutex just to measure the size
        self.metrics.block_size_bytes.set(block_parts.byte_size)
        self.metrics.total_txs.inc(len(block.data.txs))
        n_absent = sum(
            1 for cs in block.last_commit.signatures if cs.is_absent()
        ) if block.last_commit else 0
        self.metrics.missing_validators.set(n_absent)
        self.logger.with_fields(height=height, round=rs.commit_round).info(
            "committed block",
            hash=block.hash(),
            txs=len(block.data.txs),
        )

        if self.priv_validator is not None:
            self.priv_pub_key = self.priv_validator.get_pub_key()
        if self.on_committed is not None:
            self.on_committed(height)
        self._schedule_round_0()

    # --- proposal/part/vote ingestion ----------------------------------------

    def _set_proposal(self, proposal: Proposal, recv_time: Timestamp) -> None:
        """state.go defaultSetProposal:2130-2175."""
        rs = self.rs
        if rs.proposal is not None or proposal is None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            0 <= proposal.pol_round and proposal.pol_round >= proposal.round
        ):
            raise ValueError("invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
            proposal.sign_bytes(self.state.chain_id), proposal.signature
        ):
            raise ValueError("invalid proposal signature")
        rs.proposal = proposal
        rs.proposal_receive_time = recv_time
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(proposal.block_id.part_set_header)

    def _add_proposal_block_part(self, msg: BlockPartInfo, peer_id: str) -> bool:
        """state.go:2179-2254."""
        rs = self.rs
        if rs.height != msg.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        if not added:
            return False
        if rs.proposal_block_parts.byte_size > self.state.consensus_params.block.max_bytes:
            raise ValueError("total size of proposal block parts exceeds max block bytes")
        if rs.proposal_block_parts.is_complete():
            rs.proposal_block = Block.from_proto_bytes(
                rs.proposal_block_parts.get_reader()
            )
        return added

    def _handle_complete_proposal(self) -> None:
        """state.go handleCompleteProposal:2255-2287."""
        rs = self.rs
        self._publish_event(
            "publish_event_complete_proposal",
            lambda eb: eb.EventDataCompleteProposal(
                height=rs.height,
                round=rs.round,
                step=rs.step.name,
                block_id=rs.proposal.block_id if rs.proposal else None,
            ),
        )
        prevotes = rs.votes.prevotes(rs.round)
        block_id, has_maj = (
            prevotes.two_thirds_majority() if prevotes else (BlockID(), False)
        )
        if has_maj and not block_id.is_nil() and rs.valid_round < rs.round:
            if rs.proposal_block.hash() == block_id.hash:
                rs.valid_round = rs.round
                rs.valid_block = rs.proposal_block
                rs.valid_block_parts = rs.proposal_block_parts
        if rs.step <= RoundStep.PROPOSE and self._is_proposal_complete():
            self._enter_prevote(rs.height, rs.round)
            if has_maj:
                self._enter_precommit(rs.height, rs.round)
        elif rs.step == RoundStep.COMMIT:
            self._try_finalize_commit(rs.height)

    def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """state.go tryAddVote:2289 + addVote:2333."""
        try:
            return self._add_vote(vote, peer_id)
        except ConflictingVotesError as e:
            if (
                self.priv_pub_key is not None
                and vote.validator_address == self.priv_pub_key.address()
            ):
                return False
            pool = getattr(self.block_exec, "evidence_pool", None)
            if pool is not None and hasattr(pool, "report_conflicting_votes"):
                pool.report_conflicting_votes(e.vote_a, e.vote_b)
            return False
        except Exception:
            return False

    def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        rs = self.rs

        # Precommit for the previous height while in NewHeight step.
        if vote.height + 1 == rs.height and vote.type == SIGNED_MSG_TYPE_PRECOMMIT:
            if rs.step != RoundStep.NEW_HEIGHT:
                return False
            if rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote)
            if added:
                self.broadcaster.broadcast_has_vote(
                    vote.height, vote.round, vote.type, vote.validator_index
                )
            if added and (
                self.state.consensus_params.timeout.bypass_commit_timeout
                and rs.last_commit.has_all()
            ):
                self._enter_new_round(rs.height, 0)
            return added

        if vote.height != rs.height:
            return False

        if self.state.consensus_params.abci.vote_extensions_enabled(rs.height):
            my_addr = self.priv_pub_key.address() if self.priv_pub_key else b""
            if (
                vote.type == SIGNED_MSG_TYPE_PRECOMMIT
                and not vote.block_id.is_nil()
                and vote.validator_address != my_addr
            ):
                val = self.state.validators.get_by_index(vote.validator_index)
                vote.verify_extension(self.state.chain_id, val.pub_key)
                self.block_exec.verify_vote_extension(vote)
        else:
            vote.strip_extension()

        height = rs.height
        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            return False
        # Announce to peers so their PeerState marks us as having it and
        # their gossip routines skip re-sending (reactor HasVote flow).
        self.broadcaster.broadcast_has_vote(
            vote.height, vote.round, vote.type, vote.validator_index
        )
        self._publish_event(
            "publish_event_vote", lambda eb: eb.EventDataVote(vote=vote)
        )

        if vote.type == SIGNED_MSG_TYPE_PREVOTE:
            prevotes = rs.votes.prevotes(vote.round)
            block_id, ok = prevotes.two_thirds_majority()
            if ok and not block_id.is_nil():
                if rs.valid_round < vote.round and vote.round == rs.round:
                    if (
                        rs.proposal_block is not None
                        and rs.proposal_block.hash() == block_id.hash
                    ):
                        rs.valid_round = vote.round
                        rs.valid_block = rs.proposal_block
                        rs.valid_block_parts = rs.proposal_block_parts
                    else:
                        rs.proposal_block = None
                    if (
                        rs.proposal_block_parts is None
                        or not rs.proposal_block_parts.has_header(
                            block_id.part_set_header
                        )
                    ):
                        rs.proposal_block_parts = PartSet(block_id.part_set_header)
            if rs.round < vote.round and prevotes.has_two_thirds_any():
                self._enter_new_round(height, vote.round)
            elif rs.round == vote.round and rs.step >= RoundStep.PREVOTE:
                block_id, ok = prevotes.two_thirds_majority()
                if ok and (self._is_proposal_complete() or block_id.is_nil()):
                    self._enter_precommit(height, vote.round)
                elif prevotes.has_two_thirds_any():
                    self._enter_prevote_wait(height, vote.round)
            elif (
                rs.proposal is not None
                and 0 <= rs.proposal.pol_round == vote.round
            ):
                if self._is_proposal_complete():
                    self._enter_prevote(height, rs.round)
        elif vote.type == SIGNED_MSG_TYPE_PRECOMMIT:
            precommits = rs.votes.precommits(vote.round)
            block_id, ok = precommits.two_thirds_majority()
            if ok:
                self._enter_new_round(height, vote.round)
                self._enter_precommit(height, vote.round)
                if not block_id.is_nil():
                    self._enter_commit(height, vote.round)
                    if (
                        self.state.consensus_params.timeout.bypass_commit_timeout
                        and precommits.has_all()
                    ):
                        self._enter_new_round(rs.height, 0)
                else:
                    self._enter_precommit_wait(height, vote.round)
            elif rs.round <= vote.round and precommits.has_two_thirds_any():
                self._enter_new_round(height, vote.round)
                self._enter_precommit_wait(height, vote.round)
        return True

    # --- vote signing --------------------------------------------------------

    def _sign_vote(
        self, msg_type: int, hash_: bytes, header: PartSetHeader
    ) -> Optional[Vote]:
        """state.go signVote:2540-2620."""
        self.wal.flush_and_sync()
        if self.priv_pub_key is None:
            return None
        addr = self.priv_pub_key.address()
        val_idx, _ = self.rs.validators.get_by_address(addr)
        if val_idx < 0:
            return None
        rs = self.rs
        vote = Vote(
            type=msg_type,
            height=rs.height,
            round=rs.round,
            block_id=BlockID(hash_, header),
            timestamp=self._vote_time(),
            validator_address=addr,
            validator_index=val_idx,
        )
        ext_enabled = self.state.consensus_params.abci.vote_extensions_enabled(
            rs.height
        )
        if msg_type == SIGNED_MSG_TYPE_PRECOMMIT and hash_ and ext_enabled:
            vote.extension = self.block_exec.extend_vote(vote)
        self.priv_validator.sign_vote(self.state.chain_id, vote)
        if not ext_enabled:
            vote.strip_extension()
        return vote

    def _vote_time(self) -> Timestamp:
        return self._now()

    def _sign_add_vote(
        self, msg_type: int, hash_: bytes, header: PartSetHeader
    ) -> Optional[Vote]:
        if self.priv_validator is None or self.priv_pub_key is None:
            return None
        if not self.rs.validators.has_address(self.priv_pub_key.address()):
            return None
        try:
            vote = self._sign_vote(msg_type, hash_, header)
        except Exception:
            return None
        if vote is None:
            return None
        self._send_internal(MsgInfo(vote, ""))
        self.broadcaster.broadcast_vote(vote)
        return vote

    # --- WAL replay ----------------------------------------------------------

    def _catchup_replay(self) -> None:
        """replay.go catchupReplay:97-180: replay WAL messages for the
        current height after the last end-height marker."""
        height = self.rs.height
        offset = self.wal.search_for_end_height(height - 1)
        if offset is None and height > self.state.initial_height:
            pruned_from = getattr(self.wal, "first_offset", lambda: 0)()
            if pruned_from > 0:
                # The marker existed but rotation pruned it away: replaying
                # from the retention horizon would feed stale-height
                # messages into the state machine. Fatal, as in the
                # reference (replay.go treats a missing end-height as a
                # corrupt WAL).
                raise RuntimeError(
                    f"WAL end-height marker for {height - 1} was pruned "
                    f"(retention starts at offset {pruned_from}); cannot "
                    "safely replay — restore from a snapshot or state sync"
                )
            offset = 0
        start = offset or 0
        for _, msg in self.wal.iter_messages(start):
            if isinstance(msg, EndHeightMessage):
                continue
            if isinstance(msg, MsgInfo):
                with self._mtx:
                    try:
                        self._handle_msg(msg)
                    except Exception:
                        pass
            elif isinstance(msg, TimeoutInfo):
                with self._mtx:
                    self._handle_timeout(msg)
