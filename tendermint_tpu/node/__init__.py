"""Node assembly (reference: node/)."""

from tendermint_tpu.node.node import Node, NodeConfig

__all__ = ["Node", "NodeConfig"]
