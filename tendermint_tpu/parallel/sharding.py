"""Sharded batch verification over a device mesh.

The TPU analog of the reference's task-level concurrency inventory
(SURVEY.md §2.4): signature lanes are the data-parallel axis. The Straus
verification kernel (ops/ed25519_batch.py) is lane-local — no
cross-signature communication — so sharding the lane axis over an ICI
mesh partitions with zero collectives; XLA emits per-device slices and
the only sync is the final per-lane bool gather.

For commits larger than one chip's VMEM-friendly batch (100k-validator
commits, BASELINE.md config 5), this is the scaling path: a
``Mesh(devices, ('sig',))`` with lanes sharded over 'sig'.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tendermint_tpu.ops import ed25519_batch

SIG_AXIS = "sig"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SIG_AXIS,))


@lru_cache(maxsize=8)
def _sharded_fn_for_mesh(mesh: Mesh):
    # Kernel inputs are (N, 32) uint8 raw-byte arrays: lanes on axis 0.
    rows = NamedSharding(mesh, P(SIG_AXIS, None))
    lane1 = NamedSharding(mesh, P(SIG_AXIS))
    return jax.jit(
        ed25519_batch.verify_kernel,
        in_shardings=(rows, rows, rows, rows),
        out_shardings=lane1,
    )


def sharded_verify_fn(mesh: Mesh):
    """Jitted verify kernel with lane-axis sharding over ``mesh``."""
    return _sharded_fn_for_mesh(mesh)


def verify_batch_sharded(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    mesh: Optional[Mesh] = None,
) -> List[bool]:
    """Like ops.verify_batch but sharded across every device in ``mesh``.

    Lanes are padded to a multiple of the mesh size times the bucket
    granularity so each device gets an identical slab.
    """
    n = len(pubkeys)
    if n == 0:
        return []
    if mesh is None:
        mesh = make_mesh()
    n_dev = mesh.devices.size
    per_dev = max(8, -(-n // n_dev))  # ceil, min 8 lanes per device
    # Round per-device lanes up to the bucket table so compile cache hits.
    per_dev = ed25519_batch._bucket(per_dev)
    pad_to = per_dev * n_dev
    inputs, host_ok = ed25519_batch.prepare_batch(pubkeys, msgs, sigs, pad_to=pad_to)
    fn = _sharded_fn_for_mesh(mesh)
    device_ok = np.asarray(
        fn(inputs["pk"], inputs["r"], inputs["s"], inputs["k"])
    )[:n]
    return list(np.logical_and(device_ok, host_ok))
