"""Consensus reactor: gossips proposals, block parts, and votes.

Mirrors internal/consensus/reactor.go's channel layout — State(0x20),
Data(0x21), Vote(0x22), VoteSetBits(0x23) (reactor.go:78-81) — with a
broadcast-based gossip discipline: own proposals/parts/votes are
broadcast to all peers, peer messages feed the state machine's peer
queue. (The reference's per-peer PeerState-driven catch-up gossip is
approximated by rebroadcasting on NewRoundStep; targeted catch-up rides
blocksync.)

Wire format per message: 1 tag byte + proto payload.
"""

from __future__ import annotations

import struct
import threading
from typing import Optional

from tendermint_tpu.consensus.state import Broadcaster, ConsensusState
from tendermint_tpu.p2p.router import Channel, Envelope, Router
from tendermint_tpu.types.block import Proposal, Vote
from tendermint_tpu.types.part_set import Part

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

TAG_NEW_ROUND_STEP = 1
TAG_PROPOSAL = 2
TAG_BLOCK_PART = 3
TAG_VOTE = 4


def encode_new_round_step(height: int, round_: int, step: int) -> bytes:
    return bytes([TAG_NEW_ROUND_STEP]) + struct.pack(">qii", height, round_, step)


def encode_proposal(p: Proposal) -> bytes:
    return bytes([TAG_PROPOSAL]) + p.to_proto_bytes()


def encode_block_part(height: int, round_: int, part: Part) -> bytes:
    return (
        bytes([TAG_BLOCK_PART])
        + struct.pack(">qi", height, round_)
        + part.to_proto_bytes()
    )


def encode_vote(v: Vote) -> bytes:
    return bytes([TAG_VOTE]) + v.to_proto_bytes()


class ConsensusReactor(Broadcaster):
    def __init__(self, cs: ConsensusState, router: Router):
        self.cs = cs
        self.state_ch = router.open_channel(STATE_CHANNEL)
        self.data_ch = router.open_channel(DATA_CHANNEL)
        self.vote_ch = router.open_channel(VOTE_CHANNEL)
        self.vote_bits_ch = router.open_channel(VOTE_SET_BITS_CHANNEL)
        cs.broadcaster = self
        self._stop_flag = threading.Event()
        self._threads = []

    def start(self) -> None:
        self._stop_flag.clear()
        for ch, handler in (
            (self.state_ch, self._handle_state),
            (self.data_ch, self._handle_data),
            (self.vote_ch, self._handle_vote),
        ):
            t = threading.Thread(
                target=self._recv_loop, args=(ch, handler), daemon=True
            )
            t.start()
            self._threads.append(t)
        # Catch-up gossip: peers that connect (or fall behind) after a
        # message was first broadcast would never see it — the reference
        # solves this with per-peer gossip routines driven by PeerState
        # (reactor.go:501,736); here a periodic re-broadcast of the current
        # round's proposal/parts/votes serves the same role (receivers
        # dedupe cheaply before any signature work).
        t = threading.Thread(target=self._regossip_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop_flag.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()

    # --- outbound (Broadcaster) ----------------------------------------------

    def broadcast_proposal(self, proposal: Proposal) -> None:
        self.data_ch.broadcast(encode_proposal(proposal))

    def broadcast_block_part(self, height: int, round_: int, part: Part) -> None:
        self.data_ch.broadcast(encode_block_part(height, round_, part))

    def broadcast_vote(self, vote: Vote) -> None:
        self.vote_ch.broadcast(encode_vote(vote))

    def broadcast_new_round_step(self, rs) -> None:
        self.state_ch.broadcast(
            encode_new_round_step(rs.height, rs.round, int(rs.step))
        )

    # --- catch-up gossip ------------------------------------------------------

    REGOSSIP_INTERVAL = 0.25

    def _regossip_loop(self) -> None:
        while not self._stop_flag.is_set():
            self._stop_flag.wait(self.REGOSSIP_INTERVAL)
            try:
                self._regossip_once()
            except Exception:
                pass

    def _regossip_once(self) -> None:
        rs = self.cs.rs
        if rs.votes is None:
            return
        if rs.proposal is not None:
            self.broadcast_proposal(rs.proposal)
        if rs.proposal_block_parts is not None:
            for i in range(rs.proposal_block_parts.total):
                part = rs.proposal_block_parts.get_part(i)
                if part is not None:
                    self.broadcast_block_part(rs.height, rs.round, part)
        for round_ in range(max(0, rs.round - 1), rs.round + 1):
            for vs in (rs.votes.prevotes(round_), rs.votes.precommits(round_)):
                if vs is None:
                    continue
                for vote in vs.vote_list():
                    self.broadcast_vote(vote)
        # Last-height precommits so peers waiting in NewHeight can finish
        # their commit (the LastCommit gossip of reactor.go:736).
        if rs.last_commit is not None:
            for vote in rs.last_commit.vote_list():
                self.broadcast_vote(vote)

    # --- inbound --------------------------------------------------------------

    def _recv_loop(self, ch: Channel, handler) -> None:
        while not self._stop_flag.is_set():
            env = ch.receive(timeout=0.2)
            if env is None:
                continue
            try:
                handler(env)
            except Exception:
                pass  # peer input must not kill the reactor

    def _handle_state(self, env: Envelope) -> None:
        if not env.message or env.message[0] != TAG_NEW_ROUND_STEP:
            return
        height, round_, step = struct.unpack_from(">qii", env.message, 1)
        # A peer behind us re-triggers our broadcasts implicitly via the
        # internal loopback; a peer ahead is handled by blocksync.

    def _handle_data(self, env: Envelope) -> None:
        if not env.message:
            return
        tag = env.message[0]
        if tag == TAG_PROPOSAL:
            proposal = Proposal.from_proto_bytes(env.message[1:])
            self.cs.add_proposal_from_peer(proposal, env.from_peer)
        elif tag == TAG_BLOCK_PART:
            height, round_ = struct.unpack_from(">qi", env.message, 1)
            part = Part.from_proto_bytes(env.message[13:])
            self.cs.add_block_part_from_peer(height, round_, part, env.from_peer)

    def _handle_vote(self, env: Envelope) -> None:
        if not env.message or env.message[0] != TAG_VOTE:
            return
        vote = Vote.from_proto_bytes(env.message[1:])
        self.cs.add_vote_from_peer(vote, env.from_peer)
