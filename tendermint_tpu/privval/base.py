"""PrivValidator interface (types/priv_validator.go:28)."""

from __future__ import annotations

from tendermint_tpu.crypto.keys import PubKey
from tendermint_tpu.types.block import Proposal, Vote


class PrivValidator:
    def get_pub_key(self) -> PubKey:
        raise NotImplementedError

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        """Sets vote.signature (and extension signature for non-nil
        precommits); raises on double-sign risk."""
        raise NotImplementedError

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        raise NotImplementedError
