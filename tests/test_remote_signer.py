"""Remote signer (socket privval) tests.

Covers: in-process client/server exchange over tcp (SecretConnection) and
unix sockets, double-sign refusal crossing the wire as an error, the
signer running as a separate OS process, and a validator node committing
blocks while signing through the out-of-process signer
(privval/signer_client.go, signer_server.go,
signer_listener_endpoint_test.go).
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.node.node import Node, NodeConfig
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.privval.remote import (
    RemoteSignerError,
    SignerClient,
    SignerListenerEndpoint,
    SignerServer,
    parse_addr,
)
from tendermint_tpu.types.block import BlockID, PartSetHeader, Proposal, Vote
from tendermint_tpu.encoding.canonical import Timestamp

from tests.test_node import CHAIN, fast_genesis, wait_for

BASE_TS = Timestamp.from_unix_ns(1_700_000_000_000_000_000)


def _make_vote(height=1, round_=0, type_=1):
    return Vote(
        type=type_,
        height=height,
        round=round_,
        block_id=BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32)),
        timestamp=BASE_TS,
        validator_address=b"\x03" * 20,
        validator_index=0,
    )


@pytest.fixture()
def file_pv(tmp_path):
    return FilePV.generate(
        str(tmp_path / "key.json"), str(tmp_path / "state.json")
    )


def _pair(addr, file_pv):
    """Start a listener endpoint + an in-process signer dialing it."""
    ep = SignerListenerEndpoint(addr)
    ep.start()
    server = SignerServer(ep.listen_addr, CHAIN, file_pv)
    server.start()
    ep.wait_for_connection(10)
    client = SignerClient(ep, CHAIN)
    return ep, server, client


class TestAddrParse:
    def test_tcp(self):
        assert parse_addr("tcp://1.2.3.4:567") == ("tcp", ("1.2.3.4", 567))

    def test_unix(self):
        assert parse_addr("unix:///tmp/x.sock") == ("unix", "/tmp/x.sock")

    def test_bad_scheme(self):
        with pytest.raises(ValueError):
            parse_addr("http://x")


class TestInProcess:
    def test_pubkey_and_vote_over_tcp(self, file_pv):
        ep, server, client = _pair("tcp://127.0.0.1:0", file_pv)
        try:
            assert client.get_pub_key().bytes() == file_pv.get_pub_key().bytes()
            client.ping()
            vote = _make_vote()
            client.sign_vote(CHAIN, vote)
            assert vote.signature
            assert file_pv.get_pub_key().verify_signature(
                vote.sign_bytes(CHAIN), vote.signature
            )
        finally:
            server.stop()
            ep.close()

    def test_proposal_over_unix(self, file_pv, tmp_path):
        ep, server, client = _pair(
            f"unix://{tmp_path}/signer.sock", file_pv
        )
        try:
            prop = Proposal(
                height=3,
                round=0,
                pol_round=-1,
                block_id=BlockID(b"\x05" * 32, PartSetHeader(1, b"\x06" * 32)),
                timestamp=BASE_TS,
            )
            client.sign_proposal(CHAIN, prop)
            assert prop.signature
            assert file_pv.get_pub_key().verify_signature(
                prop.sign_bytes(CHAIN), prop.signature
            )
        finally:
            server.stop()
            ep.close()

    def test_double_sign_refused_over_wire(self, file_pv):
        ep, server, client = _pair("tcp://127.0.0.1:0", file_pv)
        try:
            v1 = _make_vote(height=5)
            client.sign_vote(CHAIN, v1)
            # conflicting block at same HRS: the signer's last-sign-state
            # must refuse, and the refusal crosses the wire as an error
            v2 = _make_vote(height=5)
            v2.block_id = BlockID(b"\x09" * 32, PartSetHeader(1, b"\x0a" * 32))
            with pytest.raises(RemoteSignerError, match="double sign"):
                client.sign_vote(CHAIN, v2)
            # regression to a lower height is also refused
            v0 = _make_vote(height=4)
            with pytest.raises(RemoteSignerError):
                client.sign_vote(CHAIN, v0)
        finally:
            server.stop()
            ep.close()

    def test_unauthorized_signer_rejected(self, file_pv):
        from tendermint_tpu.crypto.keys import Ed25519PrivKey

        allowed_identity = Ed25519PrivKey.generate()
        ep = SignerListenerEndpoint(
            "tcp://127.0.0.1:0",
            authorized_keys=[allowed_identity.pub_key().bytes()],
        )
        ep.start()
        # signer dials with a DIFFERENT identity -> endpoint must refuse
        stranger = SignerServer(
            ep.listen_addr, CHAIN, file_pv,
            signer_identity=Ed25519PrivKey.generate(),
            max_dial_retries=5,
        )
        stranger.start()
        try:
            # the stranger's dials are each rejected; the wait never
            # yields a connection and reports the rejections on timeout
            with pytest.raises(RemoteSignerError, match="timed out"):
                ep.wait_for_connection(2)
        finally:
            stranger.stop()
        # the authorized identity connects fine (unbounded redial: its
        # first dials may be consumed clearing dead backlog entries)
        legit = SignerServer(
            ep.listen_addr, CHAIN, file_pv,
            signer_identity=allowed_identity,
        )
        legit.start()
        try:
            # generous: under full-suite CPU contention each rejected
            # stranger dial costs a SecretConnection handshake first
            ep.wait_for_connection(30)
            SignerClient(ep, CHAIN).ping()
        finally:
            legit.stop()
            ep.close()

    def test_signer_reconnects_after_drop(self, file_pv):
        ep, server, client = _pair("tcp://127.0.0.1:0", file_pv)
        try:
            client.ping()
            # sever the current connection from the node side; the signer's
            # dial loop must re-establish it
            with ep._lock:
                ep._drop_conn_locked()
            deadline = time.monotonic() + 10
            while True:
                try:
                    ep.wait_for_connection(2)
                    client.ping()
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
        finally:
            server.stop()
            ep.close()


class TestOutOfProcess:
    def test_subprocess_signer_signs(self, tmp_path):
        key_file = str(tmp_path / "k.json")
        state_file = str(tmp_path / "s.json")
        # pre-generate so the parent knows the expected pubkey
        pv = FilePV.generate(key_file, state_file)
        expected_pub = pv.get_pub_key().bytes()

        ep = SignerListenerEndpoint("tcp://127.0.0.1:0")
        ep.start()
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "tendermint_tpu.privval.remote",
                "--addr",
                ep.listen_addr,
                "--chain-id",
                CHAIN,
                "--key-file",
                key_file,
                "--state-file",
                state_file,
            ],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        try:
            ep.wait_for_connection(15)
            client = SignerClient(ep, CHAIN)
            assert client.get_pub_key().bytes() == expected_pub
            vote = _make_vote(height=2)
            client.sign_vote(CHAIN, vote)
            assert client.get_pub_key().verify_signature(
                vote.sign_bytes(CHAIN), vote.signature
            )
        finally:
            proc.terminate()
            proc.wait(timeout=10)
            ep.close()

    def test_node_commits_via_remote_signer(self, tmp_path):
        """A single-validator node with no local key signs every proposal
        and vote through the out-of-process signer and still commits."""
        import socket as socketlib

        key_file = str(tmp_path / "k.json")
        state_file = str(tmp_path / "s.json")
        pv = FilePV.generate(key_file, state_file)
        genesis = fast_genesis([pv])

        # Reserve a port for the privval listener: the node binds it during
        # construction, but construction itself asks the signer for the
        # pubkey, so the signer process must already be dialing by then.
        # SO_REUSEADDR on the listener covers the close->rebind window.
        probe = socketlib.socket()
        probe.setsockopt(socketlib.SOL_SOCKET, socketlib.SO_REUSEADDR, 1)
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        laddr = f"tcp://127.0.0.1:{port}"

        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "tendermint_tpu.privval.remote",
                "--addr",
                laddr,
                "--chain-id",
                CHAIN,
                "--key-file",
                key_file,
                "--state-file",
                state_file,
            ],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        cfg = NodeConfig(
            chain_id=CHAIN,
            listen_addr="127.0.0.1:0",
            wal_enabled=False,
            priv_validator_laddr=laddr,
            moniker="remote-signed",
        )
        node = Node(cfg, genesis, LocalClient(KVStoreApplication()))
        try:
            node._signer_endpoint.wait_for_connection(15)
            node.start()
            assert wait_for(lambda: node.height >= 2, timeout=60), (
                f"height: {node.height}"
            )
        finally:
            node.stop()
            proc.terminate()
            proc.wait(timeout=10)
