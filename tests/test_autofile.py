"""Autofile group + rotating WAL tests (internal/libs/autofile/group.go,
consensus/wal.go rotation behavior)."""

import os

import pytest

from tendermint_tpu.consensus.wal import (
    WAL,
    EndHeightMessage,
    TimeoutInfo,
    WALCorruptionError,
)
from tendermint_tpu.libs.autofile import Group


class TestGroup:
    def test_write_read_single_head(self, tmp_path):
        g = Group(str(tmp_path / "log"))
        g.start()
        g.write(b"hello ")
        g.write(b"world")
        g.flush()
        assert g.read_from(0) == b"hello world"
        assert g.read_from(6) == b"world"
        assert g.end_offset() == 11
        g.stop()

    def test_rotation_preserves_logical_offsets(self, tmp_path):
        g = Group(str(tmp_path / "log"), head_size_limit=100)
        g.start()
        blobs = [bytes([i]) * 40 for i in range(10)]  # 400 bytes total
        for blob in blobs:
            g.write(blob)
            g.maybe_rotate()
        g.flush()
        # several sealed chunks plus the head
        assert len(g.segments()) >= 3
        assert g.read_from(0) == b"".join(blobs)
        # mid-stream logical offsets read identically across chunks
        joined = b"".join(blobs)
        for off in (0, 40, 95, 120, 250, 399):
            assert g.read_from(off) == joined[off:]

    def test_restart_resumes_offsets(self, tmp_path):
        path = str(tmp_path / "log")
        g = Group(path, head_size_limit=50)
        g.start()
        g.write(b"a" * 60)
        g.maybe_rotate()
        g.write(b"b" * 10)
        g.flush()
        end = g.end_offset()
        g.stop()

        g2 = Group(path, head_size_limit=50)
        g2.start()
        assert g2.end_offset() == end
        g2.write(b"c" * 5)
        g2.flush()
        assert g2.read_from(0) == b"a" * 60 + b"b" * 10 + b"c" * 5
        g2.stop()

    def test_total_size_limit_prunes_oldest(self, tmp_path):
        g = Group(
            str(tmp_path / "log"), head_size_limit=100, total_size_limit=250
        )
        g.start()
        for i in range(10):
            g.write(bytes([i]) * 100)
            g.maybe_rotate()
        g.flush()
        segs = g.segments()
        total = sum(os.path.getsize(p) for _, p in segs)
        assert total <= 350  # limit + one head's worth of slack
        # the first retained offset moved past zero
        assert g.first_offset() > 0
        # reading from 0 silently starts at the retention horizon
        data = g.read_from(0)
        assert data == g.read_from(g.first_offset())


class TestRotatingWAL:
    def _fill(self, wal, n, start_height=1):
        for h in range(start_height, start_height + n):
            wal.write(TimeoutInfo(0.1, h, 0, 1))
            wal.write_sync(EndHeightMessage(h))

    def test_rotation_replays_all_records(self, tmp_path):
        path = str(tmp_path / "cs.wal")
        wal = WAL(path, head_size_limit=200)
        wal.start()
        self._fill(wal, 50)
        # rotation definitely happened
        assert len(wal._group.segments()) > 2
        msgs = list(wal.iter_messages())
        assert len(msgs) == 100
        heights = [
            m.height for _, m in msgs if isinstance(m, EndHeightMessage)
        ]
        assert heights == list(range(1, 51))
        wal.stop()

    def test_search_end_height_across_chunks(self, tmp_path):
        wal = WAL(str(tmp_path / "cs.wal"), head_size_limit=200)
        wal.start()
        self._fill(wal, 30)
        off = wal.search_for_end_height(17)
        assert off is not None
        # replay from that offset starts at height 18's records
        following = list(wal.iter_messages(off))
        first_ends = [
            m.height
            for _, m in following
            if isinstance(m, EndHeightMessage)
        ]
        assert first_ends[0] == 18
        wal.stop()

    def test_restart_and_torn_tail_on_head(self, tmp_path):
        path = str(tmp_path / "cs.wal")
        wal = WAL(path, head_size_limit=200)
        wal.start()
        self._fill(wal, 20)
        wal.stop()
        # tear the head: append garbage half-record
        with open(path, "ab") as fh:
            fh.write(b"\x00\x01\x02")
        wal2 = WAL(path, head_size_limit=200)
        wal2.start()
        msgs = list(wal2.iter_messages())
        assert len(msgs) == 40  # garbage dropped, all real records intact
        self._fill(wal2, 1, start_height=21)  # still writable
        assert (
            len(list(wal2.iter_messages())) == 42
        )
        wal2.stop()

    def test_unstarted_wal_reads_all_records(self, tmp_path):
        """Reads on a constructed-but-unstarted WAL must see the head at
        its true logical base, not at 0 (replay tooling reads WALs
        without opening them for append)."""
        path = str(tmp_path / "cs.wal")
        wal = WAL(path, head_size_limit=200)
        wal.start()
        self._fill(wal, 20)
        wal.stop()
        cold = WAL(path, head_size_limit=200)  # no start()
        msgs = list(cold.iter_messages())
        assert len(msgs) == 40
        assert cold.search_for_end_height(20) is not None

    def test_pruned_marker_is_fatal_not_silent(self, tmp_path):
        wal = WAL(
            str(tmp_path / "cs.wal"),
            head_size_limit=150,
            total_size_limit=400,
        )
        wal.start()
        self._fill(wal, 200)
        assert wal.search_for_end_height(1) is None
        assert wal.first_offset() > 0  # the caller's fatal-check signal
        wal.stop()

    def test_pruning_keeps_recent_end_heights(self, tmp_path):
        wal = WAL(
            str(tmp_path / "cs.wal"),
            head_size_limit=150,
            total_size_limit=400,
        )
        wal.start()
        self._fill(wal, 200)
        # old heights pruned away, recent ones replayable
        assert wal.search_for_end_height(1) is None
        off = wal.search_for_end_height(200)
        assert off is not None
        wal.stop()
