"""Utility libraries (reference: libs/ and internal/libs/)."""
