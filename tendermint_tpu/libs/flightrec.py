"""Fault flight recorder: an always-on bounded binary ring that turns
the *next* relay wedge into a post-mortem instead of a shrug.

ROADMAP item 1's history is four bench rounds killed by relay wedges
with zero diagnostic evidence. The recorder absorbs the cheap telemetry
every subsystem already emits — completed spans and instants (via the
tracer's flight sink), metric counter/gauge deltas (via the metrics
flight sink), device-health transitions, brownout/admission events —
into a byte-bounded ring of binary-packed records. Steady-state cost is
one pack + deque append per event; nothing is serialized to JSON until
a dump is actually needed.

Dump triggers (``install()``):

- an ``instant`` named in :data:`AUTO_DUMP_INSTANTS` (the bench
  watchdog's ``bench_watchdog_kill``) arriving through the sink;
- a ``device_health_transition`` instant escalating to COOLDOWN or
  DISABLED;
- an unhandled exception (``sys.excepthook`` chain);
- SIGTERM (handler chain; the previous handler still runs).

A dump writes the last ``TENDERMINT_TPU_FLIGHTREC_WINDOW`` seconds of
records atomically (tmp + rename) to a timestamped JSON file under
``TENDERMINT_TPU_FLIGHTREC_DIR``; bench/runner.py collects child dumps
into the partial-result JSON so a wedged section ships its own
post-mortem.

Concurrency: the ring is shared by every producer thread; all ring and
dump-bookkeeping state is guarded by ``_mtx``. The class is
``@instrument_attrs``-opted so the tpusan hb/explore CI stages prove
the discipline.
"""

from __future__ import annotations

import json
import os
import signal
import struct
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from tendermint_tpu.libs.sanitizer import instrument_attrs

ENABLE_ENV = "TENDERMINT_TPU_FLIGHTREC"
DIR_ENV = "TENDERMINT_TPU_FLIGHTREC_DIR"
CAP_ENV = "TENDERMINT_TPU_FLIGHTREC_CAP"
WINDOW_ENV = "TENDERMINT_TPU_FLIGHTREC_WINDOW"

DEFAULT_CAP_BYTES = 256 * 1024
DEFAULT_WINDOW_S = 30.0
MAX_PAYLOAD_BYTES = 512  # one record's packed JSON payload cap
MAX_DUMPS = 16  # per-process disk-spam guard

DUMP_SCHEMA = "tendermint-tpu-flightrec/1"

# kind, unix-seconds timestamp, duration (us), payload length
_REC_HDR = struct.Struct("<BdIH")

KIND_SPAN = 1
KIND_INSTANT = 2
KIND_METRIC = 3
KIND_MARK = 4
KIND_NAMES = {
    KIND_SPAN: "span",
    KIND_INSTANT: "instant",
    KIND_METRIC: "metric",
    KIND_MARK: "mark",
}

# Instants whose mere arrival is the fault: the sink auto-dumps with the
# mapped reason the moment one lands in the ring.
AUTO_DUMP_INSTANTS = {"bench_watchdog_kill": "watchdog_kill"}
# device_health_transition escalations that auto-dump.
AUTO_DUMP_HEALTH_STATES = ("cooldown", "disabled")


def _enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "1") != "0"


def dump_dir() -> str:
    return os.environ.get(DIR_ENV) or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "tendermint_tpu_flightrec"
    )


@instrument_attrs
class FlightRecorder:
    """Byte-bounded ring of binary-packed telemetry records."""

    def __init__(
        self,
        cap_bytes: Optional[int] = None,
        window_s: Optional[float] = None,
    ):
        if cap_bytes is None:
            try:
                cap_bytes = int(os.environ.get(CAP_ENV, DEFAULT_CAP_BYTES))
            except ValueError:
                cap_bytes = DEFAULT_CAP_BYTES
        if window_s is None:
            try:
                window_s = float(os.environ.get(WINDOW_ENV, DEFAULT_WINDOW_S))
            except ValueError:
                window_s = DEFAULT_WINDOW_S
        self._mtx = threading.Lock()
        self.cap_bytes = max(4096, cap_bytes)
        self.window_s = max(0.1, window_s)
        self._ring: deque = deque()  # guarded-by: _mtx (packed records)
        self._bytes = 0  # guarded-by: _mtx
        self.recorded = 0  # guarded-by: _mtx
        self.evicted = 0  # guarded-by: _mtx
        self.dumps = 0  # guarded-by: _mtx
        self._installed = False  # guarded-by: _mtx
        self._prev_excepthook = None  # guarded-by: _mtx
        self._prev_sigterm = None  # guarded-by: _mtx
        self._last_dump_path: Optional[str] = None  # guarded-by: _mtx

    # --- recording -----------------------------------------------------------

    def record(
        self,
        kind: int,
        name: str,
        fields: Optional[Dict[str, Any]] = None,
        dur_s: float = 0.0,
    ) -> None:
        """Pack one record into the ring; silently drops a payload that
        refuses to serialize (telemetry must never fail the op)."""
        try:
            payload = json.dumps(
                {"name": name, **(fields or {})}, default=str
            ).encode()
        except (TypeError, ValueError):
            payload = json.dumps({"name": name}).encode()
        if len(payload) > MAX_PAYLOAD_BYTES:
            payload = payload[:MAX_PAYLOAD_BYTES]
        dur_us = min(0xFFFFFFFF, max(0, int(dur_s * 1e6)))
        rec = _REC_HDR.pack(kind, time.time(), dur_us, len(payload)) + payload
        with self._mtx:
            self._ring.append(rec)
            self._bytes += len(rec)
            self.recorded += 1
            while self._bytes > self.cap_bytes and len(self._ring) > 1:
                self._bytes -= len(self._ring.popleft())
                self.evicted += 1

    def flight_sink(
        self, kind: str, name: str, args: Dict[str, Any], ts: float, dur: float
    ) -> None:
        """The tracer's flight-sink slot (tracing.set_flight_sink):
        absorbs every completed span/instant and auto-dumps on the fault
        instants."""
        self.record(
            KIND_SPAN if kind == "span" else KIND_INSTANT, name, args, dur
        )
        if kind != "instant":
            return
        reason = AUTO_DUMP_INSTANTS.get(name)
        if reason is None and name == "device_health_transition":
            to_state = str(args.get("to_state", "")).lower()
            if to_state in AUTO_DUMP_HEALTH_STATES:
                reason = "device_%s" % to_state
        if reason is not None:
            self.dump(reason)

    def metric_sink(self, name: str, labels: Any, delta: float) -> None:
        """The metrics flight-sink slot (metrics.set_flight_sink):
        counter increments and gauge sets as (name, labels, value)."""
        fields: Dict[str, Any] = {"v": round(delta, 6)}
        if labels:
            fields["labels"] = dict(labels)
        self.record(KIND_METRIC, name, fields)

    def mark(self, name: str, **fields: Any) -> None:
        """Explicit application mark (brownout rung change, admission
        rejection burst, ...)."""
        self.record(KIND_MARK, name, fields)

    # --- snapshot / dump -----------------------------------------------------

    def snapshot(self, window_s: Optional[float] = None) -> List[Dict[str, Any]]:
        """Decode the records from the last ``window_s`` seconds."""
        if window_s is None:
            window_s = self.window_s
        cutoff = time.time() - window_s
        with self._mtx:
            raw = list(self._ring)
        out: List[Dict[str, Any]] = []
        for rec in raw:
            kind, ts, dur_us, plen = _REC_HDR.unpack_from(rec)
            if ts < cutoff:
                continue
            payload = rec[_REC_HDR.size : _REC_HDR.size + plen]
            try:
                fields = json.loads(payload)
            except ValueError:
                fields = {"name": "<truncated>"}
            row = {
                "kind": KIND_NAMES.get(kind, str(kind)),
                "ts": round(ts, 6),
                "name": fields.pop("name", ""),
            }
            if dur_us:
                row["dur_us"] = dur_us
            if fields:
                row["fields"] = fields
            out.append(row)
        return out

    def dump(
        self, reason: str, window_s: Optional[float] = None
    ) -> Optional[str]:
        """Atomically write the last-N-seconds snapshot to a timestamped
        file under ``dump_dir()``; returns the path (None when disabled,
        over the dump budget, or the write fails)."""
        if not _enabled():
            return None
        with self._mtx:
            if self.dumps >= MAX_DUMPS:
                return None
            self.dumps += 1
        records = self.snapshot(window_s)
        d = dump_dir()
        path = os.path.join(
            d,
            "flightrec-%d-%s-%d.json"
            % (os.getpid(), reason.replace("/", "_"), int(time.time() * 1e3)),
        )
        doc = {
            "schema": DUMP_SCHEMA,
            "pid": os.getpid(),
            "reason": reason,
            "dumped_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "window_s": window_s if window_s is not None else self.window_s,
            "records": records,
            "memstats": self._memstats_section(),
        }
        tmp = "%s.tmp.%d" % (path, os.getpid())
        try:
            os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass  # tmp may never have been created; dump is best-effort
            return None
        with self._mtx:
            self._last_dump_path = path
        return path

    def _memstats_section(self) -> Dict[str, Any]:
        """Device-tier snapshot for the dump (ISSUE 18): the introspect
        ledger + profiler digests, SIZE-BOUNDED to a quarter of the ring
        budget (64 KiB cap) so the new section can never push an atomic
        dump meaningfully past what the ring itself was allowed to hold
        — introspect degrades the payload (drop profile digests, then
        collapse to totals) rather than let one dump grow unbounded."""
        try:
            from tendermint_tpu.ops import introspect

            limit = min(self.cap_bytes // 4, 64 * 1024)
            return json.loads(introspect.memstats_json(limit_bytes=limit))
        except Exception:
            return {}  # the post-mortem dump must not fail on accounting

    def last_dump_path(self) -> Optional[str]:
        with self._mtx:
            return self._last_dump_path

    def stats(self) -> Dict[str, Any]:
        with self._mtx:
            return {
                "recorded": self.recorded,
                "evicted": self.evicted,
                "bytes": self._bytes,
                "cap_bytes": self.cap_bytes,
                "dumps": self.dumps,
                "installed": self._installed,
            }

    def __len__(self) -> int:
        with self._mtx:
            return len(self._ring)

    # --- fault-handler installation ------------------------------------------

    def install(self, signals: bool = True) -> bool:
        """Wire the recorder into the tracer and metrics flight sinks,
        the excepthook chain, and (main thread only) SIGTERM. Idempotent;
        returns whether the recorder is now installed."""
        if not _enabled():
            return False
        from tendermint_tpu.libs import metrics, tracing

        with self._mtx:
            already = self._installed
            self._installed = True
        if already:
            return True
        tracing.tracer.set_flight_sink(self.flight_sink)
        metrics.set_flight_sink(self.metric_sink)

        prev_hook = sys.excepthook

        def hook(exc_type, exc, tb):
            self.record(
                KIND_MARK,
                "unhandled_exception",
                {"type": getattr(exc_type, "__name__", str(exc_type)),
                 "message": str(exc)[:200]},
            )
            self.dump("unhandled_exception")
            prev_hook(exc_type, exc, tb)

        with self._mtx:
            self._prev_excepthook = prev_hook
        sys.excepthook = hook

        if signals and threading.current_thread() is threading.main_thread():
            try:
                prev = signal.getsignal(signal.SIGTERM)

                def on_sigterm(signum, frame):
                    self.record(KIND_MARK, "sigterm", {})
                    self.dump("sigterm")
                    if callable(prev) and prev not in (
                        signal.SIG_IGN,
                        signal.SIG_DFL,
                    ):
                        prev(signum, frame)
                    else:
                        signal.signal(signal.SIGTERM, signal.SIG_DFL)
                        os.kill(os.getpid(), signal.SIGTERM)

                signal.signal(signal.SIGTERM, on_sigterm)
                with self._mtx:
                    self._prev_sigterm = prev
            except (ValueError, OSError):
                pass  # embedded interpreter / exotic platform: no signal hook
        return True

    def uninstall(self) -> None:
        """Detach the sinks and restore the chained handlers (tests)."""
        from tendermint_tpu.libs import metrics, tracing

        with self._mtx:
            if not self._installed:
                return
            self._installed = False
            prev_hook = self._prev_excepthook
            prev_sig = self._prev_sigterm
            self._prev_excepthook = None
            self._prev_sigterm = None
        tracing.tracer.set_flight_sink(None)
        metrics.set_flight_sink(None)
        if prev_hook is not None:
            sys.excepthook = prev_hook
        if prev_sig is not None:
            try:
                signal.signal(signal.SIGTERM, prev_sig)
            except (ValueError, OSError):
                pass  # non-main thread / torn-down interpreter: keep ours


# The process-wide instance (same pattern as tracing.tracer: the
# instrumentation sites have no handle to pass one around).
recorder = FlightRecorder()


def install(signals: bool = True) -> bool:
    return recorder.install(signals=signals)


def mark(name: str, **fields: Any) -> None:
    recorder.mark(name, **fields)


def dump(reason: str) -> Optional[str]:
    return recorder.dump(reason)
