"""Lock-discipline checker (TPL): ``# guarded-by:`` annotations.

The concurrency-heavy classes (VerifyScheduler, DeviceHealth, the
caches, verifyd's server) all follow the same convention: shared
mutable fields are touched only inside ``with self.<lock>:``. The
convention is invisible to generic linters, so a refactor that hoists
one read out of the critical section ships silently — exactly the bug
class the device-policy rewrite fixed by hand. This checker makes the
convention machine-checked:

Annotation grammar (a comment on the field's assignment line, normally
in ``__init__``)::

    self._pending = []            # guarded-by: _mtx
    self._entries = {}            # guarded-by: _lock|_sched_mtx   (either lock)
    self.flushes = 0              # guarded-by: none(single-writer stats)

Rules:

- TPL001: a guarded field is read or written in a method of the same
  class outside a ``with`` block holding one of its locks;
- TPL002: an annotation names a lock attribute the class never assigns;
- TPL003: a ``guarded-by`` comment sits on a line with no ``self.X``
  assignment (orphaned — it guards nothing); module-level globals are
  the one exception, accepted when the annotation names a lock created
  at module scope (the ops singleton-store pattern);
- TPL004: malformed annotation text;
- TPL005: coverage for the tpusan-instrumented classes — a ``self.X``
  mutated from two or more thread-entry methods (anything but
  ``__init__``) of a class decorated ``@instrument_attrs`` that carries
  no ``guarded-by`` annotation at all. TPL001 only checks fields the
  author remembered to annotate; TPL005 closes exactly that gap for the
  classes that declared themselves concurrent by opting into the
  sanitizer. Fields named by the decorator's ``exclude=(...)`` are
  racy-by-design and skipped.

Lock aliasing is understood one level deep: ``self._wake =
threading.Condition(self._mtx)`` means holding ``_wake`` implies
holding ``_mtx`` (the scheduler's accumulator pattern). ``__init__`` is
exempt (no concurrent access before construction completes), as are
``del`` statements of locals. Nested ``def``s inside a method reset the
held-lock set — a closure may run on another thread after the lock is
released — while lambdas/comprehensions (which run inline) inherit it.

Two more conventions from the codebase are honoured: a method whose
name ends in ``_locked`` is assumed to run with the class's locks
already held (callers own the critical section), and locks/aliases
defined in a same-module base class (``_Metric`` -> Counter/Gauge/
Histogram) are inherited by subclasses before verification.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from scripts.analysis.core import (
    Checker,
    Finding,
    Module,
    decorator_names,
    dotted_name,
)

GUARD_RE = re.compile(r"guarded-by:\s*(?P<spec>[A-Za-z0-9_|]+(?:\([^)]*\))?)")
NONE_RE = re.compile(r"^none\((?P<reason>[^)]*)\)$|^none$")

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def _is_lock_ctor(call: ast.AST) -> bool:
    """``threading.Lock()`` / ``threading.Condition(...)`` etc."""
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_FACTORIES:
        return True
    if isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES:
        return True
    return False


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        #: field name -> (alternative lock names, annotation line), or
        #: None in place of the set for ``none(...)`` annotations
        self.guarded: Dict[str, Tuple[Optional[FrozenSet[str]], int]] = {}
        self.locks: Set[str] = set()
        #: condition attr -> wrapped lock attr (Condition(self._mtx))
        self.aliases: Dict[str, str] = {}
        #: decorated @instrument_attrs (tpusan attribute tracking)
        self.instrumented = False
        #: attrs named by instrument_attrs(exclude=...): racy by design
        self.excluded: Set[str] = set()


def _self_assign_targets(stmt: ast.stmt) -> List[str]:
    """Names X for ``self.X = ...`` / ``self.X: T = ...`` targets."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        targets = [stmt.target]
    elif isinstance(stmt, ast.AugAssign):
        targets = [stmt.target]
    out = []
    for t in targets:
        if (
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
        ):
            out.append(t.attr)
    return out


class LockDisciplineChecker(Checker):
    name = "locks"
    codes = {
        "TPL001": "guarded field accessed outside its lock",
        "TPL002": "guarded-by names a lock the class never creates",
        "TPL003": "guarded-by annotation on a line with no self.X assignment",
        "TPL004": "malformed guarded-by annotation",
        "TPL005": "unannotated shared-mutable attribute on an "
        "instrumented class",
    }

    def check_module(self, module: Module) -> Iterator[Finding]:
        annotated_lines: Set[int] = set()
        infos: Dict[str, _ClassInfo] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                infos[node.name] = self._collect(module, node, annotated_lines)
        # inherit locks/aliases from same-module base classes (the
        # metrics pattern: _Metric owns _lock, Counter uses it), with a
        # fixpoint for grandparent chains
        changed = True
        while changed:
            changed = False
            for info in infos.values():
                for base in info.node.bases:
                    if isinstance(base, ast.Name) and base.id in infos:
                        binfo = infos[base.id]
                        if not binfo.locks <= info.locks:
                            info.locks |= binfo.locks
                            changed = True
                        for cond, lock in binfo.aliases.items():
                            if cond not in info.aliases:
                                info.aliases[cond] = lock
                                changed = True
        for info in infos.values():
            yield from self._verify(module, info)
        self._collect_module_globals(module, annotated_lines)
        # orphaned annotations: guarded-by comments no class claimed
        for line, text in module.comments.items():
            if GUARD_RE.search(text) and line not in annotated_lines:
                yield Finding(
                    module.rel,
                    line,
                    "TPL003",
                    "guarded-by annotation does not sit on a "
                    "self.<field> assignment line",
                )

    # --- collection ----------------------------------------------------------

    def _collect_module_globals(
        self, module: Module, annotated_lines: Set[int]
    ) -> None:
        """Module-level globals may carry guard annotations too (the
        autotuner/resident-store singleton pattern): accept a
        ``guarded-by`` comment on a top-level assignment when it names a
        lock created at module scope (or ``none(...)``). Annotations
        naming no such lock stay orphaned (TPL003)."""
        module_locks: Set[str] = set()
        assigns: List[ast.AST] = []
        for node in module.tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            if not any(isinstance(t, ast.Name) for t in targets):
                continue
            assigns.append(node)
            if _is_lock_ctor(node.value):
                module_locks.add(
                    next(t.id for t in targets if isinstance(t, ast.Name))
                )
        for node in assigns:
            for line in range(node.lineno, node.end_lineno + 1):
                m = GUARD_RE.search(module.comment_on(line))
                if not m:
                    continue
                spec = m.group("spec")
                if NONE_RE.match(spec) or any(
                    s in module_locks for s in spec.split("|") if s
                ):
                    annotated_lines.add(line)

    def _collect(
        self, module: Module, cls: ast.ClassDef, annotated_lines: Set[int]
    ) -> _ClassInfo:
        info = _ClassInfo(cls)
        for name, call in decorator_names(cls):
            if name != "instrument_attrs":
                continue
            info.instrumented = True
            if call is not None:
                for kw in call.keywords:
                    if kw.arg != "exclude":
                        continue
                    for elt in ast.walk(kw.value):
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            info.excluded.add(elt.value)
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            for attr in _self_assign_targets(node):
                value = node.value
                if _is_lock_ctor(value):
                    info.locks.add(attr)
                    # Condition(self._mtx): holding the condition holds
                    # the wrapped lock.
                    if (
                        isinstance(value, ast.Call)
                        and value.args
                        and isinstance(value.args[0], ast.Attribute)
                        and isinstance(value.args[0].value, ast.Name)
                        and value.args[0].value.id == "self"
                    ):
                        info.aliases[attr] = value.args[0].attr
                # annotation on this line?
                for line in range(node.lineno, node.end_lineno + 1):
                    m = GUARD_RE.search(module.comment_on(line))
                    if m:
                        annotated_lines.add(line)
                        spec = m.group("spec")
                        if NONE_RE.match(spec):
                            info.guarded[attr] = (None, line)
                        else:
                            names = frozenset(
                                s for s in spec.split("|") if s
                            )
                            if not names:
                                continue
                            info.guarded[attr] = (names, line)
                        break
        return info

    # --- verification --------------------------------------------------------

    def _verify(self, module: Module, info: _ClassInfo) -> Iterator[Finding]:
        # TPL002/TPL004: the annotation itself must be coherent
        for attr, (locks, line) in sorted(info.guarded.items()):
            if locks is None:
                continue
            for lock in sorted(locks):
                if lock not in info.locks:
                    yield Finding(
                        module.rel,
                        line,
                        "TPL002",
                        f"{info.node.name}.{attr} guarded-by {lock!r}, but "
                        f"the class never assigns self.{lock} from a "
                        "threading lock factory",
                    )
        yield from self._verify_coverage(module, info)
        checked = {
            attr: locks
            for attr, (locks, _) in info.guarded.items()
            if locks is not None
        }
        if not checked:
            return
        for item in info.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if item.name == "__init__":
                    continue
                # the repo-wide `_locked` suffix convention: the caller
                # already holds the class's lock(s) when invoking these
                held: FrozenSet[str] = (
                    frozenset(info.locks)
                    if item.name.endswith("_locked")
                    else frozenset()
                )
                yield from self._walk_fn(module, info, checked, item, held)

    def _verify_coverage(
        self, module: Module, info: _ClassInfo
    ) -> Iterator[Finding]:
        """TPL005: on an ``@instrument_attrs`` class, every attribute
        mutated from >=2 thread-entry methods must carry SOME guarded-by
        annotation (a real lock or an explicit ``none(reason)``) or be
        listed in the decorator's ``exclude``. Mutation from two method
        entries is the static proxy for "two threads can write this"."""
        if not info.instrumented:
            return
        writers: Dict[str, Set[str]] = {}
        first_line: Dict[str, int] = {}
        for item in info.node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue
            for node in ast.walk(item):
                if not isinstance(node, ast.stmt):
                    continue
                for attr in _self_assign_targets(node):
                    writers.setdefault(attr, set()).add(item.name)
                    first_line.setdefault(attr, node.lineno)
        for attr, methods in sorted(writers.items()):
            if len(methods) < 2:
                continue
            if attr in info.guarded or attr in info.excluded:
                continue
            if attr in info.locks:
                continue
            yield Finding(
                module.rel,
                first_line[attr],
                "TPL005",
                f"{info.node.name}.{attr} is mutated from "
                f"{len(methods)} thread-entry methods "
                f"({', '.join(sorted(methods))}) but carries no "
                "guarded-by annotation",
            )

    def _expand(self, info: _ClassInfo, held: FrozenSet[str]) -> FrozenSet[str]:
        """Close the held set over Condition-wraps-lock aliases."""
        out = set(held)
        changed = True
        while changed:
            changed = False
            for cond, lock in info.aliases.items():
                if cond in out and lock not in out:
                    out.add(lock)
                    changed = True
        return frozenset(out)

    def _walk_fn(
        self,
        module: Module,
        info: _ClassInfo,
        checked: Dict[str, FrozenSet[str]],
        fn: ast.AST,
        held: FrozenSet[str],
    ) -> Iterator[Finding]:
        body = getattr(fn, "body", [])
        for stmt in body:
            yield from self._walk(module, info, checked, stmt, held)

    def _with_locks(self, node: ast.With) -> FrozenSet[str]:
        names = set()
        for item in node.items:
            ctx = item.context_expr
            if (
                isinstance(ctx, ast.Attribute)
                and isinstance(ctx.value, ast.Name)
                and ctx.value.id == "self"
            ):
                names.add(ctx.attr)
        return frozenset(names)

    def _walk(
        self,
        module: Module,
        info: _ClassInfo,
        checked: Dict[str, FrozenSet[str]],
        node: ast.AST,
        held: FrozenSet[str],
    ) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def may outlive the critical section (thread
            # targets, callbacks): analyze its body with nothing held
            yield from self._walk_fn(module, info, checked, node, frozenset())
            return
        if isinstance(node, ast.With):
            inner = held | self._with_locks(node)
            for item in node.items:
                yield from self._check_expr(
                    module, info, checked, item.context_expr, held
                )
            for stmt in node.body:
                yield from self._walk(module, info, checked, stmt, inner)
            return
        # statements: check embedded expressions, then recurse.
        # ExceptHandler / match_case carry statement bodies of their own,
        # so they must go through _walk (a `with` inside an except block
        # still counts), not be flattened as expressions.
        stmt_like = (ast.stmt, ast.ExceptHandler)
        match_case = getattr(ast, "match_case", None)
        if match_case is not None:
            stmt_like = stmt_like + (match_case,)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, stmt_like):
                yield from self._walk(module, info, checked, child, held)
            else:
                yield from self._check_expr(
                    module, info, checked, child, held
                )

    def _check_expr(
        self,
        module: Module,
        info: _ClassInfo,
        checked: Dict[str, FrozenSet[str]],
        expr: ast.AST,
        held: FrozenSet[str],
    ) -> Iterator[Finding]:
        effective = self._expand(info, held)
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._walk_fn(
                    module, info, checked, node, frozenset()
                )
                continue
            if not (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in checked
            ):
                continue
            locks = checked[node.attr]
            if not (locks & effective):
                want = "|".join(sorted(locks))
                have = ", ".join(sorted(effective)) or "none"
                yield Finding(
                    module.rel,
                    node.lineno,
                    "TPL001",
                    f"{info.node.name}.{node.attr} is guarded-by {want} "
                    f"but accessed holding: {have}",
                )
