"""The canonical example/test app: a merkle key-value store.

Mirrors abci/example/kvstore/kvstore.go: txs are "key=value" (or "key"
meaning key=key); "val:base64pubkey!power" txs update the validator set;
Query returns values (path "/key") with the app hash over sorted pairs.
Deterministic across restarts via an injected KVStore.
"""

from __future__ import annotations

import base64
import hashlib
import json
from typing import Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.storage.kv import KVStore, MemDB

VALIDATOR_TX_PREFIX = "val:"

CODE_TYPE_INVALID_TX_FORMAT = 1
CODE_TYPE_BANNED = 2
CODE_TYPE_UNKNOWN_ERROR = 3


SNAPSHOT_CHUNK_SIZE = 4096  # small so tests exercise multi-chunk flows
SNAPSHOTS_KEPT = 3


class KVStoreApplication(abci.BaseApplication):
    def __init__(self, db: Optional[KVStore] = None, snapshot_interval: int = 0):
        self._db = db or MemDB()
        self._pending: Dict[bytes, bytes] = {}
        self._pending_val_updates: List[abci.ValidatorUpdate] = []
        self._validators: Dict[str, int] = {}  # base64 pubkey -> power
        self._height = 0
        self._app_hash = b""
        # State-sync snapshots (the e2e app's snapshots.go role): payload
        # is the full serialized state, split into fixed-size chunks.
        self._snapshot_interval = snapshot_interval
        self._snapshots: Dict[int, tuple] = {}  # height -> (Snapshot, chunks)
        self._restoring: Optional[tuple] = None  # (Snapshot, app_hash, chunks)
        self._restore()

    # --- state management ---------------------------------------------------

    def _save_meta(self) -> None:
        self._db.set(
            b"__meta__",
            json.dumps(
                {
                    "height": self._height,
                    "app_hash": self._app_hash.hex(),
                    "validators": self._validators,
                }
            ).encode(),
        )

    def _restore(self) -> None:
        raw = self._db.get(b"__meta__")
        if raw is not None:
            meta = json.loads(raw.decode())
            self._height = meta["height"]
            self._app_hash = bytes.fromhex(meta["app_hash"])
            self._validators = meta.get("validators", {})

    def _compute_app_hash(self) -> bytes:
        h = hashlib.sha256()
        h.update(self._height.to_bytes(8, "big"))
        for k, v in self._db.iterator():
            if k.startswith(b"__"):
                continue
            h.update(len(k).to_bytes(4, "big") + k)
            h.update(len(v).to_bytes(4, "big") + v)
        for pk in sorted(self._validators):
            h.update(pk.encode() + self._validators[pk].to_bytes(8, "big"))
        return h.digest()

    # --- tx handling --------------------------------------------------------

    @staticmethod
    def _parse_tx(tx: bytes):
        """Returns (key, value) or raises ValueError."""
        text = tx.decode("utf-8", errors="strict")
        if text.startswith(VALIDATOR_TX_PREFIX):
            body = text[len(VALIDATOR_TX_PREFIX):]
            pubkey_b64, _, power_s = body.partition("!")
            if not pubkey_b64 or not power_s:
                raise ValueError("validator tx must be val:pubkey!power")
            base64.b64decode(pubkey_b64, validate=True)
            int(power_s)
            return None, None
        if "=" in text:
            key, _, value = text.partition("=")
        else:
            key = value = text
        if not key:
            raise ValueError("empty key")
        return key.encode(), value.encode()

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        try:
            self._parse_tx(req.tx)
        except ValueError:
            return abci.ResponseCheckTx(code=CODE_TYPE_INVALID_TX_FORMAT)
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def _exec_tx(self, tx: bytes) -> abci.ExecTxResult:
        try:
            text = tx.decode("utf-8")
            if text.startswith(VALIDATOR_TX_PREFIX):
                body = text[len(VALIDATOR_TX_PREFIX):]
                pubkey_b64, _, power_s = body.partition("!")
                power = int(power_s)
                raw = base64.b64decode(pubkey_b64, validate=True)
                if power == 0:
                    self._validators.pop(pubkey_b64, None)
                else:
                    self._validators[pubkey_b64] = power
                self._pending_val_updates.append(
                    abci.ValidatorUpdate("ed25519", raw, power)
                )
                return abci.ExecTxResult(
                    events=[
                        abci.Event(
                            "val_update",
                            [abci.EventAttribute("power", power_s, True)],
                        )
                    ]
                )
            key, value = self._parse_tx(tx)
            self._pending[key] = value
            return abci.ExecTxResult(
                events=[
                    abci.Event(
                        "app",
                        [
                            abci.EventAttribute("key", key.decode(), True),
                            abci.EventAttribute("creator", "kvstore", True),
                        ],
                    )
                ]
            )
        except ValueError:
            return abci.ExecTxResult(code=CODE_TYPE_INVALID_TX_FORMAT)

    # --- consensus connection -----------------------------------------------

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for vu in req.validators:
            self._validators[base64.b64encode(vu.pub_key_bytes).decode()] = vu.power
        if req.app_state_bytes:
            state = json.loads(req.app_state_bytes.decode() or "{}")
            for k, v in (state or {}).items():
                self._db.set(k.encode(), str(v).encode())
        self._height = 0
        self._app_hash = self._compute_app_hash()
        return abci.ResponseInitChain(app_hash=self._app_hash)

    def process_proposal(
        self, req: abci.RequestProcessProposal
    ) -> abci.ResponseProcessProposal:
        for tx in req.txs:
            try:
                self._parse_tx(tx)
            except ValueError:
                return abci.ResponseProcessProposal(abci.PROCESS_PROPOSAL_REJECT)
        return abci.ResponseProcessProposal(abci.PROCESS_PROPOSAL_ACCEPT)

    def finalize_block(
        self, req: abci.RequestFinalizeBlock
    ) -> abci.ResponseFinalizeBlock:
        self._pending = {}
        self._pending_val_updates = []
        results = [self._exec_tx(tx) for tx in req.txs]
        # Stage writes so the app hash reflects this block pre-commit.
        for k, v in self._pending.items():
            self._db.set(k, v)
        self._height = req.height
        self._app_hash = self._compute_app_hash()
        return abci.ResponseFinalizeBlock(
            tx_results=results,
            validator_updates=list(self._pending_val_updates),
            app_hash=self._app_hash,
        )

    def commit(self) -> abci.ResponseCommit:
        self._save_meta()
        if self._snapshot_interval and self._height % self._snapshot_interval == 0:
            self._take_snapshot()
        retain = self._height - 100 if self._height > 100 else 0
        return abci.ResponseCommit(retain_height=retain)

    # --- state-sync snapshots -------------------------------------------------

    def _serialize_state(self) -> bytes:
        pairs = {
            k.hex(): v.hex()
            for k, v in self._db.iterator()
            if not k.startswith(b"__")
        }
        return json.dumps(
            {
                "height": self._height,
                "app_hash": self._app_hash.hex(),
                "validators": self._validators,
                "pairs": pairs,
            },
            sort_keys=True,
        ).encode()

    def _take_snapshot(self) -> None:
        payload = self._serialize_state()
        chunks = [
            payload[i : i + SNAPSHOT_CHUNK_SIZE]
            for i in range(0, max(len(payload), 1), SNAPSHOT_CHUNK_SIZE)
        ]
        snap = abci.Snapshot(
            height=self._height,
            format=1,
            chunks=len(chunks),
            hash=hashlib.sha256(payload).digest(),
        )
        self._snapshots[self._height] = (snap, chunks)
        for h in sorted(self._snapshots):
            if len(self._snapshots) <= SNAPSHOTS_KEPT:
                break
            del self._snapshots[h]

    def list_snapshots(self, req) -> abci.ResponseListSnapshots:
        return abci.ResponseListSnapshots(
            snapshots=[s for s, _ in self._snapshots.values()]
        )

    def load_snapshot_chunk(self, req) -> abci.ResponseLoadSnapshotChunk:
        ent = self._snapshots.get(req.height)
        if ent is None or req.format != 1 or not (0 <= req.chunk < len(ent[1])):
            return abci.ResponseLoadSnapshotChunk(chunk=b"")
        return abci.ResponseLoadSnapshotChunk(chunk=ent[1][req.chunk])

    # Bound attacker-controlled chunk counts (a hostile Snapshot message
    # must not drive a multi-GB allocation; 16384 * 4 KB = 64 MB state).
    MAX_SNAPSHOT_CHUNKS = 16384

    def offer_snapshot(self, req) -> abci.ResponseOfferSnapshot:
        snap = req.snapshot
        if (
            snap is None
            or snap.format != 1
            or not (0 < snap.chunks <= self.MAX_SNAPSHOT_CHUNKS)
        ):
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT_FORMAT)
        self._restoring = (snap, req.app_hash, [None] * snap.chunks)
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(self, req) -> abci.ResponseApplySnapshotChunk:
        if self._restoring is None:
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ABORT)
        snap, trusted_app_hash, chunks = self._restoring
        if not (0 <= req.index < len(chunks)):
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_RETRY)
        chunks[req.index] = req.chunk
        if any(c is None for c in chunks):
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ACCEPT)
        payload = b"".join(chunks)
        if hashlib.sha256(payload).digest() != snap.hash:
            # A bad chunk poisoned the payload: restart the snapshot.
            self._restoring = (snap, trusted_app_hash, [None] * snap.chunks)
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_RETRY_SNAPSHOT
            )
        state = json.loads(payload.decode())
        for k, _ in list(self._db.iterator()):
            self._db.delete(k)
        for k_hex, v_hex in state["pairs"].items():
            self._db.set(bytes.fromhex(k_hex), bytes.fromhex(v_hex))
        self._height = state["height"]
        self._validators = state["validators"]
        # RECOMPUTE the app hash from the restored pairs — the payload's
        # own app_hash field is attacker-controlled; only a hash derived
        # from the actual state may be compared against the light-client-
        # verified one (the forged-pairs-with-real-hash attack).
        self._app_hash = self._compute_app_hash()
        if trusted_app_hash and self._app_hash != trusted_app_hash:
            # Wipe the poisoned restore; the node retries another snapshot.
            for k, _ in list(self._db.iterator()):
                self._db.delete(k)
            self._height = 0
            self._app_hash = b""
            self._validators = {}
            self._restoring = None
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_REJECT_SNAPSHOT
            )
        self._save_meta()
        self._restoring = None
        return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ACCEPT)

    # --- info/query ---------------------------------------------------------

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps({"size": self._height}),
            version="0.1.0",
            app_version=1,
            last_block_height=self._height,
            last_block_app_hash=self._app_hash,
        )

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "/val":
            return abci.ResponseQuery(
                code=abci.CODE_TYPE_OK,
                value=json.dumps(self._validators).encode(),
                height=self._height,
            )
        key = req.data
        value = self._db.get(key)
        return abci.ResponseQuery(
            code=abci.CODE_TYPE_OK,
            key=key,
            value=value or b"",
            log="exists" if value is not None else "does not exist",
            height=self._height,
        )
