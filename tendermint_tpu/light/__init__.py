"""Light client (reference: light/): stateless verification, bisection
client with trusted store, and the fork/attack detector."""

from tendermint_tpu.light.verifier import (
    DEFAULT_TRUST_LEVEL,
    HeaderExpiredError,
    InvalidHeaderError,
    NewValSetCantBeTrustedError,
    header_expired,
    validate_trust_level,
    verify,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
)
from tendermint_tpu.light.client import LightClient, TrustOptions
from tendermint_tpu.light.provider import Provider, MemoryProvider
from tendermint_tpu.light.store import LightStore

__all__ = [
    "DEFAULT_TRUST_LEVEL",
    "HeaderExpiredError",
    "InvalidHeaderError",
    "LightClient",
    "LightStore",
    "MemoryProvider",
    "NewValSetCantBeTrustedError",
    "Provider",
    "TrustOptions",
    "header_expired",
    "validate_trust_level",
    "verify",
    "verify_adjacent",
    "verify_backwards",
    "verify_non_adjacent",
]
