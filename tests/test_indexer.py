"""KV indexer search tests: key-level pagination and ordering
(internal/state/indexer tx/kv analog)."""

from tendermint_tpu.abci import types as abci
from tendermint_tpu.indexer.kv import KVIndexer, TxResult
from tendermint_tpu.libs.pubsub import Query
from tendermint_tpu.storage import MemDB


def _tx(height, index, payload, extra_events=()):
    events = [
        abci.Event(
            type="app",
            attributes=[
                abci.EventAttribute(key="kind", value="transfer", index=True)
            ],
        )
    ]
    events.extend(extra_events)
    return TxResult(
        height=height,
        index=index,
        tx=payload,
        result=abci.ExecTxResult(code=0, events=events),
    )


class TestSearchKeys:
    def _indexed(self, n_heights=20, per_height=5):
        idx = KVIndexer(MemDB())
        txs = []
        for h in range(1, n_heights + 1):
            for i in range(per_height):
                txs.append(_tx(h, i, b"tx-%d-%d" % (h, i)))
        idx.index_txs(txs)
        return idx, txs

    def test_keys_sorted_and_complete(self):
        idx, txs = self._indexed()
        keys = idx.search_tx_keys(Query.parse("app.kind = 'transfer'"))
        assert len(keys) == len(txs)
        assert keys == sorted(keys)
        assert keys[0][:2] == (1, 0)
        assert keys[-1][:2] == (20, 4)

    def test_page_decodes_only_its_records(self):
        idx, txs = self._indexed()
        # search_txs with a small limit must not decode beyond it
        decoded = []
        orig = idx.get_tx

        def counting_get(h):
            decoded.append(h)
            return orig(h)

        idx.get_tx = counting_get
        out = idx.search_txs(Query.parse("app.kind = 'transfer'"), limit=7)
        assert len(out) == 7
        assert len(decoded) == 7  # exactly the page, not all 100
        assert [(t.height, t.index) for t in out] == [
            (1, 0), (1, 1), (1, 2), (1, 3), (1, 4), (2, 0), (2, 1),
        ]

    def test_height_range_condition(self):
        idx, _ = self._indexed()
        keys = idx.search_tx_keys(
            Query.parse("tx.height >= 18 AND tx.height <= 19")
        )
        assert {k[0] for k in keys} == {18, 19}
        assert len(keys) == 10

    def test_hash_condition(self):
        idx, txs = self._indexed()
        h = txs[42].hash()
        keys = idx.search_tx_keys(Query.parse(f"tx.hash = '{h.hex()}'"))
        assert len(keys) == 1
        assert keys[0] == (txs[42].height, txs[42].index, h)

    def test_no_match(self):
        idx, _ = self._indexed()
        assert idx.search_tx_keys(Query.parse("app.kind = 'nope'")) == []
