"""Accumulate-with-deadline verify scheduler tests (SURVEY §7 latency
duality seam)."""

import threading
import time

import pytest

from tendermint_tpu.crypto.keys import Ed25519PrivKey
from tendermint_tpu.crypto.ed25519_ref import verify_zip215
from tendermint_tpu.crypto.scheduler import VerifyScheduler


def host_verify(pks, msgs, sigs):
    return [verify_zip215(p, m, s) for p, m, s in zip(pks, msgs, sigs)]


@pytest.fixture()
def sched():
    s = VerifyScheduler(host_verify, max_batch=32, max_delay=0.05)
    s.start()
    yield s
    s.stop()


def _signed(i: int):
    priv = Ed25519PrivKey.from_seed(bytes([i]) * 32)
    msg = b"sched-msg-%d" % i
    return priv.pub_key().bytes(), msg, priv.sign(msg)


class TestDeadline:
    def test_lone_entry_answers_within_deadline(self, sched):
        pk, msg, sig = _signed(1)
        t0 = time.monotonic()
        assert sched.verify(pk, msg, sig)
        elapsed = time.monotonic() - t0
        # one flush, no batch partners: the deadline bounds the wait
        assert elapsed < 1.0
        assert sched.flushes == 1

    def test_bad_signature_fails_only_itself(self, sched):
        good = [_signed(i) for i in range(4)]
        results = {}

        def submit(idx, pk, msg, sig):
            results[idx] = sched.verify(pk, msg, sig)

        threads = []
        for i, (pk, msg, sig) in enumerate(good):
            bad_sig = bytes(64) if i == 2 else sig
            t = threading.Thread(target=submit, args=(i, pk, msg, bad_sig))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=10)
        assert results == {0: True, 1: True, 2: False, 3: True}


class TestBatching:
    def test_concurrent_callers_share_flushes(self):
        calls = []

        def counting_verify(pks, msgs, sigs):
            calls.append(len(pks))
            return host_verify(pks, msgs, sigs)

        s = VerifyScheduler(counting_verify, max_batch=64, max_delay=0.2)
        s.start()
        try:
            entries = [_signed(i % 8) for i in range(40)]
            results = [None] * 40

            def submit(i):
                pk, msg, sig = entries[i]
                results[i] = s.verify(pk, msg, sig)

            threads = [
                threading.Thread(target=submit, args=(i,)) for i in range(40)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert all(results)
            # 40 concurrent verifies amortized into far fewer flushes;
            # only the 8 unique (pk, msg, sig) triples cost verifier
            # lanes — duplicates within a flush coalesce.
            assert len(calls) < 10, calls
            assert 8 <= sum(calls) <= 40
            assert s.entries_verified == 40
            assert sum(calls) + s.entries_coalesced == 40
        finally:
            s.stop()

    def test_duplicate_submissions_coalesce_to_one_lane(self):
        calls = []

        def counting_verify(pks, msgs, sigs):
            calls.append(len(pks))
            return host_verify(pks, msgs, sigs)

        s = VerifyScheduler(counting_verify, max_batch=64, max_delay=60.0)
        s.start()
        try:
            good = _signed(1)
            bad = (good[0], good[1], bytes(64))
            handles = [s.submit(*good) for _ in range(5)]
            handles += [s.submit(*bad) for _ in range(3)]
            # force the flush now rather than waiting out the deadline
            with s._wake:
                s.max_delay = 0.0
                s._wake.notify_all()
            oks = [s.wait(h) for h in handles]
            assert oks == [True] * 5 + [False] * 3
            # 8 submissions, 2 unique triples, 1 flush
            assert calls == [2], calls
            assert s.entries_coalesced == 6
            assert s.entries_verified == 8
        finally:
            s.stop()

    def test_max_batch_flushes_without_deadline(self):
        s = VerifyScheduler(host_verify, max_batch=4, max_delay=60.0)
        s.start()
        try:
            entries = [_signed(i) for i in range(4)]
            results = [None] * 4
            threads = [
                threading.Thread(
                    target=lambda i=i: results.__setitem__(
                        i, s.verify(*entries[i])
                    )
                )
                for i in range(4)
            ]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            # the batch-size trigger fired: nowhere near the 60s deadline
            assert time.monotonic() - t0 < 10
            assert all(results)
        finally:
            s.stop()


class TestContinuousBatching:
    def test_admits_lanes_while_dispatch_in_flight(self):
        """The tentpole property: lanes submitted while a kernel runs
        join the NEXT dispatch instead of waiting out a flush barrier."""
        release = threading.Event()
        entered = threading.Event()
        calls = []

        def gated_verify(pks, msgs, sigs):
            calls.append(len(pks))
            if len(calls) == 1:
                entered.set()
                release.wait(timeout=10)
            return host_verify(pks, msgs, sigs)

        s = VerifyScheduler(
            gated_verify, max_batch=4, max_delay=0.01,
            continuous=True, pipeline_depth=2,
        )
        s.start()
        try:
            first = [s.submit(*_signed(i)) for i in range(4)]  # size flush
            assert entered.wait(timeout=5)  # dispatch 1 is on the device
            # submit while in flight: these must be admitted, counted,
            # and dispatched without waiting for dispatch 1 to return
            second = [s.submit(*_signed(4 + i)) for i in range(4)]
            deadline = time.monotonic() + 5
            # poll through the locked stats() snapshot: the dispatcher is
            # still writing these counters, so a raw attribute read here
            # is a data race (tpusan hb mode flags it)
            while (
                s.stats()["dispatch_handoffs"] < 2
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)
            stats = s.stats()
            assert stats["dispatch_handoffs"] >= 2
            assert stats["inflight_admissions"] >= 1
            # the second batch resolves while the first is STILL blocked
            assert s.wait_many(second, timeout=5) == [True] * 4
            assert not first[0].done.is_set()
            release.set()
            assert s.wait_many(first, timeout=5) == [True] * 4
        finally:
            release.set()
            s.stop()

    def test_pipeline_depth_bounds_outstanding_dispatches(self):
        release = threading.Event()

        def gated_verify(pks, msgs, sigs):
            release.wait(timeout=10)
            return host_verify(pks, msgs, sigs)

        # size-only flushes (huge deadline): every batch is exactly
        # max_batch lanes, so the depth arithmetic below is exact
        s = VerifyScheduler(
            gated_verify, max_batch=2, max_delay=60.0,
            continuous=True, pipeline_depth=2,
        )
        s.start()
        try:
            handles = [s.submit(*_signed(i)) for i in range(8)]
            deadline = time.monotonic() + 5
            while s.dispatch_depth() < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            # both slots taken; the rest back-pressures into the
            # accumulator rather than growing the hand-off queue
            time.sleep(0.05)
            assert s.dispatch_depth() == 2
            assert s.pending_depth() == 4
            assert s.load_depth() == 8
            release.set()
            assert s.wait_many(handles, timeout=10) == [True] * 8
            assert s.load_depth() == 0
        finally:
            release.set()
            s.stop()

    def test_on_dispatch_reports_occupancy(self):
        seen = []
        release = threading.Event()

        def gated_verify(pks, msgs, sigs):
            release.wait(timeout=10)
            return host_verify(pks, msgs, sigs)

        s = VerifyScheduler(
            gated_verify, max_batch=2, max_delay=0.005,
            continuous=True, pipeline_depth=2,
            on_dispatch=lambda depth, lanes, reason: seen.append(
                (depth, lanes, reason)
            ),
        )
        s.start()
        try:
            handles = [s.submit(*_signed(i)) for i in range(4)]
            deadline = time.monotonic() + 5
            while len(seen) < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            release.set()
            s.wait_many(handles, timeout=10)
            assert len(seen) >= 2
            assert sum(lanes for _, lanes, _ in seen) == 4
            # with both batches held on the device, a later hand-off
            # observed occupancy 2 — the pipeline genuinely overlapped
            assert max(d for d, _, _ in seen) == 2
        finally:
            release.set()
            s.stop()

    def test_barrier_mode_spawns_no_workers(self):
        s = VerifyScheduler(host_verify, max_batch=8, continuous=False)
        s.start()
        try:
            assert s._workers == []
            assert s.verify(*_signed(1))
            assert s.dispatch_handoffs == 0  # flushed inline
        finally:
            s.stop()

    def test_submit_many_is_atomic_against_max_pending(self):
        release = threading.Event()

        def gated_verify(pks, msgs, sigs):
            release.wait(timeout=10)
            return host_verify(pks, msgs, sigs)

        s = VerifyScheduler(
            gated_verify, max_batch=4, max_delay=0.005,
            max_pending=6, continuous=True, pipeline_depth=1,
        )
        s.start()
        try:
            first = s.submit_many([_signed(i) for i in range(4)])
            deadline = time.monotonic() + 5
            while s.pending_depth() and time.monotonic() < deadline:
                time.sleep(0.005)
            filler = s.submit_many([_signed(10 + i) for i in range(4)])
            # 4 pending of 6: a group of 3 must be rejected WHOLE —
            # never 2 admitted + 1 shed
            from tendermint_tpu.crypto.scheduler import (
                SchedulerSaturatedError,
            )
            with pytest.raises(SchedulerSaturatedError):
                s.submit_many([_signed(20 + i) for i in range(3)])
            assert s.pending_depth() == 4
            release.set()
            assert all(s.wait_many(first + filler, timeout=10))
            assert s.entries_verified == 8  # nothing from the shed group
        finally:
            release.set()
            s.stop()

    def test_submit_many_groups_race_continuous_dispatcher(self):
        """Many atomic groups racing the dispatch workers: every group
        resolves all-or-nothing and no lane is lost or double-counted."""
        s = VerifyScheduler(
            host_verify, max_batch=8, max_delay=0.002,
            max_pending=64, continuous=True, pipeline_depth=2,
        )
        s.start()
        try:
            outcomes = {}

            def one_group(g):
                lanes = [_signed((g * 5 + i) % 16) for i in range(5)]
                try:
                    handles = s.submit_many(lanes)
                except Exception:
                    outcomes[g] = "shed"
                    return
                oks = s.wait_many(handles, timeout=10)
                outcomes[g] = "ok" if all(oks) else "partial"

            threads = [
                threading.Thread(target=one_group, args=(g,))
                for g in range(12)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert len(outcomes) == 12
            assert "partial" not in outcomes.values()
            admitted = sum(1 for v in outcomes.values() if v == "ok")
            assert admitted >= 1
            assert s.entries_verified == admitted * 5
        finally:
            s.stop()


class TestFailureModes:
    def test_verifier_exception_fails_closed(self):
        def broken(pks, msgs, sigs):
            raise RuntimeError("device on fire")

        s = VerifyScheduler(broken, max_batch=8, max_delay=0.01)
        s.start()
        try:
            pk, msg, sig = _signed(1)
            assert s.verify(pk, msg, sig) is False
        finally:
            s.stop()

    def test_stop_fails_pending_closed(self):
        started = threading.Event()

        def slow(pks, msgs, sigs):
            started.set()
            time.sleep(0.5)
            return [True] * len(pks)

        s = VerifyScheduler(slow, max_batch=1, max_delay=0.01)
        s.start()
        pk, msg, sig = _signed(1)
        out = {}
        t = threading.Thread(target=lambda: out.setdefault("r", s.verify(pk, msg, sig)))
        t.start()
        started.wait(timeout=5)
        s.stop()
        t.join(timeout=5)
        assert out["r"] in (True, False)  # resolved, never hung

    def test_submit_after_stop_raises(self):
        s = VerifyScheduler(host_verify)
        s.start()
        s.stop()
        with pytest.raises(RuntimeError):
            s.verify(b"\x00" * 32, b"m", b"\x00" * 64)
