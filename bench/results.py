"""Partial-result JSON: the on-disk evidence trail of a bench round.

The contract that makes the harness relay-resilient: each section's
result is persisted (atomically: tmp + rename) the moment the section
completes, so a later hang/SIGKILL/reboot cannot destroy earlier
evidence. The final ``BENCH_rNN.json`` is a *merge* of the partial
file — completed sections contribute their real measurement fragments
at the same top-level keys the single-child bench always used, and a
``sections`` block records per-section status / attempts / degradation
so a partially-failed round reads as partial truth, never as zero.

``--resume <partial.json>`` re-runs only sections whose status is not
``ok`` (bench/runner.py), which is why the partial schema is versioned
and validated on load.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

PARTIAL_SCHEMA = "tendermint-tpu-bench-partial/1"
MERGED_SCHEMA = "tendermint-tpu-bench/2"

# Per-section terminal statuses (ISSUE 6 tentpole).
OK = "ok"
TIMEOUT = "timeout"
CRASHED = "crashed"
SKIPPED = "skipped"
STATUSES = (OK, TIMEOUT, CRASHED, SKIPPED)

GO_CPU_BATCH_SIGS_PER_SEC = 30_000.0  # curve25519-voi batch verify, 1 core


def utc_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def new_partial(configured_backend: str) -> dict:
    return {
        "schema": PARTIAL_SCHEMA,
        "started_at": utc_now(),
        "configured_backend": configured_backend,
        "probe": {},
        "sections": {},
    }


def load_partial(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != PARTIAL_SCHEMA:
        raise ValueError(
            "not a bench partial-result file (schema=%r, want %r): %s"
            % (doc.get("schema"), PARTIAL_SCHEMA, path)
        )
    if not isinstance(doc.get("sections"), dict):
        raise ValueError("bench partial-result file has no sections map: %s" % path)
    return doc


def write_partial(doc: dict, path: str) -> None:
    """Atomic write: a watchdog kill (or operator ^C) between sections
    can never leave a torn JSON behind."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def section_block(
    status: str,
    attempts: int,
    duration_s: float,
    note: Optional[str] = None,
    degraded: bool = False,
    backend: Optional[str] = None,
    result: Optional[dict] = None,
) -> dict:
    assert status in STATUSES, status
    block = {
        "status": status,
        "attempts": attempts,
        "duration_s": round(duration_s, 2),
        "completed_at": utc_now(),
        "degraded": degraded,
        "note": note,
        "backend": backend,
    }
    if result is not None:
        block["result"] = result
    return block


def record_section(doc: dict, path: Optional[str], name: str, block: dict) -> None:
    doc["sections"][name] = block
    if path:
        write_partial(doc, path)


def merge(doc: dict, section_order: List[str]) -> dict:
    """Flatten a partial document into the headline BENCH JSON.

    Completed sections' result fragments are merged in registry order
    (so e.g. the stages section's ``impl`` refines the throughput
    section's); failed/skipped sections appear only in the ``sections``
    status map. The headline keys (metric/value/unit/vs_baseline) are
    always present — 0.0 when the throughput section itself died — so
    downstream tooling keyed on them keeps working.
    """
    sections: Dict[str, dict] = doc.get("sections", {})
    merged: dict = {
        "metric": "ed25519_batch_verify_throughput_b%s"
        % os.environ.get("BENCH_BATCH", "8192"),
        "value": 0.0,
        "unit": "sigs/s",
        "vs_baseline": 0.0,
    }
    ordered = [n for n in section_order if n in sections]
    ordered += [n for n in sections if n not in ordered]
    for name in ordered:
        block = sections[name]
        if block.get("status") == OK and isinstance(block.get("result"), dict):
            merged.update(block["result"])
    if merged.get("value"):
        merged["vs_baseline"] = round(
            merged["value"] / GO_CPU_BATCH_SIGS_PER_SEC, 3
        )
    merged["probe"] = doc.get("probe", {})
    merged["sections"] = {
        name: {k: v for k, v in block.items() if k != "result"}
        for name, block in sections.items()
    }
    merged["schema"] = MERGED_SCHEMA
    return merged


def exit_code(doc: dict) -> int:
    """0 = every section ok/skipped; 3 = partial evidence (some ok,
    some failed); 1 = nothing measured. Never the shell's 124 — a
    wedged section is an entry in ``sections``, not a whole-run kill."""
    statuses = [b.get("status") for b in doc.get("sections", {}).values()]
    failed = [s for s in statuses if s in (TIMEOUT, CRASHED)]
    ok = [s for s in statuses if s == OK]
    if not failed:
        return 0
    return 3 if ok else 1
