"""Remote signer: socket privval client/server.

The validator node keeps no key material; it exposes a listener that an
out-of-process signer dials into, and every GetPubKey/SignVote/
SignProposal round-trips over that connection. Direction matches the
reference (privval/signer_listener_endpoint.go / signer_dialer_endpoint.go):
the NODE listens, the SIGNER dials — so the key-holding process makes
only outbound connections. Double-sign protection lives on the signer
side (FilePV's last-sign-state), exactly as in the reference
(privval/file.go:135-170 behind signer_server.go).

Transports: ``tcp://host:port`` (wrapped in the p2p SecretConnection —
privval/secret_connection.go is the reference's own copy of the same
scheme) and ``unix:///path`` (plain; filesystem permissions are the
boundary, matching the reference's IsConnFromUnixSocket handling).

Wire format: 4-byte big-endian length frames carrying JSON
``{"type": ..., "body": {...}}`` with proto-encoded votes/proposals
base64ed inside — the same self-describing framing the ABCI socket
transport uses (abci/codec.py) in place of the reference's
varint-delimited proto unions (privval/msgs.go).

Runnable: ``python -m tendermint_tpu.privval.remote --addr tcp://... \
    --key-file ... --state-file ...`` starts a dialing signer process.
"""

from __future__ import annotations

import base64
import json
import socket
import struct
import threading
import time
from typing import Optional, Tuple

from tendermint_tpu.crypto.keys import (
    Ed25519PrivKey,
    PubKey,
    pubkey_from_type_and_bytes,
)
from tendermint_tpu.p2p.secret_connection import SecretConnectionError
from tendermint_tpu.privval.base import PrivValidator
from tendermint_tpu.privval.file_pv import DoubleSignError
from tendermint_tpu.types.block import Proposal, Vote

FRAME_HDR = struct.Struct(">I")
MAX_FRAME = 1 << 20  # signing payloads are small; 1 MiB is generous

DEFAULT_TIMEOUT_READ_WRITE = 5.0  # privval/signer_endpoint.go:21
DEFAULT_TIMEOUT_ACCEPT = 30.0
DEFAULT_DIAL_RETRY_INTERVAL = 0.1


class RemoteSignerError(Exception):
    """An error string returned by the remote signer (privval/errors.go)."""


class UnauthorizedSignerError(RemoteSignerError):
    """A dialer whose handshake identity is not in the allowlist."""


def parse_addr(addr: str) -> Tuple[str, object]:
    """Split ``tcp://h:p`` / ``unix:///path`` into (scheme, target)."""
    if addr.startswith("tcp://"):
        host, _, port = addr[6:].rpartition(":")
        return "tcp", (host or "127.0.0.1", int(port))
    if addr.startswith("unix://"):
        return "unix", addr[7:]
    raise ValueError(f"privval address must be tcp:// or unix://, got {addr}")


class _SocketStream:
    """sendall/recv_exact adapter SecretConnection expects.

    Partial reads persist in ``_buf`` across calls, so a socket timeout
    mid-frame loses nothing: the retried recv_exact resumes exactly where
    the interrupted one stopped (the signer's idle loop relies on this —
    a timeout is always safe to retry).
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def sendall(self, data: bytes) -> None:
        self._sock.sendall(data)

    def recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(n - len(self._buf))
            if not chunk:
                raise ConnectionError("privval connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out


class _Conn:
    """One framed connection, optionally SecretConnection-encrypted."""

    def __init__(self, sock: socket.socket, priv: Optional[Ed25519PrivKey]):
        self._sock = sock
        self._stream = _SocketStream(sock)
        self._secret = None
        if priv is not None:
            from tendermint_tpu.p2p.secret_connection import SecretConnection

            self._secret = SecretConnection(self._stream, priv)

    def send_msg(self, msg: dict) -> None:
        payload = json.dumps(msg, separators=(",", ":")).encode()
        if self._secret is not None:
            # the secure channel already length-delimits messages
            self._secret.send_msg(payload)
        else:
            self._stream.sendall(FRAME_HDR.pack(len(payload)) + payload)

    def recv_msg(self) -> dict:
        if self._secret is not None:
            payload = self._secret.recv_msg(max_size=MAX_FRAME)
        else:
            (n,) = FRAME_HDR.unpack(self._stream.recv_exact(4))
            if n > MAX_FRAME:
                raise ConnectionError("privval: frame too large")
            payload = self._stream.recv_exact(n)
        return json.loads(payload.decode())

    @property
    def remote_pubkey(self) -> Optional[PubKey]:
        return self._secret.remote_pubkey if self._secret else None

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


# --- node side --------------------------------------------------------------


class SignerListenerEndpoint:
    """Node-side endpoint: accepts the signer's inbound connection and
    serializes request/response exchanges over it
    (privval/signer_listener_endpoint.go:23-198)."""

    def __init__(
        self,
        addr: str,
        node_priv: Optional[Ed25519PrivKey] = None,
        accept_timeout: float = DEFAULT_TIMEOUT_ACCEPT,
        io_timeout: float = DEFAULT_TIMEOUT_READ_WRITE,
        authorized_keys: Optional[list] = None,
    ):
        self._scheme, self._target = parse_addr(addr)
        # tcp gets a SecretConnection; generate an ephemeral node identity
        # if the caller didn't supply one (the signer authenticates us, we
        # learn its identity from the handshake).
        if self._scheme == "tcp" and node_priv is None:
            node_priv = Ed25519PrivKey.generate()
        self._priv = node_priv if self._scheme == "tcp" else None
        self._accept_timeout = accept_timeout
        self._io_timeout = io_timeout
        # Optional allowlist of signer ed25519 pubkey bytes. Without it,
        # whoever dials first becomes the signer — bind to localhost or a
        # unix socket in that case (the reference has the same property;
        # its SecretConnection authenticates the channel, not a roster).
        self._authorized = (
            {bytes(k) for k in authorized_keys} if authorized_keys else None
        )
        if self._authorized is not None and self._scheme == "unix":
            # no SecretConnection on unix sockets -> no handshake identity
            # to check against; filesystem permissions are the boundary
            raise ValueError(
                "authorized_keys requires a tcp:// privval address"
            )
        self._lock = threading.Lock()
        self._conn: Optional[_Conn] = None
        self._listener: Optional[socket.socket] = None
        self._closed = False

    def start(self) -> None:
        if self._scheme == "tcp":
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind(self._target)
        else:
            import os

            try:
                os.unlink(self._target)
            except FileNotFoundError:
                pass
            ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ls.bind(self._target)
        # backlog > 1: a dead dial sitting in the queue must not make the
        # real signer's connection attempt bounce off a full backlog
        ls.listen(8)
        ls.settimeout(self._accept_timeout)
        self._listener = ls

    @property
    def listen_addr(self) -> str:
        assert self._listener is not None
        if self._scheme == "tcp":
            host, port = self._listener.getsockname()[:2]
            return f"tcp://{host}:{port}"
        return f"unix://{self._target}"

    def _ensure_conn(self, accept_timeout: Optional[float] = None) -> _Conn:
        if self._conn is not None:
            return self._conn
        if self._closed:
            raise RemoteSignerError("signer endpoint closed")
        if self._listener is None:
            raise RemoteSignerError("listener not started")
        if accept_timeout is not None:
            self._listener.settimeout(accept_timeout)
        sock, _ = self._listener.accept()
        sock.settimeout(self._io_timeout)
        try:
            conn = _Conn(sock, self._priv)
        except Exception:
            # handshake failure (port scanner, dropped dial, garbage):
            # release the accepted socket before surfacing
            sock.close()
            raise
        if self._authorized is not None:
            remote = conn.remote_pubkey
            if remote is None or remote.bytes() not in self._authorized:
                conn.close()
                raise UnauthorizedSignerError(
                    "signer connection rejected: unauthorized identity"
                )
        self._conn = conn
        return self._conn

    def wait_for_connection(self, max_wait: float) -> None:
        """Block until a signer has dialed in (SignerClient.WaitForConnection).

        Rejected or failed dial attempts — unauthorized identities, port
        scanners dropping mid-handshake — do not end the wait; only the
        deadline does.
        """
        deadline = time.monotonic() + max_wait
        rejected = 0
        with self._lock:
            old = self._listener.gettimeout() if self._listener else None
            try:
                while True:
                    try:
                        self._ensure_conn(
                            accept_timeout=max(
                                0.05, deadline - time.monotonic()
                            )
                        )
                        return
                    except socket.timeout:
                        pass
                    except (
                        UnauthorizedSignerError,
                        ConnectionError,
                        SecretConnectionError,
                        OSError,
                    ):
                        rejected += 1
                    if time.monotonic() >= deadline:
                        suffix = (
                            f" ({rejected} dial attempts rejected)"
                            if rejected
                            else ""
                        )
                        raise RemoteSignerError(
                            "timed out waiting for signer to connect"
                            + suffix
                        ) from None
            finally:
                if self._listener is not None and old is not None:
                    self._listener.settimeout(old)

    def send_request(self, msg: dict) -> dict:
        """One request/response exchange; drops the connection on IO error
        so the signer's redial can re-establish it.

        When no signer is connected, waits at most ``io_timeout`` for one
        to dial in — the caller is usually the consensus thread, which
        must fail fast and skip its vote rather than stall a round
        (accept_timeout is only for explicit wait_for_connection calls).
        """
        with self._lock:
            conn = self._ensure_conn(accept_timeout=self._io_timeout)
            try:
                conn.send_msg(msg)
                return conn.recv_msg()
            except (
                OSError,
                ConnectionError,
                SecretConnectionError,
                json.JSONDecodeError,
            ):
                self._drop_conn_locked()
                raise

    def _drop_conn_locked(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def close(self) -> None:
        self._closed = True
        with self._lock:
            self._drop_conn_locked()
            if self._listener is not None:
                self._listener.close()
                self._listener = None


class SignerClient(PrivValidator):
    """types.PrivValidator backed by the remote signer
    (privval/signer_client.go:18-151)."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str):
        self._endpoint = endpoint
        self._chain_id = chain_id
        self._cached_pubkey: Optional[PubKey] = None

    def ping(self) -> None:
        resp = self._endpoint.send_request({"type": "ping", "body": {}})
        if resp.get("type") != "ping":
            raise RemoteSignerError(f"unexpected ping response: {resp}")

    def get_pub_key(self) -> PubKey:
        if self._cached_pubkey is not None:
            return self._cached_pubkey
        resp = self._endpoint.send_request(
            {"type": "pubkey_request", "body": {"chain_id": self._chain_id}}
        )
        body = _require(resp, "pubkey_response")
        pub = pubkey_from_type_and_bytes(
            body["key_type"], base64.b64decode(body["pub_key"])
        )
        self._cached_pubkey = pub
        return pub

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        resp = self._endpoint.send_request(
            {
                "type": "sign_vote_request",
                "body": {
                    "chain_id": chain_id,
                    "vote": base64.b64encode(vote.to_proto_bytes()).decode(),
                },
            }
        )
        body = _require(resp, "signed_vote_response")
        signed = Vote.from_proto_bytes(base64.b64decode(body["vote"]))
        vote.signature = signed.signature
        vote.timestamp = signed.timestamp
        vote.extension_signature = signed.extension_signature

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        resp = self._endpoint.send_request(
            {
                "type": "sign_proposal_request",
                "body": {
                    "chain_id": chain_id,
                    "proposal": base64.b64encode(
                        proposal.to_proto_bytes()
                    ).decode(),
                },
            }
        )
        body = _require(resp, "signed_proposal_response")
        signed = Proposal.from_proto_bytes(base64.b64decode(body["proposal"]))
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp


def _require(resp: dict, expected_type: str) -> dict:
    body = resp.get("body", {})
    if body.get("error"):
        raise RemoteSignerError(body["error"])
    if resp.get("type") != expected_type:
        raise RemoteSignerError(
            f"expected {expected_type}, got {resp.get('type')}"
        )
    return body


# --- signer side ------------------------------------------------------------


class SignerServer:
    """Signer-side service: dials the node and answers signing requests
    from the wrapped PrivValidator (privval/signer_server.go:20-108 +
    signer_dialer_endpoint.go). The wrapped FilePV enforces double-sign
    protection; refusals travel back as error strings."""

    def __init__(
        self,
        addr: str,
        chain_id: str,
        priv_val: PrivValidator,
        signer_identity: Optional[Ed25519PrivKey] = None,
        dial_retry_interval: float = DEFAULT_DIAL_RETRY_INTERVAL,
        max_dial_retries: Optional[int] = None,
    ):
        self._scheme, self._target = parse_addr(addr)
        self._chain_id = chain_id
        self._priv_val = priv_val
        if self._scheme == "tcp" and signer_identity is None:
            signer_identity = Ed25519PrivKey.generate()
        self._identity = signer_identity if self._scheme == "tcp" else None
        self._dial_retry_interval = dial_retry_interval
        self._max_dial_retries = max_dial_retries
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="signer-server", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def serve_forever(self) -> None:
        self._run()

    def _dial(self) -> _Conn:
        """Dial with retries. ``max_dial_retries=None`` (the default)
        retries until stopped — a signer that gives up after a node
        restart window silently halts the validator, so bounded retries
        are opt-in (tests)."""
        last_err: Optional[Exception] = None
        attempts = 0
        while self._max_dial_retries is None or attempts < self._max_dial_retries:
            attempts += 1
            if self._stop.is_set():
                raise ConnectionError("signer stopped")
            try:
                if self._scheme == "tcp":
                    sock = socket.create_connection(self._target, timeout=5)
                else:
                    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    sock.connect(self._target)
                sock.settimeout(DEFAULT_TIMEOUT_READ_WRITE)
                return _Conn(sock, self._identity)
            except (OSError, SecretConnectionError, ConnectionError) as e:
                last_err = e
                time.sleep(self._dial_retry_interval)
        raise ConnectionError(f"signer could not dial node: {last_err}")

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._dial()
            except ConnectionError:
                return
            try:
                while not self._stop.is_set():
                    try:
                        req = conn.recv_msg()
                    except socket.timeout:
                        # safe: _SocketStream buffers partial reads, so a
                        # mid-frame timeout resumes without desync
                        continue
                    conn.send_msg(self._handle(req))
            except (
                OSError,
                ConnectionError,
                SecretConnectionError,
                json.JSONDecodeError,
            ):
                conn.close()
                continue

    def _handle(self, req: dict) -> dict:
        """privval/signer_requestHandler.go:14-78: every failure becomes a
        response-with-error, never a dropped connection."""
        typ = req.get("type")
        body = req.get("body", {})
        try:
            if typ == "ping":
                return {"type": "ping", "body": {}}
            if typ == "pubkey_request":
                pub = self._priv_val.get_pub_key()
                return {
                    "type": "pubkey_response",
                    "body": {
                        "key_type": pub.type,
                        "pub_key": base64.b64encode(pub.bytes()).decode(),
                    },
                }
            if typ == "sign_vote_request":
                vote = Vote.from_proto_bytes(base64.b64decode(body["vote"]))
                self._priv_val.sign_vote(body["chain_id"], vote)
                return {
                    "type": "signed_vote_response",
                    "body": {
                        "vote": base64.b64encode(
                            vote.to_proto_bytes()
                        ).decode()
                    },
                }
            if typ == "sign_proposal_request":
                proposal = Proposal.from_proto_bytes(
                    base64.b64decode(body["proposal"])
                )
                self._priv_val.sign_proposal(body["chain_id"], proposal)
                return {
                    "type": "signed_proposal_response",
                    "body": {
                        "proposal": base64.b64encode(
                            proposal.to_proto_bytes()
                        ).decode()
                    },
                }
            return {
                "type": "error",
                "body": {"error": f"unknown request type {typ!r}"},
            }
        except DoubleSignError as e:
            return {
                "type": f"signed_{'vote' if typ == 'sign_vote_request' else 'proposal'}_response",
                "body": {"error": f"double sign: {e}"},
            }
        except Exception as e:  # defensive: never kill the serve loop
            return {"type": "error", "body": {"error": str(e)}}


def main(argv: Optional[list] = None) -> int:
    """Run a dialing signer process around a FilePV."""
    import argparse

    from tendermint_tpu.privval.file_pv import FilePV

    ap = argparse.ArgumentParser(
        prog="python -m tendermint_tpu.privval.remote",
        description="out-of-process validator signer (dials the node)",
    )
    ap.add_argument("--addr", required=True, help="node privval listen addr")
    ap.add_argument("--chain-id", required=True)
    ap.add_argument("--key-file", required=True)
    ap.add_argument("--state-file", required=True)
    args = ap.parse_args(argv)

    pv = FilePV.load_or_generate(args.key_file, args.state_file)
    server = SignerServer(args.addr, args.chain_id, pv)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
