"""PartSet: a block split into parts for gossip (types/part_set.go).

Blocks are serialized and cut into 65536-byte parts, each with a merkle
inclusion proof against the PartSetHeader hash, so peers can stream and
verify parts independently.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import List, Optional

from tendermint_tpu.crypto import merkle
from tendermint_tpu.encoding.proto import (
    Reader,
    encode_bytes_field,
    encode_message_field,
    encode_varint_field,
)
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.types.block import BLOCK_PART_SIZE_BYTES, PartSetHeader


@dataclass
class Part:
    """types/part_set.go:23-28."""

    index: int
    bytes: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        """types/part_set.go:30-45."""
        if len(self.bytes) > BLOCK_PART_SIZE_BYTES:
            raise ValueError(
                f"part bytes exceed maximum size {BLOCK_PART_SIZE_BYTES}"
            )
        if self.proof.index != self.index:
            raise ValueError("part index mismatch with proof index")
        if len(self.proof.leaf_hash) != merkle.HASH_SIZE:
            raise ValueError("bad proof leaf hash")

    def to_proto_bytes(self) -> bytes:
        proof = (
            encode_varint_field(1, self.proof.total)
            + encode_varint_field(2, self.proof.index)
            + encode_bytes_field(3, self.proof.leaf_hash)
        )
        for aunt in self.proof.aunts:
            proof += encode_bytes_field(4, aunt)
        return (
            encode_varint_field(1, self.index)
            + encode_bytes_field(2, self.bytes)
            + encode_message_field(3, proof, always=True)
        )

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Part":
        r = Reader(data)
        index = 0
        payload = b""
        proof = merkle.Proof(total=0, index=0, leaf_hash=b"")
        for f, w in r.fields():
            if f == 1 and w == 0:
                index = r.read_varint()
            elif f == 2 and w == 2:
                payload = r.read_bytes()
            elif f == 3 and w == 2:
                pr = Reader(r.read_bytes())
                total = pidx = 0
                leaf = b""
                aunts: List[bytes] = []
                for pf, pw in pr.fields():
                    if pf == 1 and pw == 0:
                        total = pr.read_svarint()
                    elif pf == 2 and pw == 0:
                        pidx = pr.read_svarint()
                    elif pf == 3 and pw == 2:
                        leaf = pr.read_bytes()
                    elif pf == 4 and pw == 2:
                        aunts.append(pr.read_bytes())
                    else:
                        pr.skip(pw)
                proof = merkle.Proof(total=total, index=pidx, leaf_hash=leaf, aunts=aunts)
            else:
                r.skip(w)
        return cls(index, payload, proof)


class PartSet:
    """types/part_set.go:156-380: complete (from data) or accumulating
    (from a header, parts arriving from peers)."""

    def __init__(self, header: PartSetHeader):
        self.total = header.total
        self.hash = header.hash
        self.parts: List[Optional[Part]] = [None] * header.total
        self.parts_bit_array = BitArray(header.total)
        self.count = 0
        self.byte_size = 0

    @classmethod
    def from_data(cls, data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES) -> "PartSet":
        """types/part_set.go NewPartSetFromData: split + merkle proofs."""
        total = (len(data) + part_size - 1) // part_size
        chunks = [data[i * part_size : (i + 1) * part_size] for i in range(total)]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(total, root))
        for i, chunk in enumerate(chunks):
            part = Part(index=i, bytes=chunk, proof=proofs[i])
            ps.parts[i] = part
            ps.parts_bit_array.set_index(i, True)
        ps.count = total
        ps.byte_size = len(data)
        return ps

    def header(self) -> PartSetHeader:
        return PartSetHeader(self.total, self.hash)

    def has_header(self, header: PartSetHeader) -> bool:
        return self.header() == header

    def get_part(self, index: int) -> Optional[Part]:
        if 0 <= index < self.total:
            return self.parts[index]
        return None

    def is_complete(self) -> bool:
        return self.count == self.total

    def add_part(self, part: Part) -> bool:
        """types/part_set.go:272-304: False if present, raises on invalid."""
        if part.index >= self.total:
            raise ValueError("error part set unexpected index")
        if self.parts[part.index] is not None:
            return False
        part.validate_basic()
        if not part.proof.verify(self.hash, part.bytes):
            raise ValueError("error part set invalid proof")
        self.parts[part.index] = part
        self.parts_bit_array.set_index(part.index, True)
        self.count += 1
        self.byte_size += len(part.bytes)
        return True

    def get_reader(self) -> bytes:
        """Reassembled bytes; only valid when complete."""
        if not self.is_complete():
            raise ValueError("cannot read incomplete part set")
        return b"".join(p.bytes for p in self.parts)
