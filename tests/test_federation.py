"""Verifyd federation (tendermint_tpu/verifyd/federation.py, ISSUE 19).

Pins the routing subsystem's load-bearing properties: the consistent-
hash ring is deterministic (same key, same shard, forever) and
minimal-remap (losing a shard moves ONLY that shard's keys, each to
its next preference rung); committee digests are order-independent;
a FederationClient keeps whole committees on one shard, walks the
failover ladder on sheds and dead shards (host oracle last — never a
silent drop, never an unexplained verdict), bumps ``route_epoch`` on
every membership flip, and merges per-shard tenant SLO views into one
fleet view. The new wire fields (request 9/10, response 6, slab header
v4) round-trip and stay absent for pre-federation peers.
"""

import json
import threading
import time

import pytest

from tests.test_verifyd import host_verify, make_lanes
from tendermint_tpu.verifyd import federation, protocol
from tendermint_tpu.verifyd.client import (
    VerifydClient,
    VerifydRejectedError,
)
from tendermint_tpu.verifyd.federation import (
    FederationClient,
    HashRing,
    digest_validator_set,
)
from tendermint_tpu.verifyd.server import VerifydServer


def make_keys(n, tag=b"fed"):
    """n distinct synthetic 32-byte pubkeys (ring tests never verify)."""
    import hashlib

    return [
        hashlib.sha256(b"%s-%d" % (tag, i)).digest() for i in range(n)
    ]


def start_shards(n, verify_fns=None, **kw):
    """n in-process shard servers; returns (servers, addrs)."""
    servers, addrs = [], []
    for sid in range(n):
        fn = verify_fns[sid] if verify_fns else host_verify
        srv = VerifydServer(
            verify_fn=fn, max_batch=64, max_delay=0.002, shard_id=sid, **kw
        )
        srv.start()
        h, p = srv.address
        servers.append(srv)
        addrs.append(f"{h}:{p}")
    return servers, addrs


# --- consistent-hash ring ---------------------------------------------------


class TestHashRing:
    def test_same_key_always_same_shard(self):
        ring = HashRing(range(4))
        again = HashRing(range(4))
        for key in make_keys(64):
            assert ring.route(key) == again.route(key)
            assert ring.preference(key) == again.preference(key)

    def test_preference_is_a_permutation_of_shards(self):
        ring = HashRing(range(4))
        for key in make_keys(32):
            pref = ring.preference(key)
            assert sorted(pref) == [0, 1, 2, 3]

    def test_split_is_near_even(self):
        ring = HashRing(range(4))
        counts = {s: 0 for s in range(4)}
        for key in make_keys(1000):
            counts[ring.route(key)] += 1
        # 64 vnodes/shard: no shard should starve or hog
        assert min(counts.values()) >= 100
        assert max(counts.values()) <= 450

    def test_minimal_remap_on_shard_loss(self):
        """Killing shard d moves ONLY d's keys, each to its next
        preference rung — the property that makes failover cheap."""
        ring = HashRing(range(4))
        keys = make_keys(200)
        for dead in range(4):
            for key in keys:
                pref = ring.preference(key)
                routed = ring.route(key, dead={dead})
                if pref[0] != dead:
                    assert routed == pref[0]  # unaffected key: no remap
                else:
                    assert routed == pref[1]  # victim key: next rung

    def test_all_dead_returns_primary(self):
        ring = HashRing(range(2))
        key = make_keys(1)[0]
        assert ring.route(key, dead={0, 1}) == ring.preference(key)[0]


def test_digest_validator_set_order_independent():
    keys = make_keys(4)
    d = digest_validator_set(keys)
    assert digest_validator_set(list(reversed(keys))) == d
    assert digest_validator_set(keys[2:] + keys[:2]) == d
    assert digest_validator_set(keys[:3]) != d


# --- client-side routing ----------------------------------------------------


class TestRouting:
    def test_committee_rides_one_shard(self):
        """Every lane of a noted committee lands on the SAME shard, and
        repeat calls land on the same shard again."""
        seen = [set(), set()]

        def recorder(sid):
            def fn(pks, msgs, sigs):
                seen[sid].update(bytes(p) for p in pks)
                return [True] * len(pks)

            return fn

        servers, addrs = start_shards(2, verify_fns=[recorder(0), recorder(1)])
        fed = FederationClient(addrs)
        try:
            committees = [make_keys(4, tag=b"c%d" % c) for c in range(6)]
            for keys in committees:
                fed.note_validator_set(keys)
            pks = [pk for keys in committees for pk in keys]
            msgs = [b"m%d" % i for i in range(len(pks))]
            sigs = [b"\x07" * 64] * len(pks)
            assert fed.verify(pks, msgs, sigs) == [True] * len(pks)
            first = [set(s) for s in seen]
            assert fed.verify(pks, msgs, sigs) == [True] * len(pks)
            assert [set(s) for s in seen] == first  # stable placement
            for keys in committees:
                owners = {
                    sid for sid in range(2) if set(keys) & seen[sid]
                }
                assert len(owners) == 1  # never split across shards
            # both shards carry traffic and their slices are disjoint
            assert seen[0] and seen[1]
            assert not (seen[0] & seen[1])
        finally:
            fed.close()
            for s in servers:
                s.stop()

    def test_unknown_key_routes_by_its_own_digest(self):
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs)
        try:
            pk = make_keys(1)[0]
            assert fed.routing_key(pk) == pk
            digest = fed.note_validator_set([pk])
            assert fed.routing_key(pk) == digest
        finally:
            fed.close()
            for s in servers:
                s.stop()

    def test_requests_stamp_shard_and_epoch_on_the_wire(self):
        """The server sees the routed shard id (misroutes stay 0) and
        the router's epoch; a deliberately mis-stamped request is
        counted but still served — routing is placement advice."""
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs)
        try:
            pks, msgs, sigs = make_lanes(3)
            assert fed.verify(pks, msgs, sigs) == [True] * 3
            sid = fed.shard_for(pks[0])
            stats = servers[sid].stats()
            assert stats["misroutes"] == 0
            assert stats["route_epoch_seen"] == fed.route_epoch
            # cross-wire a request to the OTHER shard
            other = 1 - sid
            c = VerifydClient(addrs[other], fallback=False, shard_id=sid)
            assert c.verify(pks, msgs, sigs) == [True] * 3
            c.close()
            assert servers[other].stats()["misroutes"] == 1
        finally:
            fed.close()
            for s in servers:
                s.stop()


# --- failover ladder (CI explore target: TestFailover) ----------------------


class TestFailover:
    def test_dead_shard_reroutes_to_next_rung(self):
        """SIGKILL-equivalent (stopped server): the dead shard's keys
        re-route to the survivor, the dead shard is quarantined, and
        the route epoch bumps so servers can spot stale maps."""
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs, dead_retry_s=60.0)
        try:
            pks, msgs, sigs = make_lanes(4)
            committee = list(dict.fromkeys(pks))
            fed.note_validator_set(committee)
            victim = fed.shard_for(pks[0])
            epoch0 = fed.route_epoch
            servers[victim].stop()
            assert fed.verify(pks, msgs, sigs) == [True] * 4
            st = fed.stats()
            assert st["failovers"] >= 1
            assert st["rerouted_lanes"] >= 4
            assert st["host_fallback_lanes"] == 0
            assert fed.alive_shards() == [1 - victim]
            assert fed.route_epoch > epoch0
            # every shard client carries the bumped epoch on field 10
            for c in fed._clients:
                assert c.route_epoch == fed.route_epoch
            # survivor now owns the victim's keys
            assert fed.shard_for(pks[0]) == 1 - victim
        finally:
            fed.close()
            for s in servers:
                s.stop()

    def test_dead_shard_revives_after_quarantine(self):
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs, dead_retry_s=0.05)
        try:
            pks, msgs, sigs = make_lanes(4, seed=1)
            fed.note_validator_set(list(dict.fromkeys(pks)))
            victim = fed.shard_for(pks[0])
            h, p = servers[victim].address
            servers[victim].stop()
            assert fed.verify(pks, msgs, sigs) == [True] * 4
            # quarantined until a successful probe revives it (the
            # _dead entry outlives its expiry time, so this holds no
            # matter how slowly the sanitizer schedules us)
            assert victim in fed._dead
            # restart on the same port; the expired quarantine lets the
            # next call probe it, and success revives the shard
            servers[victim] = VerifydServer(
                verify_fn=host_verify, host=h, port=p,
                max_batch=64, max_delay=0.002, shard_id=victim,
            )
            servers[victim].start()
            time.sleep(0.1)  # quarantine expires
            epoch_dead = fed.route_epoch
            assert fed.verify(pks, msgs, sigs) == [True] * 4
            assert victim not in fed._dead
            assert victim in fed.alive_shards()
            assert fed.route_epoch > epoch_dead
        finally:
            fed.close()
            for s in servers:
                s.stop()

    def test_shed_walks_the_ladder(self):
        """A shard that sheds (RESOURCE_EXHAUSTED) keeps its quarantine
        clean — it is browning out, not dead — but the group's lanes
        re-route to the next rung and still verify."""
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs, failover_backoff_s=0.001)
        try:
            pks, msgs, sigs = make_lanes(4, seed=2)
            fed.note_validator_set(list(dict.fromkeys(pks)))
            victim = fed.shard_for(pks[0])

            def always_shed(*a, **kw):
                raise VerifydRejectedError(
                    protocol.STATUS_RESOURCE_EXHAUSTED, "brownout"
                )

            fed._clients[victim].verify = always_shed
            assert fed.verify(pks, msgs, sigs) == [True] * 4
            st = fed.stats()
            assert st["failovers"] >= 1
            assert st["host_fallback_lanes"] == 0
            # shed != dead: the shard stays in the alive set
            assert victim in fed.alive_shards()
        finally:
            fed.close()
            for s in servers:
                s.stop()

    def test_host_oracle_is_the_last_rung(self):
        """With every shard dead the verdicts still arrive — REAL
        host-oracle verdicts, positionally correct for a bad lane —
        and the fallback is accounted, never silent."""
        servers, addrs = start_shards(2)
        for s in servers:
            s.stop()
        fed = FederationClient(addrs, failover_backoff_s=0.001, timeout=5.0)
        try:
            pks, msgs, sigs = make_lanes(5, seed=3, bad={2})
            got = fed.verify(pks, msgs, sigs)
            assert got == [True, True, False, True, True]
            assert fed.stats()["host_fallback_lanes"] == 5
            assert fed.alive_shards() == []
        finally:
            fed.close()

    def test_mixed_batch_verdicts_merge_positionally(self):
        """Two committees on different shards, interleaved lanes, one
        bad signature: the verdict vector maps back lane-for-lane."""
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs)
        try:
            a_pks, a_msgs, a_sigs = make_lanes(3, seed=4, bad={1})
            b_pks, b_msgs, b_sigs = make_lanes(3, seed=5)
            fed.note_validator_set([a_pks[0]])
            fed.note_validator_set([b_pks[0]])
            pks = [a_pks[0], b_pks[0], a_pks[1], b_pks[1], a_pks[2]]
            msgs = [a_msgs[0], b_msgs[0], a_msgs[1], b_msgs[1], a_msgs[2]]
            sigs = [a_sigs[0], b_sigs[0], a_sigs[1], b_sigs[1], a_sigs[2]]
            assert fed.verify(pks, msgs, sigs) == [
                True, True, False, True, True,
            ]
        finally:
            fed.close()
            for s in servers:
                s.stop()


# --- gossip / fleet stats ---------------------------------------------------


class TestFleetStats:
    def test_server_stats_snapshot_over_the_wire(self):
        servers, addrs = start_shards(1)
        c = VerifydClient(addrs[0], fallback=False)
        try:
            pks, msgs, sigs = make_lanes(2, seed=6)
            assert c.verify(pks, msgs, sigs) == [True] * 2
            snap = c.server_stats()
            assert snap["shard_id"] == 0
            assert snap["stats"]["requests_served"] >= 1
            assert isinstance(snap["pinned_keys"], list)
            assert "brownout" in snap and "tenants" in snap
        finally:
            c.close()
            servers[0].stop()

    def test_refresh_marks_unreachable_shards_dead(self):
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs, dead_retry_s=60.0)
        try:
            servers[1].stop()
            snaps = fed.refresh(timeout=1.0)
            assert 0 in snaps and 1 not in snaps
            assert fed.alive_shards() == [0]
        finally:
            fed.close()
            servers[0].stop()

    def test_fleet_tenants_merges_shard_views(self):
        """The fleet view a tenant reasons about: p99 is the fleet MAX,
        slo the tightest bound, counters fleet SUMS, shedding an OR —
        the closed rung of ROADMAP item 5."""
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs)
        try:
            with fed._mtx:
                fed._gossip = {
                    0: {
                        "tenants": {
                            "chain-a": {
                                "p99_ms": 12.0, "slo_ms": 250,
                                "slo_sheds": 3, "sheds": 4,
                                "lanes": 100, "host_direct": 1,
                                "slo_shedding": 0,
                            }
                        }
                    },
                    1: {
                        "tenants": {
                            "chain-a": {
                                "p99_ms": 40.0, "slo_ms": 100,
                                "slo_sheds": 2, "sheds": 1,
                                "lanes": 50, "host_direct": 0,
                                "slo_shedding": 1,
                            }
                        }
                    },
                }
            view = fed.fleet_tenants()["chain-a"]
            assert view["p99_ms"] == 40.0
            assert view["slo_ms"] == 100
            assert view["slo_sheds"] == 5
            assert view["sheds"] == 5
            assert view["lanes"] == 150
            assert view["host_direct"] == 1
            assert view["slo_shedding"] == 1
        finally:
            fed.close()
            for s in servers:
                s.stop()

    def test_refresh_drops_and_counts_inflated_gossip_snapshot(self):
        """A misbehaving shard's oversized STATS snapshot must not
        balloon the fleet view: before the gossip caps, refresh() stored
        whatever JSON the shard returned. Now the snapshot is dropped
        whole and counted in gossip_rejects, while the shard itself
        stays alive (it answered; only its gossip is rejected)."""
        from tendermint_tpu.verifyd import federation as fedmod

        servers, addrs = start_shards(2)
        fed = FederationClient(addrs, dead_retry_s=60.0)
        try:
            inflated = {
                "tenants": {
                    f"t{i}": {"p99_ms": 1.0}
                    for i in range(fedmod.MAX_GOSSIP_TENANTS + 1)
                }
            }
            fed._clients[1].server_stats = (
                lambda timeout=2.0, _s=inflated: _s
            )
            snaps = fed.refresh(timeout=2.0)
            assert 0 in snaps and 1 not in snaps
            assert fed.gossip_rejects == 1
            assert fed.alive_shards() == [0, 1]
            # the rejected snapshot's tenants never reach the fleet view
            assert "t0" not in fed.fleet_tenants()
            assert fed.stats()["gossip_rejects"] == 1
        finally:
            fed.close()
            for s in servers:
                s.stop()

    def test_sanitize_snapshot_caps(self):
        from tendermint_tpu.verifyd import federation as fedmod

        sanitize = FederationClient._sanitize_snapshot
        ok = {"tenants": {"a": {"p99_ms": 1.0}}, "brownout": {}}
        assert sanitize(ok) is ok
        with pytest.raises(ValueError, match="tenants"):
            sanitize({
                "tenants": {
                    f"t{i}": {} for i in range(fedmod.MAX_GOSSIP_TENANTS + 1)
                }
            })
        with pytest.raises(ValueError, match="B$"):
            sanitize({"pad": "x" * fedmod.MAX_GOSSIP_SNAPSHOT_BYTES})
        with pytest.raises(ValueError, match="not a dict"):
            sanitize(["not", "a", "dict"])

    def test_slo_propagates_to_every_shard(self):
        """Satellite 1: one ``--tenant-slo`` reaches ALL shards
        identically (wire field 8), so the merged fleet view carries
        the same budget each shard enforced locally."""
        servers, addrs = start_shards(2)
        fed = FederationClient(addrs, tenant="chain-slo", slo_ms=250)
        try:
            committees = [make_keys(4, tag=b"s%d" % c) for c in range(6)]
            for keys in committees:
                fed.note_validator_set(keys)
            pks = [pk for keys in committees for pk in keys]
            msgs = [b"slo-%d" % i for i in range(len(pks))]
            sigs = [b"\x08" * 64] * len(pks)

            # noop verifiers: the synthetic lanes aren't real signatures
            for s in servers:
                s.stop()
            servers, addrs2 = start_shards(
                2, verify_fns=[lambda *a: [True] * len(a[0])] * 2
            )
            fed.close()
            fed = FederationClient(addrs2, tenant="chain-slo", slo_ms=250)
            for keys in committees:
                fed.note_validator_set(keys)
            assert fed.verify(pks, msgs, sigs) == [True] * len(pks)
            served = [
                s for s in servers
                if s.tenant_stats().get("chain-slo", {}).get("lanes", 0) > 0
            ]
            assert len(served) == 2  # both shards saw the tenant...
            for s in served:  # ...with the SAME budget
                assert s.tenant_stats()["chain-slo"]["slo_ms"] == 250
        finally:
            fed.close()
            for s in servers:
                s.stop()


# --- wire fields ------------------------------------------------------------


class TestWireFields:
    def test_request_shard_and_epoch_roundtrip(self):
        req = protocol.VerifyRequest(
            kind=protocol.KIND_RAW,
            pks=[b"\x01" * 32],
            msgs=[b"m"],
            sigs=[b"\x02" * 64],
            shard_id=3,
            route_epoch=17,
        )
        got = protocol.decode_request(
            protocol.encode_request(req)
        )
        assert got.shard_id == 3
        assert got.route_epoch == 17

    def test_unrouted_request_omits_the_fields(self):
        """shard_id=-1 / epoch=0 must be wire-IDENTICAL to a
        pre-federation client: absent, not zero-valued."""
        req = protocol.VerifyRequest(
            kind=protocol.KIND_RAW,
            pks=[b"\x01" * 32],
            msgs=[b"m"],
            sigs=[b"\x02" * 64],
        )
        wire = protocol.encode_request(req)
        routed = protocol.encode_request(
            protocol.VerifyRequest(
                kind=protocol.KIND_RAW,
                pks=[b"\x01" * 32],
                msgs=[b"m"],
                sigs=[b"\x02" * 64],
                shard_id=0,
                route_epoch=1,
            )
        )
        assert len(routed) > len(wire)
        got = protocol.decode_request(wire)
        assert got.shard_id == -1
        assert got.route_epoch == 0

    def test_response_shard_id_roundtrip_and_omission(self):
        resp = protocol.VerifyResponse(
            status=protocol.STATUS_OK, verdicts=[True], shard_id=2
        )
        got = protocol.decode_response(
            protocol.encode_response(resp)
        )
        assert got.shard_id == 2
        bare = protocol.decode_response(
            protocol.encode_response(
                protocol.VerifyResponse(
                    status=protocol.STATUS_OK, verdicts=[True]
                )
            )
        )
        assert bare.shard_id == -1

    def test_shard_id_zero_survives_the_shift(self):
        """Shard 0 is a VALID identity: the +1 wire shift must not
        collapse it into 'absent'."""
        req = protocol.VerifyRequest(
            kind=protocol.KIND_RAW,
            pks=[b"\x01" * 32],
            msgs=[b"m"],
            sigs=[b"\x02" * 64],
            shard_id=0,
        )
        got = protocol.decode_request(
            protocol.encode_request(req)
        )
        assert got.shard_id == 0


# --- process-wide backend wiring --------------------------------------------


class TestBackendWiring:
    def test_single_address_is_not_a_federation(self, monkeypatch):
        monkeypatch.setenv(federation.SHARDS_ENV, "127.0.0.1:1")
        federation.reset_federation()
        try:
            assert federation.federation_client() is None
            assert federation.federation_backend() is None
        finally:
            federation.reset_federation()

    def test_env_configures_and_caches_the_client(self, monkeypatch):
        servers, addrs = start_shards(2)
        monkeypatch.setenv(federation.SHARDS_ENV, ",".join(addrs))
        federation.reset_federation()
        try:
            fed = federation.federation_client()
            assert fed is not None
            assert federation.federation_client() is fed  # cached
            pks, msgs, sigs = make_lanes(3, seed=7)
            backend = federation.federation_backend()
            assert backend(pks, msgs, sigs) == [True] * 3
        finally:
            federation.reset_federation()
            for s in servers:
                s.stop()

    def test_federation_outranks_single_remote(self, monkeypatch):
        from tendermint_tpu.crypto import batch as crypto_batch

        servers, addrs = start_shards(2)
        monkeypatch.setenv(federation.SHARDS_ENV, ",".join(addrs))
        monkeypatch.setenv(
            "TENDERMINT_TPU_VERIFY_REMOTE", "127.0.0.1:1"
        )
        federation.reset_federation()
        try:
            backend = crypto_batch.remote_verify_backend()
            assert backend is not None
            pks, msgs, sigs = make_lanes(3, seed=8)
            # the dead single-remote address would fail; the federation
            # serves — proof the digest router owns placement
            assert backend(pks, msgs, sigs) == [True] * 3
        finally:
            federation.reset_federation()
            for s in servers:
                s.stop()
