"""Mesh lifecycle and per-device health for the sharded verify engine.

The policy half of ``parallel/``: :mod:`tendermint_tpu.parallel.sharding`
compiles and dispatches lane-sharded kernels; this module decides *which
devices* each dispatch may span and settles the health consequences.

One process-wide :class:`MeshManager` (``manager``) owns:

- **Discovery + sizing** — the mesh defaults to every device; the
  ``[ops] mesh_devices`` config (``configure()``) caps it, and the
  ``TENDERMINT_TPU_MESH`` env var applies when the config is unset
  (the same precedence pattern as ``verify_remote`` in verifyd/client).
  A resolved size below 2 disables sharding: the engines keep their
  single-device path.
- **Per-device health** — one :class:`~ops.device_policy.DeviceHealth`
  machine per device id with ``retry_budget=1``: the first failure
  *attributed* to a device (``DeviceFault.device`` or a ``device N``
  mention in the error text) puts that device in COOLDOWN and every
  later :meth:`plan` builds a smaller mesh around it. A sick chip
  degrades the mesh to (n-1)-way — it never forces the host fallback;
  that remains the job of the *shared* machine in ops/device_policy.
- **COOLDOWN re-admission** — once an excluded device's backoff
  expires, the next plan admits it as that machine's half-open probe:
  a successful sharded dispatch re-promotes it (``readmissions``),
  a failure re-arms the cooldown with doubled backoff.
- **Forced meshes** — ``verify_batch_sharded(..., mesh=...)`` scopes an
  explicit mesh via the :meth:`forced` context manager; plans built
  inside use exactly those devices (minus health-excluded ones) and
  skip the lane floor.

Everything here is control-plane: no jax import until a plan is
actually requested, so config plumbing (node assembly, verifyd CLI)
stays cheap.
"""

from __future__ import annotations

import os
import re
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.sanitizer import instrument_attrs


def _dp():
    # Lazy: tendermint_tpu.ops eagerly imports the ed25519 engine (and
    # with it jax), and this module is imported from light config-
    # plumbing paths (node assembly, verifyd CLI) that must stay cheap.
    from tendermint_tpu.ops import device_policy

    return device_policy


SIG_AXIS = "sig"

MESH_ENV = "TENDERMINT_TPU_MESH"

# Lane floor for implicit sharding: below 4 x the smallest padding
# bucket (ops/ed25519_batch._BUCKETS[0] == 64) the 8-way padding and
# dispatch overhead beat the parallelism, so small batches stay on the
# single-device path (regression-pinned in tests/test_mesh.py).
MIN_MESH_LANES = 256

# "device 3" / "chip 3" / "TPU_3"-shaped mentions in error text; only
# ids actually in the failing plan are accepted as culprits.
_DEVICE_RE = re.compile(r"(?:device|chip|tpu)[\s_:#]*(\d+)", re.IGNORECASE)


def attribute_device(
    exc: BaseException, device_ids: Tuple[int, ...]
) -> Optional[int]:
    """Best-effort culprit attribution for a failed sharded dispatch.

    An explicit integer ``device`` attribute wins (the fault-injection
    harness and any future backend shim set it); otherwise a 'device N'
    mention in the error text. Anything else — including ids not in the
    plan — is None: unattributed failures take the engines' ordinary
    per-chunk fallback instead of shrinking the mesh blindly.
    """
    dev = getattr(exc, "device", None)
    if isinstance(dev, bool):
        dev = None
    if isinstance(dev, int):
        return dev if dev in device_ids else None
    m = _DEVICE_RE.search(str(exc))
    if m:
        parsed = int(m.group(1))
        if parsed in device_ids:
            return parsed
    return None


class MeshPlan:
    """One batch's sharding decision: the mesh to dispatch on plus the
    per-device health attempt tokens to settle at collect time."""

    __slots__ = ("mesh", "device_ids", "attempts", "forced", "readmitted")

    def __init__(self, mesh, device_ids, attempts, forced):
        self.mesh = mesh
        self.device_ids: Tuple[int, ...] = device_ids
        self.attempts: Dict[int, device_policy.Attempt] = attempts
        self.forced = forced
        # probe devices already counted as re-admitted (on_success runs
        # once per chunk; the same plan serves many chunks)
        self.readmitted: set = set()

    @property
    def n_dev(self) -> int:
        return len(self.device_ids)


def _dev_id(device) -> int:
    return int(getattr(device, "id", 0))


@instrument_attrs
class MeshManager:
    """Process-wide mesh sizing + per-device health (module docstring)."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        cooldown_base: float = 5.0,
        cooldown_max: float = 300.0,
    ):
        self._mtx = threading.Lock()
        self._clock = clock
        self.cooldown_base = cooldown_base
        self.cooldown_max = cooldown_max
        self._configured = 0  # [ops] mesh_devices cap; 0 = unset  # guarded-by: _mtx
        self._config_gen = 0  # bumped per configure()/reset()  # guarded-by: _mtx
        self._devices: Optional[tuple] = None  # discovery cache  # guarded-by: _mtx
        self._health: Dict[int, device_policy.DeviceHealth] = {}  # guarded-by: _mtx
        self._meshes: Dict[Tuple[int, ...], object] = {}  # Mesh per id-set  # guarded-by: _mtx
        self._metrics = None  # OpsMetrics, bound by the node  # guarded-by: _mtx
        # observability (monotone; tests read these via snapshot())
        self.exclusions = 0  # guarded-by: _mtx
        self.readmissions = 0  # guarded-by: _mtx
        self.dispatches = 0  # guarded-by: _mtx
        self._tls = threading.local()  # forced-mesh scope, per thread

    # --- wiring --------------------------------------------------------------

    def configure(self, n_devices: int) -> None:
        """Apply the ``[ops] mesh_devices`` cap (0 = all devices; the
        TENDERMINT_TPU_MESH env var applies only when this is 0)."""
        with self._mtx:
            self._configured = max(0, int(n_devices or 0))
            self._config_gen += 1

    def config_gen(self) -> int:
        """Monotone configuration generation (bumped by configure() and
        reset()). Consumers that cache anything derived from the mesh
        size — the scheduler's mesh-aware max_batch default — compare
        against this instead of baking a pre-configuration value in."""
        with self._mtx:
            return self._config_gen

    def bind_metrics(self, metrics) -> None:
        """Mirror mesh activity into a libs/metrics.OpsMetrics. Last
        binder wins (one node per process outside tests)."""
        with self._mtx:
            self._metrics = metrics
        if metrics is not None:
            metrics.mesh_devices.set(0)

    def reset(self) -> None:
        """Tests/operator: drop all per-device state and overrides."""
        with self._mtx:
            self._configured = 0
            self._config_gen += 1
            self._devices = None
            self._health.clear()
            self.exclusions = 0
            self.readmissions = 0
            self.dispatches = 0

    # --- forced-mesh scope ----------------------------------------------------

    @contextmanager
    def forced(self, mesh):
        """Scope an explicit mesh (verify_batch_sharded(..., mesh=...)):
        plans built inside dispatch on exactly these devices, minus any
        health-excluded ones, regardless of the configured cap."""
        prev = getattr(self._tls, "mesh", None)
        self._tls.mesh = mesh
        try:
            yield
        finally:
            self._tls.mesh = prev

    def forced_mesh(self):
        return getattr(self._tls, "mesh", None)

    # --- sizing ---------------------------------------------------------------

    def _discover_locked(self) -> list:
        if self._devices is None:
            try:
                import jax

                self._devices = tuple(jax.devices())
            except Exception:  # no backend: sharding simply unavailable
                self._devices = ()
        return list(self._devices)

    def _limit_locked(self, n_available: int) -> int:
        limit = self._configured
        if limit <= 0:
            env = os.environ.get(MESH_ENV, "").strip().lower()
            if env in ("off", "none", "host"):
                return 1
            if env and env not in ("all", "auto", "0"):
                try:
                    limit = int(env)
                except ValueError:
                    limit = 0
        if limit <= 0:
            limit = n_available
        return min(limit, n_available)

    def device_count(self) -> int:
        """Devices a non-forced plan would span right now (config/env
        capped); 1 when sharding is unavailable. Never raises — the
        scheduler uses this to size cross-client super-batches."""
        try:
            with self._mtx:
                devs = self._discover_locked()
                if len(devs) < 2:
                    return 1
                return max(1, self._limit_locked(len(devs)))
        except Exception:  # discovery is best-effort from light callers
            return 1

    def _health_locked(self, did: int) -> device_policy.DeviceHealth:
        h = self._health.get(did)
        if h is None:
            # retry_budget=1: ONE attributed failure excludes the chip —
            # retrying a chunk on a mesh containing a known-sick device
            # would just fail again and double the lost latency.
            h = _dp().DeviceHealth(
                retry_budget=1,
                cooldown_base=self.cooldown_base,
                cooldown_max=self.cooldown_max,
                clock=self._clock,
            )
            self._health[did] = h
        return h

    # --- planning -------------------------------------------------------------

    def plan(self) -> Optional[MeshPlan]:
        """The device set for one batch, or None for the single-device
        path. COOLDOWN devices whose backoff expired join as half-open
        probes; their attempt outcome is settled by on_success /
        on_failure (or released by abandon)."""
        forced = self.forced_mesh()
        with self._mtx:
            if forced is not None:
                devs = list(forced.devices.flat)
            else:
                devs = self._discover_locked()
                if not devs:
                    return None
                limit = self._limit_locked(len(devs))
                if limit < 2:
                    return None
                devs = devs[:limit]
            health = {_dev_id(d): self._health_locked(_dev_id(d)) for d in devs}
        usable: List = []
        attempts: Dict[int, device_policy.Attempt] = {}
        for d in devs:
            did = _dev_id(d)
            att = health[did].begin_attempt("mesh")
            if att is None:
                continue
            usable.append(d)
            attempts[did] = att
        min_dev = 1 if forced is not None else 2
        if len(usable) < min_dev:
            for did, att in attempts.items():
                health[did].release_probe(att)
            return None
        ids = tuple(_dev_id(d) for d in usable)
        if forced is not None and len(usable) == len(devs):
            return MeshPlan(forced, ids, attempts, True)
        return MeshPlan(self._mesh_for(ids, usable), ids, attempts, forced is not None)

    def replan(self, plan: MeshPlan) -> Optional[MeshPlan]:
        """A fresh, smaller plan after on_failure excluded a device.
        None when no usable mesh remains — the caller degrades to the
        single-device path (NOT the host)."""
        return self.plan()

    def _mesh_for(self, ids: Tuple[int, ...], devices: list):
        with self._mtx:
            mesh = self._meshes.get(ids)
            if mesh is None:
                from jax.sharding import Mesh

                mesh = Mesh(np.asarray(devices), (SIG_AXIS,))
                self._meshes[ids] = mesh
            return mesh

    # --- outcome settlement ---------------------------------------------------

    def note_dispatch(self, plan: MeshPlan, lanes: int) -> None:
        """One sharded chunk of ``lanes`` padded lanes went out across
        ``plan``'s devices; mirror it into metrics."""
        with self._mtx:
            self.dispatches += 1
            metrics = self._metrics
        if metrics is not None:
            metrics.mesh_devices.set(plan.n_dev)
            metrics.mesh_dispatches.labels(devices=str(plan.n_dev)).inc()
            per_dev = lanes // max(1, plan.n_dev)
            for did in plan.device_ids:
                metrics.mesh_lanes.labels(device=str(did)).inc(per_dev)

    def on_success(self, plan: MeshPlan) -> None:
        """A sharded chunk materialized: record success on every device
        attempt (re-promoting any probing device)."""
        # Settle the plan under _mtx: one plan serves every chunk of a
        # batch, and concurrent on_success/on_failure calls otherwise
        # race on plan.attempts (popped by on_failure) and
        # plan.readmitted (mutated here).
        with self._mtx:
            metrics = self._metrics
            attempts = list(plan.attempts.items())
            health = {did: self._health.get(did) for did, _ in attempts}
            newly_readmitted = [
                did
                for did, att in attempts
                if att.probe
                and health.get(did) is not None
                and did not in plan.readmitted
            ]
            plan.readmitted.update(newly_readmitted)
            self.readmissions += len(newly_readmitted)
        for did, att in attempts:
            h = health.get(did)
            if h is None:
                continue
            h.record_success(att)
        for did in newly_readmitted:
            tracing.instant("mesh_device_readmitted", device=did)
            if metrics is not None:
                metrics.mesh_readmissions.labels(device=str(did)).inc()

    def on_failure(self, plan: MeshPlan, exc: BaseException) -> Optional[int]:
        """A sharded dispatch/collect failed. Returns the culprit device
        id when the failure is attributable (that device enters its
        COOLDOWN; the caller should replan and retry the chunk), else
        None (the caller keeps its ordinary per-chunk fallback). Either
        way, in-flight probe reservations are settled."""
        culprit = attribute_device(exc, plan.device_ids)
        with self._mtx:
            metrics = self._metrics
            if culprit is not None:
                self.exclusions += 1
            attempts = list(plan.attempts.items())
            health = {did: self._health.get(did) for did, _ in attempts}
            readmitted = set(plan.readmitted)
            if culprit is not None:
                # Drop the culprit's token under the lock: the same plan
                # object may serve later (concurrent) chunks of the
                # batch, and a stale on_success must not re-promote a
                # chip just sent to COOLDOWN.
                plan.attempts.pop(culprit, None)
        stall = _dp().DeviceStallError(
            "sharded dispatch failed"
            + (f" (device {culprit} excluded)" if culprit is not None else "")
        )
        for did, att in attempts:
            h = health.get(did)
            if h is None:
                continue
            if did == culprit:
                h.record_failure(exc, att)
            elif att.probe and did not in readmitted:
                # The probe rode a dispatch that died: re-arm its cooldown
                # rather than concluding anything about the device.
                h.record_failure(stall, att)
        if culprit is not None:
            tracing.instant("mesh_device_excluded", device=culprit)
            if metrics is not None:
                metrics.mesh_exclusions.labels(device=str(culprit)).inc()
        return culprit

    def abandon(self, plan: MeshPlan) -> None:
        """The engine built a plan but never dispatched on it (e.g. the
        shared health machine denied every chunk): release un-dispatched
        probe reservations so excluded devices stay probe-able."""
        with self._mtx:
            attempts = list(plan.attempts.items())
            health = {did: self._health.get(did) for did, _ in attempts}
            readmitted = set(plan.readmitted)
        for did, att in attempts:
            h = health.get(did)
            if h is not None and did not in readmitted:
                h.release_probe(att)

    # --- inspection -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._mtx:
            health = dict(self._health)
            out = {
                "configured": self._configured,
                "exclusions": self.exclusions,
                "readmissions": self.readmissions,
                "dispatches": self.dispatches,
            }
        dp = _dp()
        out["devices"] = {did: h.state for did, h in sorted(health.items())}
        out["excluded"] = sorted(
            did
            for did, h in health.items()
            if h.state in (dp.COOLDOWN, dp.DISABLED)
        )
        return out


# The process-wide instance both engines, the scheduler, verifyd, and
# the node share.
manager = MeshManager()


def plan_for_lanes(lanes: int) -> Optional[MeshPlan]:
    """The engines' gate: a plan when the batch is worth sharding, None
    for the single-device path. An explicit (forced) mesh skips the
    lane floor — the caller asked for sharding."""
    if manager.forced_mesh() is None and lanes < MIN_MESH_LANES:
        return None
    return manager.plan()
