"""Light client with trusted store, bisection, and fork detection.

Mirrors light/client.go: trust options anchor the first block (height +
hash from a social-consensus source); VerifyLightBlockAtHeight then walks
forward sequentially or by skipping (bisection against the trust level),
or backwards via the hash chain. After verification the new block is
cross-checked against witness providers (light/detector.go); a
conflicting header yields LightClientAttackEvidence reported to all
providers.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field as dc_field
from typing import Callable, List, Optional

from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.metrics import LightMetrics
from tendermint_tpu.light import batch as light_batch
from tendermint_tpu.light import verifier
from tendermint_tpu.light.provider import (
    HeightTooHighError,
    LightBlockNotFoundError,
    Provider,
    ProviderError,
)
from tendermint_tpu.light.store import LightStore
from tendermint_tpu.types import Fraction
from tendermint_tpu.types.evidence import LightClientAttackEvidence
from tendermint_tpu.types.light import LightBlock

DEFAULT_PRUNING_SIZE = 1000
DEFAULT_MAX_CLOCK_DRIFT = 10.0  # seconds
DEFAULT_MAX_BLOCK_LAG = 10.0


class LightClientError(Exception):
    pass


class DivergedHeaderError(LightClientError):
    """A witness returned a conflicting verified header."""

    def __init__(self, evidence: LightClientAttackEvidence, witness_index: int):
        self.evidence = evidence
        self.witness_index = witness_index
        super().__init__("conflicting headers detected: light client attack")


@dataclass
class TrustOptions:
    """light.TrustOptions: period + (height, hash) root of trust."""

    period: float  # trusting period, seconds
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period <= 0:
            raise ValueError("negative or zero trusting period")
        if self.height <= 0:
            raise ValueError("negative or zero height")
        if len(self.hash) != 32:
            raise ValueError(f"expected hash size 32, got {len(self.hash)}")


class LightClient:
    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: List[Provider],
        store: Optional[LightStore] = None,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        max_clock_drift: float = DEFAULT_MAX_CLOCK_DRIFT,
        sequential: bool = False,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
        now: Optional[Callable[[], Timestamp]] = None,
        bisect_batching: Optional[bool] = None,
        metrics: Optional[LightMetrics] = None,
    ):
        trust_options.validate()
        verifier.validate_trust_level(trust_level)
        self.chain_id = chain_id
        self.trusting_period = trust_options.period
        self.trust_level = trust_level
        self.max_clock_drift = max_clock_drift
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = store or LightStore()
        self.sequential = sequential
        self.pruning_size = pruning_size
        # one-super-batch-per-round bisection (light/batch.py); None
        # defers to the TENDERMINT_TPU_LIGHT_BATCH env gate
        self.bisect_batching = (
            light_batch.batching_enabled()
            if bisect_batching is None
            else bisect_batching
        )
        self.metrics = metrics or LightMetrics.nop()
        self._now = now or (lambda: Timestamp.from_unix_ns(_time.time_ns()))
        self._initialize(trust_options)

    # --- initialization ------------------------------------------------------

    def _initialize(self, opts: TrustOptions) -> None:
        """light/client.go initializeWithTrustOptions: fetch the anchor
        block from the primary, check hash + self-consistency."""
        existing = self.store.light_block(opts.height)
        if existing is not None and existing.hash() == opts.hash:
            return
        lb = self.primary.light_block(opts.height)
        if lb.hash() != opts.hash:
            raise LightClientError(
                f"expected header's hash {opts.hash.hex()}, but got "
                f"{lb.hash().hex()}"
            )
        lb.validate_basic(self.chain_id)
        # 1/3+ of the valset must have signed (we can't check 2/3 of the
        # *previous* set without trusting more).
        from tendermint_tpu.types.validation import verify_commit_light_trusting

        verify_commit_light_trusting(
            self.chain_id, lb.validator_set, lb.signed_header.commit, Fraction(1, 3)
        )
        self.store.save_light_block(lb)

    # --- public API ----------------------------------------------------------

    def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        return self.store.light_block(height)

    def latest_trusted(self) -> Optional[LightBlock]:
        return self.store.latest_light_block()

    def update(self, now: Optional[Timestamp] = None) -> Optional[LightBlock]:
        """Verify the primary's latest block (client.go Update)."""
        latest = self.primary.light_block(0)
        trusted = self.store.latest_light_block()
        if trusted is not None and latest.height <= trusted.height:
            return None
        return self.verify_light_block_at_height(latest.height, now)

    def verify_light_block_at_height(
        self, height: int, now: Optional[Timestamp] = None
    ) -> LightBlock:
        """client.go VerifyLightBlockAtHeight:413."""
        if height <= 0:
            raise ValueError("height must be positive")
        now = now or self._now()
        existing = self.store.light_block(height)
        if existing is not None:
            return existing
        latest = self.store.latest_light_block()
        if latest is None:
            raise LightClientError("no trusted state; initialize first")
        if height < latest.height:
            return self._backwards(latest, height)
        target = self._fetch_from_primary(height)
        self.verify_header(target, now)
        return target

    def verify_header(self, new_block: LightBlock, now: Timestamp) -> None:
        """client.go VerifyHeader: forward verification + detector."""
        trusted = self.store.latest_light_block()
        if trusted is None:
            raise LightClientError("no trusted state")
        if new_block.height <= trusted.height:
            raise LightClientError(
                f"height {new_block.height} is not above trusted "
                f"{trusted.height}"
            )
        new_block.validate_basic(self.chain_id)
        if self.sequential:
            self._verify_sequential(trusted, new_block, now)
        else:
            self._verify_skipping(trusted, new_block, now)
        self._detect_divergence(new_block, now)
        self.store.save_light_block(new_block)
        if self.store.size() > self.pruning_size:
            self.store.prune(self.pruning_size)

    # --- verification strategies ---------------------------------------------

    def _verify_sequential(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> None:
        """client.go verifySequential:554: fetch every header in between."""
        current = trusted
        for h in range(trusted.height + 1, new_block.height + 1):
            interim = (
                new_block if h == new_block.height else self._fetch_from_primary(h)
            )
            verifier.verify_adjacent(
                current.signed_header,
                interim.signed_header,
                interim.validator_set,
                self.trusting_period,
                now,
                self.max_clock_drift,
            )
            if h != new_block.height:
                self.store.save_light_block(interim)
            current = interim

    def _verify_skipping(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> None:
        """client.go verifySkipping:647: bisection. Trust the target if
        trustLevel of the current trusted valset signed it; otherwise
        bisect towards the trusted block. Batched by default: the whole
        pivot ladder of a round rides one scheduler super-batch
        (light/batch.py) instead of one device call per pivot."""
        if self.bisect_batching:
            return self._verify_skipping_batched(trusted, new_block, now)
        return self._verify_skipping_sequential(trusted, new_block, now)

    def _verify_skipping_batched(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> None:
        """Same accept/reject decisions as the sequential loop, proved
        by the parity suite: each round plans the full descending pivot
        ladder [target, mid, mid-of-mid, ...] down to base+1, verifies
        every candidate in ONE super-batch, then accepts the first
        (shallowest) candidate that verifies — exactly the candidate the
        sequential descent would have accepted. Hard errors surface at
        the first candidate the sequential walk would have visited;
        verdicts of deeper candidates are ignored past that point."""
        pivots = {}  # height -> prefetched pivot, reused across rounds
        trace_base = trusted
        current = new_block
        rounds = 0
        try:
            while True:
                base = trace_base
                candidates = [current]
                # the exception owed if evaluation descends off the ladder:
                # a pivot fetch/validate failure, or "cannot split further"
                ladder_stop: Optional[Exception] = None
                while ladder_stop is None:
                    pivot_height = (base.height + candidates[-1].height) // 2
                    if pivot_height in (base.height, candidates[-1].height):
                        ladder_stop = LightClientError(
                            "bisection failed: cannot split further"
                        )
                        break
                    pivot = pivots.get(pivot_height)
                    if pivot is None:
                        try:
                            pivot = self._fetch_from_primary(pivot_height)
                            pivot.validate_basic(self.chain_id)
                        except Exception as exc:
                            ladder_stop = exc
                            break
                        pivots[pivot_height] = pivot
                    candidates.append(pivot)
                rounds += 1
                with tracing.span(
                    "light_round",
                    round=rounds,
                    base=base.height,
                    target=current.height,
                    candidates=len(candidates),
                ):
                    outcomes = light_batch.evaluate_candidates(
                        self.chain_id,
                        base,
                        candidates,
                        self.trusting_period,
                        now,
                        self.max_clock_drift,
                        self.trust_level,
                    )
                accepted = None
                for cand, out in zip(candidates, outcomes):
                    if out.kind == light_batch.OK:
                        accepted = cand
                        break
                    if out.kind == light_batch.BISECT:
                        continue
                    raise out.error
                if accepted is None:
                    # every candidate needs a deeper pivot and there is none
                    raise ladder_stop
                if accepted.height == new_block.height:
                    return
                trace_base = accepted
                self.store.save_light_block(accepted)
                current = new_block
        finally:
            self.metrics.bisection_rounds.observe(rounds)

    def _verify_skipping_sequential(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> None:
        """The reference's one-call-per-pivot loop, kept verbatim as the
        parity baseline (TENDERMINT_TPU_LIGHT_BATCH=off)."""
        verification_trace = [trusted]
        current = new_block
        while True:
            base = verification_trace[-1]
            try:
                verifier.verify(
                    base.signed_header,
                    base.validator_set,
                    current.signed_header,
                    current.validator_set,
                    self.trusting_period,
                    now,
                    self.max_clock_drift,
                    self.trust_level,
                )
            except verifier.NewValSetCantBeTrustedError:
                # Not enough trusted power: bisect to the midpoint.
                pivot_height = (base.height + current.height) // 2
                if pivot_height in (base.height, current.height):
                    raise LightClientError(
                        "bisection failed: cannot split further"
                    )
                pivot = self._fetch_from_primary(pivot_height)
                pivot.validate_basic(self.chain_id)
                current = pivot
                continue
            # Verified against base.
            if current.height == new_block.height:
                return
            verification_trace.append(current)
            self.store.save_light_block(current)
            current = new_block

    def _backwards(self, trusted: LightBlock, height: int) -> LightBlock:
        """client.go backwards:722: follow LastBlockID hashes down."""
        current = trusted
        for h in range(trusted.height - 1, height - 1, -1):
            interim = self._fetch_from_primary(h)
            verifier.verify_backwards(interim.signed_header.header, current.signed_header.header)
            self.store.save_light_block(interim)
            current = interim
        return current

    # --- detector (light/detector.go) ----------------------------------------

    def _detect_divergence(self, new_block: LightBlock, now: Timestamp) -> None:
        """detector.go:28-120: ask every witness for the same height; a
        conflicting header is an attack only if the witness's block itself
        verifies against our trust root — an unverifiable witness is just a
        bad witness and gets dropped (detector.go examineConflictingHeader)."""
        if not self.witnesses:
            return
        trusted = self.store.light_block_before(new_block.height)
        # Gather every conflicting witness header first, then verify all
        # of them against the trusted root in ONE scheduler super-batch
        # (batched mode) — a round of witness cross-checks costs one
        # device call, not one per witness.
        bad_witnesses = []
        conflicts = []  # (witness index, witness, block, basic_ok)
        for i, witness in enumerate(list(self.witnesses)):
            try:
                w_block = witness.light_block(new_block.height)
            except (LightBlockNotFoundError, HeightTooHighError, ProviderError):
                continue
            if w_block.hash() == new_block.hash():
                continue
            # Verify the witness trace against the trusted root before
            # treating the conflict as evidence; garbage from a faulty
            # witness must not DoS the client or spawn bogus evidence.
            try:
                w_block.validate_basic(self.chain_id)
            except (ValueError, verifier.InvalidHeaderError):
                conflicts.append((i, witness, w_block, False))
                continue
            conflicts.append((i, witness, w_block, True))
        outcomes = {}
        to_verify = [
            c for c in conflicts if c[3] and trusted is not None
        ]
        if to_verify:
            if self.bisect_batching:
                evaluated = light_batch.evaluate_candidates(
                    self.chain_id,
                    trusted,
                    [c[2] for c in to_verify],
                    self.trusting_period,
                    now,
                    self.max_clock_drift,
                    self.trust_level,
                )
            else:
                evaluated = [
                    light_batch._resolve_sequential(
                        self.chain_id, trusted, c[2], self.trusting_period,
                        now, self.max_clock_drift, self.trust_level,
                    )
                    for c in to_verify
                ]
            for c, out in zip(to_verify, evaluated):
                outcomes[c[0]] = out
        for i, witness, w_block, basic_ok in conflicts:
            out = outcomes.get(i)
            if not basic_ok:
                bad_witnesses.append(witness)
                continue
            if out is not None and out.kind != light_batch.OK:
                err = out.error
                if isinstance(err, (ValueError, verifier.InvalidHeaderError)):
                    # includes NewValSetCantBeTrusted: an unverifiable
                    # witness is just a bad witness, not an attack
                    bad_witnesses.append(witness)
                    continue
                raise err  # e.g. NotEnoughVotingPowerError, raw as before
            # Conflict verified on both sides: a real light-client attack
            # (detector.go:122-215 abridged: common height = latest trusted
            # below the conflict).
            common = self.store.light_block_before(new_block.height)
            ev = LightClientAttackEvidence(
                conflicting_block=w_block,
                common_height=common.height if common else new_block.height - 1,
                total_voting_power=(
                    common.validator_set.total_voting_power() if common else 0
                ),
                timestamp=common.signed_header.header.time
                if common
                else new_block.signed_header.header.time,
            )
            for p in [self.primary] + self.witnesses:
                if p is not witness:
                    try:
                        p.report_evidence(ev)
                    except ProviderError:
                        pass
            raise DivergedHeaderError(ev, i)
        for w in bad_witnesses:
            self.witnesses.remove(w)

    # --- provider plumbing ----------------------------------------------------

    def _fetch_from_primary(self, height: int) -> LightBlock:
        lb = self.primary.light_block(height)
        if lb.height != height:
            raise LightClientError(
                f"primary returned height {lb.height}, wanted {height}"
            )
        return lb
