"""ABCI clients (abci/client/client.go).

The client interface mirrors the reference's Client (one method per ABCI
call plus lifecycle); LocalClient wraps an in-process Application behind
a mutex exactly like abci/client/local_client.go:40 (the app sees
serialized calls). Socket/gRPC transports are separate modules.
"""

from __future__ import annotations

import threading
from typing import Optional

from tendermint_tpu.abci import types as abci


class AbciClient:
    """abci/client/client.go:25: transport-agnostic client contract."""

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def is_running(self) -> bool:
        return True

    def echo(self, msg: str) -> str:
        raise NotImplementedError

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def prepare_proposal(
        self, req: abci.RequestPrepareProposal
    ) -> abci.ResponsePrepareProposal:
        raise NotImplementedError

    def process_proposal(
        self, req: abci.RequestProcessProposal
    ) -> abci.ResponseProcessProposal:
        raise NotImplementedError

    def extend_vote(self, req: abci.RequestExtendVote) -> abci.ResponseExtendVote:
        raise NotImplementedError

    def verify_vote_extension(
        self, req: abci.RequestVerifyVoteExtension
    ) -> abci.ResponseVerifyVoteExtension:
        raise NotImplementedError

    def finalize_block(
        self, req: abci.RequestFinalizeBlock
    ) -> abci.ResponseFinalizeBlock:
        raise NotImplementedError

    def commit(self) -> abci.ResponseCommit:
        raise NotImplementedError

    def list_snapshots(
        self, req: abci.RequestListSnapshots
    ) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(
        self, req: abci.RequestOfferSnapshot
    ) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError


class LocalClient(AbciClient):
    """In-process app behind one mutex (abci/client/local_client.go:40)."""

    def __init__(self, app: abci.Application):
        self._app = app
        self._mtx = threading.Lock()
        self._running = False

    def start(self) -> None:
        self._running = True

    def stop(self) -> None:
        self._running = False

    def is_running(self) -> bool:
        return self._running

    def echo(self, msg: str) -> str:
        return msg

    def info(self, req):
        with self._mtx:
            return self._app.info(req)

    def query(self, req):
        with self._mtx:
            return self._app.query(req)

    def check_tx(self, req):
        with self._mtx:
            return self._app.check_tx(req)

    def init_chain(self, req):
        with self._mtx:
            return self._app.init_chain(req)

    def prepare_proposal(self, req):
        with self._mtx:
            return self._app.prepare_proposal(req)

    def process_proposal(self, req):
        with self._mtx:
            return self._app.process_proposal(req)

    def extend_vote(self, req):
        with self._mtx:
            return self._app.extend_vote(req)

    def verify_vote_extension(self, req):
        with self._mtx:
            return self._app.verify_vote_extension(req)

    def finalize_block(self, req):
        with self._mtx:
            return self._app.finalize_block(req)

    def commit(self):
        with self._mtx:
            return self._app.commit()

    def list_snapshots(self, req):
        with self._mtx:
            return self._app.list_snapshots(req)

    def offer_snapshot(self, req):
        with self._mtx:
            return self._app.offer_snapshot(req)

    def load_snapshot_chunk(self, req):
        with self._mtx:
            return self._app.load_snapshot_chunk(req)

    def apply_snapshot_chunk(self, req):
        with self._mtx:
            return self._app.apply_snapshot_chunk(req)
