"""BlockExecutor end-to-end against the kvstore app: the first chain
slice — propose, validate, apply, repeat (internal/state/execution_test.go
analog, without consensus gossip)."""

import pytest

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.state import StateStore, state_from_genesis
from tendermint_tpu.state.execution import BlockExecutor, InvalidBlockError
from tendermint_tpu.storage import MemDB
from tendermint_tpu.storage.blockstore import BlockStore
from tendermint_tpu.types import BlockID, ExtendedCommit
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.part_set import PartSet
from tests.helpers import CHAIN_ID, make_commit, make_validators


BASE_NS = 1_700_000_000_000_000_000


def make_chain_env(n_vals=4):
    privs, vset = make_validators(n_vals)
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time=Timestamp.from_unix_ns(BASE_NS),
        validators=[
            GenesisValidator(pub_key=v.pub_key, power=v.voting_power)
            for v in vset.validators
        ],
    )
    state = state_from_genesis(gen)
    app = KVStoreApplication()
    client = LocalClient(app)
    client.start()
    init = client.init_chain(
        abci.RequestInitChain(chain_id=CHAIN_ID, initial_height=1)
    )
    state.app_hash = init.app_hash
    state_store = StateStore(MemDB())
    state_store.save(state)
    block_store = BlockStore(MemDB())
    clock = {"ns": BASE_NS}

    def now():
        clock["ns"] += 1_000_000_000
        return Timestamp.from_unix_ns(clock["ns"])

    executor = BlockExecutor(state_store, client, block_store, now=now)
    return executor, state, privs, vset, app


def advance_one_height(executor, state, privs, vset, txs, last_ec):
    height = state.last_block_height + 1
    proposer = state.validators.get_proposer().address

    class _Pool:
        def lock(self): pass
        def unlock(self): pass
        def reap_max_bytes_max_gas(self, mb, mg): return txs
        def update(self, *a, **k): pass
        def remove_tx_by_key(self, key): pass

    executor.mempool = _Pool()
    block = executor.create_proposal_block(height, state, last_ec, proposer)
    assert executor.process_proposal(block, state)
    parts = PartSet.from_data(block.to_proto_bytes())
    block_id = BlockID(block.hash(), parts.header())
    new_state = executor.apply_block(state, block_id, block)
    executor.block_store.save_block(
        block, parts, make_commit(block_id, height, 0, vset, privs)
    )
    commit = make_commit(
        block_id, height, 0, vset, privs,
        time_ns=BASE_NS + height * 1_000_000_000,
    )
    return new_state, ExtendedCommit.wrap_commit(commit)


class TestChainSlice:
    def test_three_heights_with_txs(self):
        executor, state, privs, vset, app = make_chain_env()
        ec = ExtendedCommit()
        hashes = [state.app_hash]
        for h, txs in enumerate([[b"a=1"], [b"b=2", b"c=3"], []], start=1):
            state, ec = advance_one_height(executor, state, privs, vset, txs, ec)
            assert state.last_block_height == h
            hashes.append(state.app_hash)
        # app state reflects txs
        q = app.query(abci.RequestQuery(data=b"b"))
        assert q.value == b"2"
        # app hash changed when txs landed, and also at empty block (height in hash)
        assert hashes[1] != hashes[0] and hashes[2] != hashes[1]
        # state store has the chain of validators
        for h in (1, 2, 3, 4):
            executor.state_store.load_validators(h)

    def test_reloaded_state_matches(self):
        executor, state, privs, vset, app = make_chain_env()
        state, ec = advance_one_height(executor, state, privs, vset, [b"x=9"], ExtendedCommit())
        loaded = executor.state_store.load()
        assert loaded.last_block_height == state.last_block_height
        assert loaded.app_hash == state.app_hash
        assert loaded.last_results_hash == state.last_results_hash
        assert loaded.validators.hash() == state.validators.hash()

    def test_invalid_block_rejected(self):
        executor, state, privs, vset, app = make_chain_env()
        ec = ExtendedCommit()
        state, ec = advance_one_height(executor, state, privs, vset, [], ec)
        # Build a block with the wrong app hash.
        proposer = state.validators.get_proposer().address
        block = executor.create_proposal_block(2, state, ec, proposer)
        block.header.app_hash = b"\x01" * 32
        block._hash = None
        parts = PartSet.from_data(block.to_proto_bytes())
        with pytest.raises(InvalidBlockError, match="AppHash"):
            executor.apply_block(state, BlockID(block.hash(), parts.header()), block)

    def test_validator_update_tx_rotates_set(self):
        executor, state, privs, vset, app = make_chain_env()
        import base64

        from tendermint_tpu.crypto.keys import Ed25519PrivKey

        new_priv = Ed25519PrivKey.from_seed(b"\x77" * 32)
        pk_b64 = base64.b64encode(new_priv.pub_key().bytes()).decode()
        tx = f"val:{pk_b64}!25".encode()
        ec = ExtendedCommit()
        state, ec = advance_one_height(executor, state, privs, vset, [tx], ec)
        # valset change lands in NextValidators after the delay
        assert state.last_height_validators_changed == 3
        assert len(state.next_validators) == 5
        assert len(state.validators) == 4
