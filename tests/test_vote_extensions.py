"""ABCI++ vote-extension lifecycle tests.

End-to-end over a real in-process node: with
``abci.vote_extensions_enable_height`` set, every precommit for a block
carries the application's extension (ExtendVote), peers verify them
(VerifyVoteExtension), extended commits persist in the block store, and
the NEXT proposer receives the extensions back in PrepareProposal's
local_last_commit — the full loop an application like a price oracle
depends on (abci/types/application.go, state.go vote-extension paths).
"""

import threading

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.node.node import Node, NodeConfig
from tendermint_tpu.p2p.transport import MemoryNetwork
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.types.params import ConsensusParams, TimeoutParams

from tests.test_node import BASE_NS, CHAIN, wait_for


class ExtensionApp(KVStoreApplication):
    """kvstore + deterministic vote extensions + received-extension log."""

    def __init__(self):
        super().__init__()
        self.lock = threading.Lock()
        self.extended_heights = []
        self.verified = []
        self.received_in_prepare = []

    def extend_vote(self, req: abci.RequestExtendVote) -> abci.ResponseExtendVote:
        with self.lock:
            self.extended_heights.append(req.height)
        return abci.ResponseExtendVote(
            vote_extension=b"ext-h%d" % req.height
        )

    def verify_vote_extension(self, req):
        with self.lock:
            self.verified.append((req.height, bytes(req.vote_extension)))
        ok = req.vote_extension == b"ext-h%d" % req.height
        return abci.ResponseVerifyVoteExtension(
            status=abci.VERIFY_VOTE_EXTENSION_ACCEPT
            if ok
            else abci.VERIFY_VOTE_EXTENSION_REJECT
        )

    def prepare_proposal(self, req):
        if req.local_last_commit is not None:
            exts = [
                bytes(v.vote_extension)
                for v in (req.local_last_commit.votes or [])
                if v.vote_extension
            ]
            if exts:
                with self.lock:
                    self.received_in_prepare.append(
                        (req.height, sorted(exts))
                    )
        return super().prepare_proposal(req)


def _genesis(pvs, enable_height=1):
    params = ConsensusParams()
    params.timeout = TimeoutParams(
        propose=0.6, propose_delta=0.2, vote=0.3, vote_delta=0.1, commit=0.1
    )
    params.abci.vote_extensions_enable_height = enable_height
    return GenesisDoc(
        chain_id=CHAIN,
        genesis_time=Timestamp.from_unix_ns(BASE_NS),
        consensus_params=params,
        validators=[
            GenesisValidator(pub_key=pv.get_pub_key(), power=10) for pv in pvs
        ],
    )


class TestVoteExtensions:
    def test_extension_lifecycle_across_network(self, tmp_path):
        net = MemoryNetwork()
        pvs = [
            FilePV.generate(
                str(tmp_path / f"pk{i}.json"), str(tmp_path / f"ps{i}.json")
            )
            for i in range(3)
        ]
        genesis = _genesis(pvs)
        nodes, apps = [], []
        for i in range(3):
            app = ExtensionApp()
            node = Node(
                NodeConfig(
                    chain_id=CHAIN,
                    listen_addr=f"extnode{i}",
                    wal_enabled=False,
                    blocksync=False,
                    moniker=f"extnode{i}",
                ),
                genesis,
                LocalClient(app),
                priv_validator=pvs[i],
                memory_network=net,
            )
            nodes.append(node)
            apps.append(app)
        for i, node in enumerate(nodes):
            if i > 0:
                node.config.persistent_peers = [
                    f"{nodes[0].node_key.node_id}@extnode0"
                ]
        for node in nodes:
            node.start()
        try:
            assert wait_for(
                lambda: all(n.height >= 3 for n in nodes), timeout=90
            ), f"heights: {[n.height for n in nodes]}"

            # every validator produced extensions
            for app in apps:
                assert app.extended_heights, "ExtendVote never called"
            # peers verified each other's extensions and saw the right bytes
            assert any(app.verified for app in apps)
            for app in apps:
                for height, ext in app.verified:
                    assert ext == b"ext-h%d" % height
            # extended commits persisted: reload one and check extensions
            node = nodes[0]
            h = min(n.height for n in nodes) - 1
            ec = node.block_store.load_block_extended_commit(h)
            assert ec is not None, f"no extended commit stored at {h}"
            exts = [
                bytes(s.extension)
                for s in ec.extended_signatures
                if s.extension
            ]
            assert exts and all(
                e == b"ext-h%d" % h for e in exts
            ), exts
            # a later proposer received the previous height's extensions
            assert wait_for(
                lambda: any(app.received_in_prepare for app in apps),
                timeout=30,
            ), "extensions never flowed back into PrepareProposal"
            got_h, got_exts = next(
                app.received_in_prepare[0]
                for app in apps
                if app.received_in_prepare
            )
            assert all(e == b"ext-h%d" % (got_h - 1) for e in got_exts)
        finally:
            for node in nodes:
                node.stop()

    def test_tampered_extension_rejected_at_ingestion(self, tmp_path):
        """A precommit whose extension was tampered after signing must be
        refused at ingestion (state.go:2387-2416): the extension
        signature no longer covers the bytes."""
        from tendermint_tpu.consensus.state import ConsensusState
        from tendermint_tpu.consensus.wal import NilWAL
        from tendermint_tpu.encoding.canonical import (
            SIGNED_MSG_TYPE_PRECOMMIT,
            Timestamp,
        )
        from tendermint_tpu.state import StateStore, state_from_genesis
        from tendermint_tpu.state.execution import BlockExecutor
        from tendermint_tpu.storage import MemDB
        from tendermint_tpu.storage.blockstore import BlockStore
        from tendermint_tpu.types.block import BlockID, PartSetHeader, Vote

        privs = [
            FilePV.generate(
                str(tmp_path / f"k{i}.json"), str(tmp_path / f"s{i}.json")
            )
            for i in range(2)
        ]
        genesis = _genesis(privs, enable_height=1)  # enabled BEFORE build
        sm_state = state_from_genesis(genesis)
        app = ExtensionApp()
        client = LocalClient(app)
        client.start()
        client.init_chain(
            abci.RequestInitChain(chain_id=CHAIN, initial_height=1)
        )
        state_store = StateStore(MemDB())
        state_store.save(sm_state)
        block_store = BlockStore(MemDB())
        cs = ConsensusState(
            sm_state,
            BlockExecutor(state_store, client, block_store),
            block_store,
            priv_validator=privs[0],
            wal=NilWAL(),
        )
        try:
            other = privs[1]
            addr = other.get_pub_key().address()
            val_idx, _ = cs.state.validators.get_by_address(addr)
            good = Vote(
                type=SIGNED_MSG_TYPE_PRECOMMIT,
                height=1,
                round=0,
                block_id=BlockID(b"\x07" * 32, PartSetHeader(1, b"\x08" * 32)),
                timestamp=Timestamp.from_unix_ns(1_700_000_000_000_000_000),
                validator_address=addr,
                validator_index=val_idx,
                extension=b"ext-h1",  # what ExtensionApp accepts at h1
            )
            other.sign_vote(cs.state.chain_id, good)
            # control: the untampered vote ingests fine
            import copy

            ok_vote = copy.deepcopy(good)
            assert cs._add_vote(ok_vote, "peer1")
            # tamper the extension AFTER signing -> must be refused
            bad = copy.deepcopy(good)
            bad.extension = b"tampered"
            with pytest.raises(Exception):
                cs._add_vote(bad, "peer2")
            # strip the extension entirely -> also refused
            stripped = copy.deepcopy(good)
            stripped.extension = b""
            stripped.extension_signature = b""
            with pytest.raises(Exception):
                cs._add_vote(stripped, "peer3")
        finally:
            cs.stop()


class TestExtensionRestart:
    def test_extended_commits_survive_restart_and_replay(self, tmp_path):
        """Weak spot named by review: a chain whose commits carry vote
        extensions must restart cleanly — WAL replay + handshake walk
        extended commits, and the node keeps extending after resuming
        (replay_test.go vote-extension coverage analog)."""
        home = str(tmp_path / "exthome")
        import os

        os.makedirs(home, exist_ok=True)
        pv = FilePV.generate(
            str(tmp_path / "epk.json"), str(tmp_path / "eps.json")
        )
        genesis = _genesis([pv])

        def build():
            app = ExtensionApp()
            node = Node(
                NodeConfig(
                    chain_id=CHAIN,
                    listen_addr="127.0.0.1:0",
                    wal_enabled=True,
                    blocksync=False,
                    moniker="ext-restart",
                    home=home,
                ),
                genesis,
                LocalClient(app),
                priv_validator=pv,
            )
            return node, app

        node, app = build()
        node.start()
        try:
            assert wait_for(lambda: node.height >= 3, timeout=60)
        finally:
            node.stop()
        h_before = node.height
        ec = node.block_store.load_block_extended_commit(h_before)
        assert ec is not None and any(
            v.extension for v in ec.extended_signatures
        ), "pre-restart extended commit missing extensions"

        node2, app2 = build()
        node2.start()
        try:
            assert wait_for(
                lambda: node2.height >= h_before + 2, timeout=60
            ), f"stuck at {node2.height} after restart (was {h_before})"
            # the resumed node keeps extending votes
            assert app2.extended_heights, "no ExtendVote after restart"
            ec2 = node2.block_store.load_block_extended_commit(node2.height)
            assert ec2 is not None and any(
                v.extension for v in ec2.extended_signatures
            )
        finally:
            node2.stop()
