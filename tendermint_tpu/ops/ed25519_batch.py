"""Batched Ed25519 ZIP-215 verification on TPU (f32 limb engine).

The device kernel verifies, for each lane i, the cofactored equation

    [8]([s_i]B - R_i - [k_i]A_i) == identity

with a shared-doubling (Straus) double-scalar multiplication: 64 4-bit
windows, per-window additions from a constant Niels basepoint table
(7-mul mixed adds) and a per-lane table of [0..15](-A_i). All lanes
execute the same 64-step loop, so the computation is pure SIMD over the
batch — the TPU analog of the reference's CPU multi-scalar batch verify
(crypto/ed25519/ed25519.go:198-233, types/validation.go:154).

Layout is transfer-minimal: the host uploads only the raw 32-byte
strings (A, R, S, and the SHA-512 challenge k reduced mod L) as uint8;
limb conversion, sign-bit stripping, and 4-bit windowing all happen on
device, where radix 2^8 f32 limbs make a 32-byte string its own limb
vector (see :mod:`field32`). Host work is the SHA-512 challenge hash
(batched in the C extension when available), the s < L canonicity
check (vectorized byte compare), and padding.

Large batches are split into fixed-size chunks whose kernel calls are
enqueued back-to-back: JAX's async dispatch overlaps each chunk's H2D
transfer with the previous chunk's compute.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto.hashing import L, sha512_batch_mod_l
from tendermint_tpu.ops import curve32 as curve, field32 as field

_L_BYTES_BE = np.frombuffer(L.to_bytes(32, "big"), dtype=np.uint8)

NWINDOWS = 64  # 256 bits / 4

# Chunk size for pipelined dispatch; also the largest compiled kernel.
CHUNK = 4096
_BUCKETS = [64, 256, 1024, CHUNK]


# --- constant basepoint table (host precompute, Niels form) -----------------


def _build_b_niels_table(width: int = 16) -> np.ndarray:
    """(width, 3, 32) f32: [0..width-1]B as (Y+X, Y-X, 2dT), Z=1."""
    from tendermint_tpu.crypto import ed25519_ref as ref

    out = np.zeros((width, 3, field.NLIMBS), dtype=np.float32)
    p_mod = field.P

    def affine(pt):
        x_, y_, z_, _ = pt
        zinv = pow(z_, p_mod - 2, p_mod)
        return (x_ * zinv % p_mod, y_ * zinv % p_mod)

    for i in range(width):
        if i == 0:
            x, y = 0, 1
        else:
            acc = ref.B_POINT
            for _ in range(i - 1):
                acc = ref.pt_add(acc, ref.B_POINT)
            x, y = affine(acc)
        out[i, 0] = field.int_to_limbs((y + x) % p_mod)
        out[i, 1] = field.int_to_limbs((y - x) % p_mod)
        out[i, 2] = field.int_to_limbs(2 * field.D * x * y % p_mod)
    return out


B_NIELS = _build_b_niels_table()


# --- device kernel ----------------------------------------------------------


def _bytes_to_fe(raw: jnp.ndarray) -> jnp.ndarray:
    """(N, 32) uint8 -> (32, N) f32 limbs (radix 2^8 == raw bytes)."""
    return raw.astype(jnp.float32).T


def _strip_sign(y: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(32, N) limbs with bit 255 set-or-not -> (limbs, sign (N,))."""
    sign = jnp.floor(y[31] * (1.0 / 128.0))
    y = y.at[31].add(-128.0 * sign)
    return y, sign


def _to_windows(raw: jnp.ndarray) -> jnp.ndarray:
    """(N, 32) uint8 scalars (LE) -> (64, N) f32 4-bit digits, MSB first."""
    b = raw.astype(jnp.float32).T  # (32, N)
    hi = jnp.floor(b * (1.0 / 16.0))
    lo = b - 16.0 * hi
    # MSB-first interleave: hi[31], lo[31], hi[30], ...
    return jnp.stack([hi[::-1], lo[::-1]], axis=1).reshape(2 * field.NLIMBS, -1)


def _select_b_niels(digit: jnp.ndarray, table: jnp.ndarray) -> curve.NielsPoint:
    """digit: (N,) f32 in [0,16); table: (16, 3, 32) const -> Niels point."""
    onehot = (
        jnp.arange(16, dtype=jnp.float32)[:, None] == digit[None, :]
    ).astype(jnp.float32)  # (16, N)
    sel = jnp.einsum("tn,tcl->cln", onehot, table)
    return (sel[0], sel[1], sel[2])


def _select_lane_cached(digit: jnp.ndarray, table: jnp.ndarray) -> curve.CachedPoint:
    """digit: (N,); table: (16, 4, 32, N) cached-form per-lane table."""
    onehot = (
        jnp.arange(16, dtype=jnp.float32)[:, None] == digit[None, :]
    ).astype(jnp.float32)
    sel = (onehot[:, None, None, :] * table).sum(axis=0)
    return (sel[0], sel[1], sel[2], sel[3])


def _build_lane_table(p: curve.Point) -> jnp.ndarray:
    """(16, 4, 32, N) cached-form table of [0..15]p.

    Chained complete additions build the extended multiples (lax.scan
    keeps the traced graph to one pt_add); the conversion to cached form
    (Y+X, Y-X, Z, 2dT) batches the 2d pre-scale of all 16 entries into a
    single wide multiply so the window loop's adds need none.
    """
    n = p[0].shape[1]
    cached_p = curve.pt_to_cached(p)
    p_stacked = jnp.stack(p)

    def step(acc, _):
        nxt = jnp.stack(
            curve.pt_add_cached((acc[0], acc[1], acc[2], acc[3]), cached_p)
        )
        return nxt, nxt

    _, rows = jax.lax.scan(step, p_stacked, None, length=14)
    ext = jnp.concatenate(
        [jnp.stack(curve.pt_identity(n))[None], p_stacked[None], rows], axis=0
    )  # (16, 4, 32, N) extended
    x, y, z, t = ext[:, 0], ext[:, 1], ext[:, 2], ext[:, 3]
    # one wide 2d*T multiply across all 16 entries (lanes folded in)
    t_flat = t.transpose(1, 0, 2).reshape(field.NLIMBS, 16 * n)
    td2 = field.fe_mul_const(t_flat, field.D2_FE).reshape(field.NLIMBS, 16, n)
    td2 = td2.transpose(1, 0, 2)
    yplusx = field.fe_add(
        y.transpose(1, 0, 2).reshape(field.NLIMBS, 16 * n),
        x.transpose(1, 0, 2).reshape(field.NLIMBS, 16 * n),
    ).reshape(field.NLIMBS, 16, n).transpose(1, 0, 2)
    yminusx = field.fe_sub(
        y.transpose(1, 0, 2).reshape(field.NLIMBS, 16 * n),
        x.transpose(1, 0, 2).reshape(field.NLIMBS, 16 * n),
    ).reshape(field.NLIMBS, 16, n).transpose(1, 0, 2)
    return jnp.stack([yplusx, yminusx, z, td2], axis=1)


def _dbl_step(_, acc_stacked):
    return jnp.stack(
        curve.pt_double(
            (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])
        )
    )


def straus_sb_minus_ka(
    a_pt: curve.Point, s_win: jnp.ndarray, k_win: jnp.ndarray
) -> curve.Point:
    """Shared-doubling double-scalar core: [s]B - [k]A per lane.

    The same 64-step window loop serves both signature schemes on this
    curve — ed25519 (below) and the schnorrkel/ristretto verifier
    (ops/sr25519_batch.py): their verification equations are both
    instances of [s]B - [k]A - R == identity-class.
    """
    nn = a_pt[0].shape[1]
    neg_a = curve.pt_neg(a_pt)
    a_table = _build_lane_table(neg_a)
    b_table = jnp.asarray(B_NIELS)

    init = jnp.stack(curve.pt_identity(nn))

    def body(i, acc_stacked):
        acc_stacked = jax.lax.fori_loop(0, 4, _dbl_step, acc_stacked)
        acc = (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])
        sd = jax.lax.dynamic_index_in_dim(s_win, i, keepdims=False)
        kd = jax.lax.dynamic_index_in_dim(k_win, i, keepdims=False)
        acc = curve.pt_madd(acc, _select_b_niels(sd, b_table))
        acc = curve.pt_add_cached(acc, _select_lane_cached(kd, a_table))
        return jnp.stack(acc)

    acc_stacked = jax.lax.fori_loop(0, NWINDOWS, body, init)
    return (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])


def verify_kernel(
    pk_bytes: jnp.ndarray,
    r_bytes: jnp.ndarray,
    s_bytes: jnp.ndarray,
    k_bytes: jnp.ndarray,
) -> jnp.ndarray:
    """(N,32)x4 uint8 -> (N,) bool."""
    a_y, a_sign = _strip_sign(_bytes_to_fe(pk_bytes))
    r_y, r_sign = _strip_sign(_bytes_to_fe(r_bytes))
    s_win = _to_windows(s_bytes)
    k_win = _to_windows(k_bytes)

    # Decompress A and R as one 2N batch: halves the decompression HLO
    # and doubles its SIMD width.
    nn = a_y.shape[1]
    both_pt, both_ok = curve.pt_decompress(
        jnp.concatenate([a_y, r_y], axis=1),
        jnp.concatenate([a_sign, r_sign], axis=0),
    )
    a_pt = tuple(c[:, :nn] for c in both_pt)
    r_pt = tuple(c[:, nn:] for c in both_pt)
    a_ok, r_ok = both_ok[:nn], both_ok[nn:]

    acc = straus_sb_minus_ka(a_pt, s_win, k_win)
    # [s]B - [k]A computed; subtract R, multiply by cofactor 8, test identity.
    acc = curve.pt_add(acc, curve.pt_neg(r_pt))
    acc_stacked = jax.lax.fori_loop(0, 3, _dbl_step, jnp.stack(acc))
    acc = (acc_stacked[0], acc_stacked[1], acc_stacked[2], acc_stacked[3])
    return curve.pt_is_identity(acc) & a_ok & r_ok


def _enable_persistent_cache() -> None:
    """First compilation of the verifier is expensive; persist it across
    processes (driver, tests, bench) in a repo-local cache dir."""
    import os

    cache_dir = os.environ.get(
        "TENDERMINT_TPU_JAX_CACHE",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))), ".jax_cache"
        ),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover - cache is best-effort
        pass


_enable_persistent_cache()


@lru_cache(maxsize=16)
def _compiled_kernel(n: int, backend: Optional[str], mul_impl: str = "vpu"):
    """One compiled verifier per (padded size, backend, field-mul impl).

    The field-mul impl ("vpu" f32 shifts vs "mxu" int8 dot_general —
    see ops/field_mxu.py) is a trace-time switch on field32, so it is
    pinned here around the trace — under field32's trace lock, so
    concurrent first compilations can't interleave their set/restore —
    and must be part of the cache key.
    """

    def run(pk, r, s, k):
        with field.pinned_mul_impl(mul_impl):
            return verify_kernel(pk, r, s, k)

    return jax.jit(run, backend=backend)


# --- implementation dispatch (XLA graph vs Pallas kernel) -------------------
#
# The Pallas kernel (ops/pallas_verify.py) keeps every field-op
# intermediate in VMEM; the XLA graph materializes them to HBM. On TPU
# backends the Pallas path is the default; CPU stays on the XLA graph
# (Pallas interpret mode is a test vehicle, far too slow for real
# batches). TENDERMINT_TPU_VERIFY_IMPL=pallas|xla|mxu|auto overrides;
# "mxu" is the XLA graph with field multiplies as int8 dot_general
# contractions (ops/field_mxu.py) instead of f32 VPU shifts.

_IMPL_ENV = "TENDERMINT_TPU_VERIFY_IMPL"
_PALLAS_BROKEN = False  # sticky per-process fallback after a failure
# Device-vs-host fallback state lives in ops/device_policy.py, shared
# with the sr25519 engine so a broken backend is broken once.


def _platform(backend: Optional[str]) -> str:
    try:
        if backend:
            return jax.local_devices(backend=backend)[0].platform
        return jax.default_backend()
    except Exception:
        return "unknown"


def active_impl(backend: Optional[str] = None) -> str:
    """Which verifier implementation verify_batch will dispatch to."""
    import os

    mode = os.environ.get(_IMPL_ENV, "auto").lower()
    if mode == "mxu":
        return "mxu"
    if mode == "xla" or _PALLAS_BROKEN:
        return "xla"
    if mode == "pallas":
        return "pallas"
    return "pallas" if _platform(backend) in ("tpu", "axon") else "xla"


def _run_chunk(inputs: dict, lo: int, hi: int, backend: Optional[str]):
    """Dispatch one padded chunk, preferring Pallas on TPU backends."""
    global _PALLAS_BROKEN
    from tendermint_tpu.ops import fault_injection

    fault_injection.fire("ed25519.chunk")
    args = (
        jnp.asarray(inputs["pk"][lo:hi]),
        jnp.asarray(inputs["r"][lo:hi]),
        jnp.asarray(inputs["s"][lo:hi]),
        jnp.asarray(inputs["k"][lo:hi]),
    )
    impl = active_impl(backend)
    if impl == "pallas":
        try:
            from tendermint_tpu.ops import pallas_verify

            return pallas_verify.compiled_verify(hi - lo)(*args)
        except Exception as exc:  # compile/runtime failure -> XLA graph
            _PALLAS_BROKEN = True
            import warnings

            warnings.warn(
                f"pallas verifier failed ({exc!r}); falling back to XLA graph"
            )
    # TENDERMINT_TPU_VERIFY_IMPL=mxu forces the int8 contraction; the
    # field-level default (field32.set_mul_impl / TENDERMINT_TPU_FIELD_MUL)
    # is honored otherwise.
    mul_impl = "mxu" if impl == "mxu" else field.get_mul_impl()
    return _compiled_kernel(hi - lo, backend, mul_impl)(*args)


# --- host-side preparation --------------------------------------------------


def _bucket(n: int) -> int:
    """Padded size for n lanes: next bucket, or the next CHUNK multiple
    above CHUNK (large batches are dispatched CHUNK at a time)."""
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + CHUNK - 1) // CHUNK) * CHUNK


# A known-good padding triple so padded lanes verify true and never mask
# real failures (they are sliced off anyway).
def _make_pad_entry() -> Tuple[bytes, bytes, bytes]:
    from tendermint_tpu.crypto import ed25519_ref as ref

    priv, pub = ref.keypair_from_seed(b"\x42" * 32)
    msg = b"tendermint-tpu-pad"
    return pub, msg, ref.sign(priv, msg)


_PAD_PK, _PAD_MSG, _PAD_SIG = _make_pad_entry()
_PAD_K: Optional[bytes] = None


def _pad_k() -> bytes:
    global _PAD_K
    if _PAD_K is None:
        _PAD_K = sha512_batch_mod_l(
            [_PAD_SIG[:32] + _PAD_PK + _PAD_MSG]
        )[0]
    return _PAD_K


def canonical_lt(arr_le: np.ndarray, bound_be: np.ndarray) -> np.ndarray:
    """(N, 32) little-endian values -> (N,) bool value < bound, no
    Python loop (shared by the ed25519 s < L and the ristretto
    encoding < p checks; equality is non-canonical -> False)."""
    be = arr_le[:, ::-1].astype(np.int16)
    diff = be - bound_be.astype(np.int16)[None, :]
    nz = diff != 0
    first = np.argmax(nz, axis=1)
    rows = np.arange(arr_le.shape[0])
    val = diff[rows, first]
    return np.where(nz.any(axis=1), val < 0, False)


def _s_canonical(s_arr: np.ndarray) -> np.ndarray:
    """(N, 32) little-endian s -> (N,) bool s < L."""
    return canonical_lt(s_arr, _L_BYTES_BE)


def prepare_batch(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    pad_to: Optional[int] = None,
) -> Tuple[dict, np.ndarray]:
    """Host prep: batch-hash challenges, stack raw bytes, pad to bucket.

    Returns (device inputs dict of (M,32) uint8 arrays, host_ok (N,)
    bool of structural checks: lengths and s < L canonicity)."""
    from tendermint_tpu.crypto.hashing import reduce_mod_l, sha512_batch_prefixed

    n = len(pubkeys)
    len_ok = all(len(pk) == 32 and len(sg) == 64 for pk, sg in zip(pubkeys, sigs))
    if len_ok:
        # Fast path (every batch from commit verification): two joins +
        # one prefixed C hash call — no per-signature Python work.
        pk_arr = np.frombuffer(b"".join(pubkeys), dtype=np.uint8).reshape(n, 32)
        sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
        r_arr, s_arr = sig_arr[:, :32], sig_arr[:, 32:]
        host_ok = _s_canonical(s_arr)
        prefix = np.concatenate([r_arr, pk_arr], axis=1)  # (n, 64) = R || A
        k_arr = reduce_mod_l(sha512_batch_prefixed(prefix, list(msgs)))
    else:
        host_ok = np.ones(n, dtype=bool)
        pk_arr = np.zeros((n, 32), dtype=np.uint8)
        r_arr = np.zeros((n, 32), dtype=np.uint8)
        s_arr = np.zeros((n, 32), dtype=np.uint8)
        hash_inputs: List[bytes] = []
        hash_rows: List[int] = []
        for i, (pk, msg, sig) in enumerate(zip(pubkeys, msgs, sigs)):
            if len(pk) != 32 or len(sig) != 64:
                host_ok[i] = False
                continue
            pk_arr[i] = np.frombuffer(pk, dtype=np.uint8)
            r_arr[i] = np.frombuffer(sig[:32], dtype=np.uint8)
            s_arr[i] = np.frombuffer(sig[32:], dtype=np.uint8)
            hash_inputs.append(sig[:32] + pk + msg)
            hash_rows.append(i)
        host_ok &= _s_canonical(s_arr)
        k_arr = np.zeros((n, 32), dtype=np.uint8)
        if hash_inputs:
            k_list = sha512_batch_mod_l(hash_inputs)
            rows = np.asarray(hash_rows)
            k_arr[rows] = np.frombuffer(b"".join(k_list), dtype=np.uint8).reshape(
                -1, 32
            )

    m = pad_to if pad_to is not None else _bucket(n)
    if m > n:
        pad = np.zeros((m - n, 32), dtype=np.uint8)
        pk_arr = np.concatenate([pk_arr, pad + np.frombuffer(_PAD_PK, dtype=np.uint8)])
        r_arr = np.concatenate([r_arr, pad + np.frombuffer(_PAD_SIG[:32], dtype=np.uint8)])
        s_arr = np.concatenate([s_arr, pad + np.frombuffer(_PAD_SIG[32:], dtype=np.uint8)])
        k_arr = np.concatenate([k_arr, pad + np.frombuffer(_pad_k(), dtype=np.uint8)])

    inputs = dict(pk=pk_arr, r=r_arr, s=s_arr, k=k_arr)
    return inputs, host_ok


def _host_verify_lanes(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    lo: int,
    hi: int,
) -> np.ndarray:
    """CPU oracle over lanes [lo, hi) of the original (unpadded) batch."""
    from tendermint_tpu.crypto.ed25519_ref import verify_zip215

    return np.array(
        [
            verify_zip215(pubkeys[i], msgs[i], sigs[i])
            for i in range(lo, hi)
        ],
        dtype=bool,
    )


def verify_batch(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    backend: Optional[str] = None,
) -> List[bool]:
    """Batch ZIP-215 verification; returns per-entry validity.

    The entry point behind crypto.Ed25519BatchVerifier — reference
    contract crypto/crypto.go:58-76 / crypto/ed25519/ed25519.go:198-233.

    Batches larger than CHUNK are split and their kernel calls enqueued
    back-to-back so H2D transfer of chunk j+1 overlaps compute of
    chunk j (JAX async dispatch).

    Device failures degrade per CHUNK, not per process: a chunk whose
    dispatch or materialization fails is re-verified on the CPU oracle
    while the rest of the batch stays on the device (if the health
    state machine — ops/device_policy.py — still admits it). A batch
    that completes on the device re-promotes a degraded path; the
    state machine alone decides when the device is cooling down or
    disabled, and it recovers via half-open probe batches.
    """
    from tendermint_tpu.ops import fault_injection
    from tendermint_tpu.ops.device_policy import shared as health

    n = len(pubkeys)
    if n == 0:
        return []
    attempt = health.begin_attempt("ed25519")
    if attempt is None:
        # DISABLED, or cooling down (another caller may hold the probe
        # slot). Instant answer — the circuit breaker never blocks.
        health.count_fallback("ed25519", n)
        return list(_host_verify_lanes(pubkeys, msgs, sigs, 0, n))

    try:
        inputs, host_ok = prepare_batch(pubkeys, msgs, sigs, pad_to=_bucket(n))
    except Exception as exc:
        # Host prep failed before any device work. Never take the node
        # down over infrastructure — degrade to the host oracle.
        health.record_failure(exc, attempt)
        import warnings

        warnings.warn(
            f"batch prepare failed ({exc!r}); host fallback "
            f"(device state={health.state})"
        )
        health.count_fallback("ed25519", n)
        return list(_host_verify_lanes(pubkeys, msgs, sigs, 0, n))

    m = inputs["pk"].shape[0]
    # Dispatch phase: enqueue chunk kernels back-to-back; a chunk whose
    # dispatch raises falls back to the host WITHOUT abandoning the
    # remaining chunks (the health machine re-admits or refuses them).
    chunks = []  # (lo, hi, device result or None)
    for lo in range(0, m, CHUNK):
        hi = min(lo + CHUNK, m)
        if attempt is None:
            attempt = health.begin_attempt("ed25519")
        if attempt is None:
            chunks.append((lo, hi, None))
            continue
        try:
            chunks.append((lo, hi, _run_chunk(inputs, lo, hi, backend)))
        except Exception as exc:
            health.record_failure(exc, attempt)
            attempt = None
            import warnings

            warnings.warn(
                f"device chunk [{lo}:{hi}] dispatch failed ({exc!r}); "
                f"CPU fallback for the chunk (device state={health.state})"
            )
            chunks.append((lo, hi, None))

    # Collect phase: JAX dispatch is async, so runtime errors can
    # surface at materialization; those too degrade per chunk.
    results = np.ones(m, dtype=bool)
    fallback_lanes = 0
    device_chunks_ok = 0
    for lo, hi, out in chunks:
        ok = None
        if out is not None:
            try:
                fault_injection.fire("ed25519.collect")
                ok = np.asarray(out)
                device_chunks_ok += 1
            except Exception as exc:
                health.record_failure(exc, attempt)
                attempt = None
                import warnings

                warnings.warn(
                    f"device chunk [{lo}:{hi}] failed at collect ({exc!r}); "
                    f"CPU fallback for the chunk (device state={health.state})"
                )
        if ok is None:
            ok = np.ones(hi - lo, dtype=bool)
            top = min(hi, n)  # padded lanes need no host verify
            if lo < top:
                fallback_lanes += top - lo
                ok[: top - lo] = _host_verify_lanes(pubkeys, msgs, sigs, lo, top)
        results[lo:hi] = ok

    if fallback_lanes:
        health.count_fallback("ed25519", fallback_lanes)
    if attempt is not None and device_chunks_ok:
        # No failure consumed the attempt and device work round-tripped:
        # re-promote (clears DEGRADED, completes a half-open probe).
        health.record_success(attempt)
    return [bool(v) for v in np.logical_and(results[:n], host_ok)]
