"""Light-client RPC proxy (light/proxy + light/rpc in the reference).

Serves a JSON-RPC surface backed by a LightClient: header/commit/
validators responses are returned only after bisection verification
against the primary (with witness cross-checking via the client's
detector); `abci_query` is forwarded to the primary and its result is
checked against the VERIFIED app hash when the app supplies proof-free
value equality is impossible — here we verify the queried height's
header first and mark the response accordingly (the reference verifies
merkle proofs; this proxy verifies the enclosing header and forwards
the app's proof_ops for client-side checking).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from tendermint_tpu.light.client import LightClient
from tendermint_tpu.rpc import encoding as enc
from tendermint_tpu.rpc.client import HTTPClient
from tendermint_tpu.rpc.server import INVALID_PARAMS, RPCError, RPCServer


class LightProxy:
    """Route table + server lifecycle for a light-client RPC endpoint."""

    def __init__(
        self,
        client: LightClient,
        primary_url: str,
        laddr: str = "127.0.0.1:0",
    ):
        self.client = client
        self.primary = HTTPClient(primary_url)
        host, _, port = laddr.rpartition(":")
        self.server = RPCServer(
            self.routes(), host=host or "127.0.0.1", port=int(port or 0)
        )

    # --- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    @property
    def url(self) -> str:
        return self.server.url

    # --- routes --------------------------------------------------------------

    def routes(self) -> Dict[str, Callable]:
        return {
            "health": self.health,
            "status": self.status,
            "header": self.header,
            "commit": self.commit,
            "validators": self.validators,
            "abci_query": self.abci_query,
            "abci_info": self.abci_info,
        }

    def health(self) -> Dict[str, Any]:
        return {}

    def status(self) -> Dict[str, Any]:
        latest = self.client.update()
        trusted = self.client.latest_trusted()
        lb = latest or trusted
        if lb is None:
            raise RPCError(INVALID_PARAMS, "no trusted state yet")
        return {
            "light_client": {
                "chain_id": self.client.chain_id,
                "trusted_height": str(lb.header.height),
                "trusted_hash": enc.hex_bytes(lb.header.hash()),
                "trusting_period_seconds": str(
                    int(self.client.trusting_period)
                ),
                "num_witnesses": len(self.client.witnesses),
            }
        }

    def _verified(self, height) -> "object":
        try:
            h = int(height)
        except (TypeError, ValueError):
            raise RPCError(INVALID_PARAMS, "height required")
        try:
            return self.client.verify_light_block_at_height(h)
        except Exception as e:
            raise RPCError(INVALID_PARAMS, f"light verification failed: {e}")

    def header(self, height=None) -> Dict[str, Any]:
        lb = self._verified(height)
        return {"header": enc.header_json(lb.header)}

    def commit(self, height=None) -> Dict[str, Any]:
        lb = self._verified(height)
        return {
            "signed_header": {
                "header": enc.header_json(lb.header),
                "commit": enc.commit_json(lb.signed_header.commit),
            },
            "canonical": True,
        }

    def validators(self, height=None) -> Dict[str, Any]:
        lb = self._verified(height)
        vals = lb.validator_set.validators
        return {
            "block_height": str(lb.header.height),
            "validators": [enc.validator_json(v) for v in vals],
            "count": str(len(vals)),
            "total": str(len(vals)),
        }

    def abci_query(self, path="", data=None, height=0, prove=True) -> Dict[str, Any]:
        """Forward to the primary, but pin the query to a VERIFIED height
        (light/rpc/client.go ABCIQueryWithOptions: query at a height whose
        header the light client has verified, so the app hash the proof
        anchors to is trusted)."""
        h = int(height) if height else 0
        if h == 0:
            latest = self.client.update() or self.client.latest_trusted()
            if latest is None:
                raise RPCError(INVALID_PARAMS, "no trusted state yet")
            h = latest.header.height
        else:
            self._verified(h)
        out = self.primary.call(
            "abci_query",
            {"path": path, "data": data, "height": h, "prove": bool(prove)},
        )
        resp = out.get("response", {})
        resp["verified_height"] = str(h)
        return out

    def abci_info(self) -> Dict[str, Any]:
        return self.primary.call("abci_info")
