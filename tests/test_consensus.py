"""Consensus state machine tests.

The in-process analog of internal/consensus/state_test.go: a single
validator self-commits blocks ("onlyValidatorIsUs", node/node.go:286-294),
and a 4-validator in-process network (common_test.go style, with the
loopback broadcaster playing the role of the in-memory p2p transport)
reaches consensus across rounds.
"""

import threading
import time

import pytest

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.abci import types as abci
from tendermint_tpu.consensus.state import Broadcaster, ConsensusState
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.encoding.canonical import Timestamp
from tendermint_tpu.privval import FilePV
from tendermint_tpu.state import StateStore, state_from_genesis
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.storage import MemDB
from tendermint_tpu.storage.blockstore import BlockStore
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.params import ConsensusParams, TimeoutParams

CHAIN_ID = "cons-chain"
BASE_NS = 1_700_000_000_000_000_000


def fast_params() -> ConsensusParams:
    p = ConsensusParams()
    p.timeout = TimeoutParams(
        propose=0.5, propose_delta=0.1, vote=0.2, vote_delta=0.1, commit=0.05
    )
    return p


def build_validator(tmp_path, n_vals=1, index=0, privs=None):
    """One validator's full stack: app + stores + executor + consensus."""
    if privs is None:
        privs = [
            FilePV.generate(
                str(tmp_path / f"key{i}.json"), str(tmp_path / f"state{i}.json")
            )
            for i in range(n_vals)
        ]
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time=Timestamp.from_unix_ns(BASE_NS),
        consensus_params=fast_params(),
        validators=[
            GenesisValidator(pub_key=pv.get_pub_key(), power=10) for pv in privs
        ],
    )
    sm_state = state_from_genesis(gen)
    app = KVStoreApplication()
    client = LocalClient(app)
    client.start()
    init = client.init_chain(abci.RequestInitChain(chain_id=CHAIN_ID, initial_height=1))
    sm_state.app_hash = init.app_hash
    state_store = StateStore(MemDB())
    state_store.save(sm_state)
    block_store = BlockStore(MemDB())
    block_exec = BlockExecutor(state_store, client, block_store)
    cs = ConsensusState(
        sm_state,
        block_exec,
        block_store,
        priv_validator=privs[index],
        wal=WAL(str(tmp_path / f"wal{index}.log")),
    )
    return cs, privs, app


def wait_for_height(cs_list, height, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(cs.block_store.height() >= height for cs in cs_list):
            return True
        time.sleep(0.02)
    return False


class TestSingleValidator:
    def test_self_commits_blocks(self, tmp_path):
        cs, privs, app = build_validator(tmp_path)
        cs.start()
        try:
            assert wait_for_height([cs], 3), (
                f"only reached height {cs.block_store.height()}"
            )
        finally:
            cs.stop()
        # Chain is verifiable: every stored commit validates.
        from tendermint_tpu.types import verify_commit

        for h in range(1, 3):
            commit = cs.block_store.load_block_commit(h)
            meta = cs.block_store.load_block_meta(h)
            vals = cs.block_exec.state_store.load_validators(h)
            verify_commit(CHAIN_ID, vals, meta.block_id, h, commit)

    def test_wal_replay_restart(self, tmp_path):
        cs, privs, app = build_validator(tmp_path)
        cs.start()
        assert wait_for_height([cs], 2)
        cs.stop()
        height_before = cs.block_store.height()
        # Restart from the same stores + WAL: must resume, not double-sign.
        sm_state = cs.block_exec.state_store.load()
        cs2 = ConsensusState(
            sm_state,
            cs.block_exec,
            cs.block_store,
            priv_validator=privs[0],
            wal=WAL(str(tmp_path / "wal0.log")),
        )
        cs2.start()
        try:
            assert wait_for_height([cs2], height_before + 2)
        finally:
            cs2.stop()


class LoopbackNet(Broadcaster):
    """In-process 'network': every broadcast is delivered to all other
    validators' peer queues (the p2ptest memory-transport analog)."""

    def __init__(self):
        self.nodes = []

    def attach(self, cs):
        net = self

        class NodeB(Broadcaster):
            def broadcast_proposal(self, proposal):
                net.deliver(cs, "proposal", proposal)

            def broadcast_block_part(self, height, round_, part):
                net.deliver(cs, "part", (height, round_, part))

            def broadcast_vote(self, vote):
                net.deliver(cs, "vote", vote)

        cs.broadcaster = NodeB()
        self.nodes.append(cs)

    def deliver(self, sender, kind, payload):
        for node in self.nodes:
            if node is sender:
                continue
            if kind == "proposal":
                node.add_proposal_from_peer(payload, "peer")
            elif kind == "part":
                h, r, p = payload
                node.add_block_part_from_peer(h, r, p, "peer")
            else:
                node.add_vote_from_peer(payload, "peer")


class TestFourValidatorNetwork:
    def test_network_commits(self, tmp_path):
        privs = [
            FilePV.generate(
                str(tmp_path / f"key{i}.json"), str(tmp_path / f"state{i}.json")
            )
            for i in range(4)
        ]
        net = LoopbackNet()
        nodes = []
        for i in range(4):
            cs, _, _ = build_validator(tmp_path, n_vals=4, index=i, privs=privs)
            net.attach(cs)
            nodes.append(cs)
        for cs in nodes:
            cs.start()
        try:
            assert wait_for_height(nodes, 3, timeout=60), (
                f"heights: {[cs.block_store.height() for cs in nodes]}"
            )
            # All nodes converged on identical blocks.
            h1 = [cs.block_store.load_block_meta(1).block_id for cs in nodes]
            assert all(b == h1[0] for b in h1)
        finally:
            for cs in nodes:
                cs.stop()

    def test_network_survives_one_silent_node(self, tmp_path):
        privs = [
            FilePV.generate(
                str(tmp_path / f"key{i}.json"), str(tmp_path / f"state{i}.json")
            )
            for i in range(4)
        ]
        net = LoopbackNet()
        nodes = []
        for i in range(4):
            cs, _, _ = build_validator(tmp_path, n_vals=4, index=i, privs=privs)
            net.attach(cs)
            nodes.append(cs)
        # Node 3 never starts: 3/4 = 30/40 power > 2/3 still commits.
        for cs in nodes[:3]:
            cs.start()
        try:
            assert wait_for_height(nodes[:3], 2, timeout=90), (
                f"heights: {[cs.block_store.height() for cs in nodes[:3]]}"
            )
        finally:
            for cs in nodes[:3]:
                cs.stop()


class TestPeerRobustness:
    def test_malformed_peer_input_does_not_kill_loop(self, tmp_path):
        """A bad proposal signature or bogus block part from a peer must be
        dropped, not crash the receive routine (liveness)."""
        cs, privs, app = build_validator(tmp_path)
        cs.start()
        try:
            from tendermint_tpu.types import Proposal
            from tendermint_tpu.types.part_set import Part
            from tendermint_tpu.crypto import merkle
            from tests.helpers import make_block_id

            bad = Proposal(
                height=cs.rs.height, round=0, pol_round=-1,
                block_id=make_block_id(), timestamp=Timestamp.from_unix_ns(BASE_NS),
                signature=b"\x01" * 64,
            )
            cs.add_proposal_from_peer(bad, "evil")
            cs.add_block_part_from_peer(
                cs.rs.height, 0,
                Part(index=0, bytes=b"junk",
                     proof=merkle.Proof(total=1, index=0, leaf_hash=b"\x02" * 32)),
                "evil",
            )
            # The node still commits blocks afterwards.
            assert wait_for_height([cs], 2, timeout=30)
        finally:
            cs.stop()


# --- POL locking / unlocking (state_test.go locking sections) ---------------


class CaptureB(Broadcaster):
    """Records everything the subject validator broadcasts."""

    def __init__(self):
        self.proposals = []
        self.parts = []
        self.votes = []

    def broadcast_proposal(self, proposal):
        self.proposals.append(proposal)

    def broadcast_block_part(self, height, round_, part):
        self.parts.append((height, round_, part))

    def broadcast_vote(self, vote):
        self.votes.append(vote)


def _wait(fn, timeout=15.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    return None


def _vote_of(cap, type_, round_, height=1):
    for v in cap.votes:
        if v.type == type_ and v.round == round_ and v.height == height:
            return v
    return None


class LockHarness:
    """Reference common_test.go style driver: ONE real ConsensusState
    (chosen to be the height-1 round-0 proposer) plus three scripted
    validators whose votes are crafted and injected. Pins the POL
    lock/unlock/relock rules of state.go defaultDoPrevote:1512 and
    enterPrecommit:1682."""

    def __init__(self, tmp_path, subject_is_proposer=True):
        privs = [
            FilePV.generate(
                str(tmp_path / f"lk{i}.json"), str(tmp_path / f"ls{i}.json")
            )
            for i in range(4)
        ]
        probe, _, _ = build_validator(tmp_path, n_vals=4, index=0, privs=privs)
        proposer_addr = probe.rs.validators.get_proposer().address
        by_addr = {p.get_pub_key().address(): i for i, p in enumerate(privs)}
        prop_idx = by_addr[proposer_addr]
        if subject_is_proposer:
            idx = prop_idx
        else:
            idx = next(i for i in range(4) if i != prop_idx)
        if idx == 0:
            self.cs = probe
        else:
            self.cs, _, _ = build_validator(
                tmp_path, n_vals=4, index=idx, privs=privs
            )
        self.tmp_path = tmp_path
        self.privs = privs
        self.cap = CaptureB()
        self.cs.broadcaster = self.cap
        self.vset = self.cs.state.validators
        self.index_of = {
            v.address: i for i, v in enumerate(self.vset.validators)
        }
        self.priv_of_index = {
            self.index_of[p.get_pub_key().address()]: p for p in privs
        }
        self.subject_index = self.index_of[
            privs[idx].get_pub_key().address()
        ]

    def others(self):
        return [i for i in range(4) if i != self.subject_index]

    def make_vote(self, val_index, type_, round_, block_id, height=1):
        from tendermint_tpu.types.block import Vote

        pv = self.priv_of_index[val_index]
        v = Vote(
            type=type_,
            height=height,
            round=round_,
            block_id=block_id,
            timestamp=Timestamp.from_unix_ns(BASE_NS + 1000 + val_index),
            validator_address=pv.get_pub_key().address(),
            validator_index=val_index,
        )
        v.signature = pv.priv_key.sign(v.sign_bytes(CHAIN_ID))
        return v

    def inject_votes(self, type_, round_, block_id, n=None, height=1):
        idxs = self.others() if n is None else self.others()[:n]
        for i in idxs:
            self.cs.add_vote_from_peer(
                self.make_vote(i, type_, round_, block_id, height), f"peer{i}"
            )

    def proposal_block_id(self):
        """BlockID of the subject's own round-0 proposal."""
        prop = _wait(lambda: self.cap.proposals[0] if self.cap.proposals else None)
        assert prop is not None, "subject never proposed"
        return prop.block_id

    def alternative_block(self, proposer_index_in_vset):
        """A valid competing block built by the given validator's own
        proposal machinery (different proposer + timestamp -> different
        hash), plus its part set."""
        from tendermint_tpu.types.block import BLOCK_PART_SIZE_BYTES
        from tendermint_tpu.types.part_set import PartSet as PS

        priv = self.priv_of_index[proposer_index_in_vset]
        priv_pos = next(
            i for i, p in enumerate(self.privs) if p is priv
        )
        shadow, _, _ = build_validator(
            self.tmp_path, n_vals=4, index=priv_pos, privs=self.privs
        )
        block = shadow._create_proposal_block()
        assert block is not None
        parts = PS.from_data(block.to_proto_bytes(), BLOCK_PART_SIZE_BYTES)
        return block, parts

    def inject_proposal(self, proposer_index, block, parts, round_, pol_round=-1):
        from tendermint_tpu.types.block import BlockID as BID, Proposal

        priv = self.priv_of_index[proposer_index]
        prop = Proposal(
            height=1,
            round=round_,
            pol_round=pol_round,
            block_id=BID(block.hash(), parts.header()),
            timestamp=block.header.time,
        )
        prop.signature = priv.priv_key.sign(prop.sign_bytes(CHAIN_ID))
        self.cs.add_proposal_from_peer(prop, "peerP")
        for i in range(parts.total):
            self.cs.add_block_part_from_peer(1, round_, parts.get_part(i), "peerP")


class TestLocking:
    def test_nil_prevote_on_propose_timeout(self, tmp_path):
        """No proposal arrives: after the propose timeout the validator
        prevotes nil (state_test.go TestStateFullRoundNil analog)."""
        h = LockHarness(tmp_path, subject_is_proposer=False)
        h.cs.start()
        try:
            pv = _wait(lambda: _vote_of(h.cap, 1, 0))  # SIGNED_MSG_TYPE_PREVOTE
            assert pv is not None, "no prevote broadcast"
            assert pv.block_id.is_nil(), "must prevote nil without a proposal"
        finally:
            h.cs.stop()

    def test_lock_then_nil_prevote_on_new_block_without_pol(self, tmp_path):
        """Round 0: subject proposes A, sees a polka for A, precommits A
        and locks. Round 1: a valid competing block B arrives with NO
        POL — the locked validator must prevote nil, not B
        (state_test.go TestStateLock_NoPOL / POLRelock family)."""
        from tendermint_tpu.encoding.canonical import (
            SIGNED_MSG_TYPE_PRECOMMIT as PC,
            SIGNED_MSG_TYPE_PREVOTE as PV,
        )
        from tendermint_tpu.types.block import BlockID as BID

        h = LockHarness(tmp_path, subject_is_proposer=True)
        h.cs.start()
        try:
            a_id = h.proposal_block_id()
            # polka for A in round 0 -> subject precommits A and locks
            h.inject_votes(PV, 0, a_id, n=2)
            pc0 = _wait(lambda: _vote_of(h.cap, PC, 0))
            assert pc0 is not None and pc0.block_id.hash == a_id.hash
            assert h.cs.rs.locked_round == 0

            # nil precommits from everyone else -> round 1
            h.inject_votes(PC, 0, BID())
            assert _wait(lambda: h.cs.rs.round == 1, timeout=20), (
                f"stuck in round {h.cs.rs.round}"
            )

            # competing valid block B from the round-1 proposer, no POL
            r1_proposer = h.index_of[
                h.cs.rs.validators.get_proposer().address
            ]
            assert r1_proposer != h.subject_index, "rotation must move on"
            block_b, parts_b = h.alternative_block(r1_proposer)
            assert block_b.hash() != a_id.hash
            h.inject_proposal(r1_proposer, block_b, parts_b, round_=1)

            pv1 = _wait(lambda: _vote_of(h.cap, PV, 1), timeout=20)
            assert pv1 is not None, "no round-1 prevote"
            assert pv1.block_id.is_nil(), (
                "locked validator prevoted a different block without a POL"
            )
            # it DID consider B (not a timeout artifact)
            assert h.cs.rs.proposal_block is not None
            assert h.cs.rs.proposal_block.hash() == block_b.hash()
            assert h.cs.rs.locked_block.hash() == a_id.hash

            # now a round-1 polka for B arrives: the subject must RELOCK
            # to B and precommit it (enterPrecommit:1682 relock rule)
            b_id = BID(block_b.hash(), parts_b.header())
            h.inject_votes(PV, 1, b_id)
            pc1 = _wait(lambda: _vote_of(h.cap, PC, 1), timeout=20)
            assert pc1 is not None, "no round-1 precommit"
            assert pc1.block_id.hash == block_b.hash(), "must relock on new POL"
            assert h.cs.rs.locked_round == 1
            assert h.cs.rs.locked_block.hash() == block_b.hash()
        finally:
            h.cs.stop()

    def test_prevote_locked_block_when_reproposed_with_pol(self, tmp_path):
        """Round 1 re-proposes the LOCKED block A with pol_round=0: the
        validator prevotes A again (the pol_round acceptance path of
        defaultDoPrevote:1512)."""
        from tendermint_tpu.encoding.canonical import (
            SIGNED_MSG_TYPE_PRECOMMIT as PC,
            SIGNED_MSG_TYPE_PREVOTE as PV,
        )
        from tendermint_tpu.types.block import BlockID as BID, Proposal

        h = LockHarness(tmp_path, subject_is_proposer=True)
        h.cs.start()
        try:
            a_id = h.proposal_block_id()
            a_parts = _wait(
                lambda: h.cap.parts if h.cap.parts else None
            )
            h.inject_votes(PV, 0, a_id, n=2)
            assert _wait(lambda: h.cs.rs.locked_round == 0, timeout=20)
            locked_block = h.cs.rs.locked_block
            h.inject_votes(PC, 0, BID())
            assert _wait(lambda: h.cs.rs.round == 1, timeout=20)

            r1_proposer = h.index_of[
                h.cs.rs.validators.get_proposer().address
            ]
            priv = h.priv_of_index[r1_proposer]
            prop = Proposal(
                height=1,
                round=1,
                pol_round=0,
                block_id=a_id,
                timestamp=locked_block.header.time,
            )
            prop.signature = priv.priv_key.sign(prop.sign_bytes(CHAIN_ID))
            h.cs.add_proposal_from_peer(prop, "peerP")
            for _, _, part in a_parts:
                h.cs.add_block_part_from_peer(1, 1, part, "peerP")

            pv1 = _wait(lambda: _vote_of(h.cap, PV, 1), timeout=20)
            assert pv1 is not None, "no round-1 prevote"
            assert pv1.block_id.hash == a_id.hash, (
                "validator must prevote its locked block when re-proposed "
                "with a valid POL round"
            )
        finally:
            h.cs.stop()

    def test_invalid_injected_votes_do_not_corrupt_lock_state(self, tmp_path):
        """Garbage votes (bad signature / bogus index) around a genuine
        polka neither stall the round nor alter lock bookkeeping
        (invalid_test.go vote-injection analog at the state layer)."""
        from tendermint_tpu.encoding.canonical import (
            SIGNED_MSG_TYPE_PRECOMMIT as PC,
            SIGNED_MSG_TYPE_PREVOTE as PV,
        )
        from tendermint_tpu.types.block import Vote

        h = LockHarness(tmp_path, subject_is_proposer=True)
        h.cs.start()
        try:
            a_id = h.proposal_block_id()
            good = h.make_vote(h.others()[0], PV, 0, a_id)
            bad_sig = h.make_vote(h.others()[1], PV, 0, a_id)
            bad_sig.signature = b"\x01" * 64
            bad_idx = Vote(
                type=PV, height=1, round=0, block_id=a_id,
                timestamp=Timestamp.from_unix_ns(BASE_NS),
                validator_address=b"\x05" * 20, validator_index=55,
                signature=b"\x02" * 64,
            )
            h.cs.add_vote_from_peer(bad_sig, "evil")
            h.cs.add_vote_from_peer(bad_idx, "evil")
            h.cs.add_vote_from_peer(good, "peer")
            # only the good vote + subject's own count: no polka yet
            time.sleep(0.3)
            assert h.cs.rs.locked_round == -1
            # second genuine prevote completes the polka -> lock + precommit A
            h.cs.add_vote_from_peer(
                h.make_vote(h.others()[1], PV, 0, a_id), "peer"
            )
            pc0 = _wait(lambda: _vote_of(h.cap, PC, 0), timeout=20)
            assert pc0 is not None and pc0.block_id.hash == a_id.hash
            assert h.cs.rs.locked_round == 0
        finally:
            h.cs.stop()


class TestDoubleSignRiskGuard:
    def test_restart_with_recent_own_signature_refuses(self, tmp_path):
        """state.go checkDoubleSigningRisk:2663: a validator whose key
        signed a commit within the lookback window must refuse to join
        consensus (the migrate-a-validator protection)."""
        from tendermint_tpu.consensus.state import DoubleSigningRiskError

        cs, privs, app = build_validator(tmp_path)
        cs.start()
        assert wait_for_height([cs], 3)
        cs.stop()

        sm_state = cs.block_exec.state_store.load()
        cs2 = ConsensusState(
            sm_state,
            cs.block_exec,
            cs.block_store,
            priv_validator=privs[0],
            wal=WAL(str(tmp_path / "wal0.log")),
            double_sign_check_height=10,
        )
        with pytest.raises(DoubleSigningRiskError):
            cs2.start()
        cs2.stop()

    def test_restart_disabled_guard_proceeds(self, tmp_path):
        """Default double_sign_check_height=0 keeps today's restart
        behavior (WAL replay, no refusal)."""
        cs, privs, app = build_validator(tmp_path)
        cs.start()
        assert wait_for_height([cs], 2)
        cs.stop()
        sm_state = cs.block_exec.state_store.load()
        cs2 = ConsensusState(
            sm_state,
            cs.block_exec,
            cs.block_store,
            priv_validator=privs[0],
            wal=WAL(str(tmp_path / "wal0.log")),
        )
        cs2.start()
        try:
            assert wait_for_height([cs2], cs.block_store.height() + 1)
        finally:
            cs2.stop()

    def test_unsigned_lookback_window_proceeds(self, tmp_path):
        """A key with NO signatures in the window (fresh validator key
        joining an existing chain) starts normally even with the guard
        enabled."""
        cs, privs, app = build_validator(tmp_path)
        cs.start()
        assert wait_for_height([cs], 2)
        cs.stop()
        sm_state = cs.block_exec.state_store.load()
        other = FilePV.generate(
            str(tmp_path / "okey.json"), str(tmp_path / "ostate.json")
        )
        cs2 = ConsensusState(
            sm_state,
            cs.block_exec,
            cs.block_store,
            priv_validator=other,  # not in the validator set: observer
            wal=WAL(str(tmp_path / "wal-obs.log")),
            double_sign_check_height=10,
        )
        cs2.start()  # must NOT raise: no own signature in the window
        cs2.stop()


class TestRoundSkipping:
    def test_two_thirds_any_at_future_round_skips_forward(self, tmp_path):
        """Liveness rule (state.go addVote): +2/3 of prevotes at ANY
        value in a FUTURE round pulls a lagging validator straight to
        that round instead of grinding through timeouts round by
        round."""
        from tendermint_tpu.encoding.canonical import (
            SIGNED_MSG_TYPE_PREVOTE as PV,
        )
        from tendermint_tpu.types.block import BlockID as BID

        h = LockHarness(tmp_path, subject_is_proposer=False)
        h.cs.start()
        try:
            # the rest of the network is already at round 5
            h.inject_votes(PV, 5, BID())
            assert _wait(lambda: h.cs.rs.round == 5, timeout=20), (
                f"stuck at round {h.cs.rs.round}"
            )
            # and it participates there: a prevote at round 5 (nil if
            # no proposal, or its own block when rotation makes it the
            # round-5 proposer)
            pv5 = _wait(lambda: _vote_of(h.cap, PV, 5), timeout=20)
            assert pv5 is not None
        finally:
            h.cs.stop()

    def test_future_round_precommits_skip_too(self, tmp_path):
        from tendermint_tpu.encoding.canonical import (
            SIGNED_MSG_TYPE_PRECOMMIT as PC,
        )
        from tendermint_tpu.types.block import BlockID as BID

        h = LockHarness(tmp_path, subject_is_proposer=False)
        h.cs.start()
        try:
            h.inject_votes(PC, 3, BID())
            assert _wait(lambda: h.cs.rs.round >= 3, timeout=20), (
                f"stuck at round {h.cs.rs.round}"
            )
        finally:
            h.cs.stop()
