"""Chaos battery for the relay-resilient bench harness (bench/,
ISSUE 6): a dead or silent section child must cost exactly its own
section — the merged JSON still carries every other section's real
measurements plus an honest per-section status — and ``--resume``
re-runs only what failed.

The subprocess scenarios lean on the two no-jax sections (``host_ref``
measures the pure-python reference verifier; ``_chaos`` misbehaves on
demand via BENCH_CHAOS) so each child costs interpreter startup, not a
kernel compile.
"""

import json
import os
import signal
import time

import pytest

from bench import heartbeat, results, runner, sections
from bench.heartbeat import Watchdog

pytestmark = pytest.mark.chaos


@pytest.fixture()
def bench_env(monkeypatch, tmp_path):
    """Isolated runner environment: partial + probe log in tmp, tracing
    off, single attempt, short watchdog windows."""
    partial = tmp_path / "partial.json"
    probe_log = tmp_path / "probe_log.md"
    monkeypatch.setenv("BENCH_PARTIAL", str(partial))
    monkeypatch.setenv("BENCH_PROBE_LOG", str(probe_log))
    monkeypatch.setenv("TENDERMINT_TPU_TRACE", "off")
    monkeypatch.setenv("BENCH_SECTION_ATTEMPTS", "1")
    monkeypatch.setenv("BENCH_SECTION_TIMEOUT", "60")
    monkeypatch.setenv("BENCH_HEARTBEAT_TIMEOUT", "15")
    monkeypatch.setenv("BENCH_HOST_REF_SIGS", "4")
    monkeypatch.delenv("BENCH_SECTIONS", raising=False)
    monkeypatch.delenv("BENCH_CHAOS", raising=False)
    return {"partial": str(partial), "probe_log": str(probe_log)}


# --- registry ----------------------------------------------------------------


def test_registry_covers_documented_sections():
    """The sections the ISSUE names, each with the isolation metadata
    the runner keys on."""
    for name in (
        "throughput",
        "stages",
        "cache",
        "light_client",
        "blocksync",
        "verify_commit",
        "verifyd",
        "multichip",
    ):
        assert sections.get(name).needs_jax, name
    assert not sections.get("host_ref").needs_jax
    assert not sections.get("_chaos").needs_jax
    with pytest.raises(KeyError, match="unknown bench section"):
        sections.get("nope")


def test_default_plan_respects_skips_and_chaos_gate(monkeypatch):
    monkeypatch.delenv("BENCH_SECTIONS", raising=False)
    monkeypatch.delenv("BENCH_CHAOS", raising=False)
    plan = sections.default_plan()
    assert "_chaos" not in plan  # only present when BENCH_CHAOS asks
    assert "throughput" in plan and "host_ref" in plan
    monkeypatch.setenv("BENCH_SKIP_COMMIT", "1")
    monkeypatch.setenv("BENCH_SKIP_EXTRAS", "1")
    plan = sections.default_plan()
    assert "verify_commit" not in plan
    assert "light_client" not in plan and "blocksync" not in plan
    monkeypatch.setenv("BENCH_CHAOS", "ok")
    assert "_chaos" in sections.default_plan()
    monkeypatch.setenv("BENCH_SECTIONS", "host_ref,bogus")
    with pytest.raises(KeyError):
        sections.default_plan()


def test_retry_ladder_halves_knobs_and_lands_on_cpu(monkeypatch):
    monkeypatch.setenv("BENCH_SECTION_ATTEMPTS", "3")
    monkeypatch.delenv("BENCH_BATCH", raising=False)
    sec = sections.get("throughput")
    assert runner.ladder_env(sec, 1) == {}
    rung2 = runner.ladder_env(sec, 2)
    assert rung2["BENCH_BATCH"] == "4096" and "BENCH_FORCE_CPU" not in rung2
    rung3 = runner.ladder_env(sec, 3)
    assert rung3["BENCH_BATCH"] == "2048"
    assert rung3["BENCH_FORCE_CPU"] == "1"  # final rung gives up on the relay
    # operator-set bases degrade from the operator's number, with floors
    monkeypatch.setenv("BENCH_BATCH", "600")
    assert runner.ladder_env(sec, 2)["BENCH_BATCH"] == "300"
    assert runner.ladder_env(sec, 3)["BENCH_BATCH"] == "256"  # floor


def test_child_env_strips_sanitizer(monkeypatch):
    """tpusan must never ride into a bench child: instrumented locks
    would poison every number it reports. The runner strips the env var
    no matter what mode the parent runs under."""
    sec = sections.get("host_ref")
    for mode in ("1", "hb", "explore:42"):
        monkeypatch.setenv("TENDERMINT_TPU_SANITIZE", mode)
        env = runner.build_child_env(sec, {}, "/tmp/spool", False)
        assert "TENDERMINT_TPU_SANITIZE" not in env
    # and an explicit override cannot smuggle it back pre-strip
    monkeypatch.delenv("TENDERMINT_TPU_SANITIZE", raising=False)
    env = runner.build_child_env(
        sec, {"TENDERMINT_TPU_SANITIZE": "hb"}, "/tmp/spool", False
    )
    assert "TENDERMINT_TPU_SANITIZE" not in env


# --- heartbeat / watchdog units ---------------------------------------------


def test_watchdog_kills_on_silence_not_on_progress(tmp_path):
    spool = str(tmp_path / "hb.spool")
    clock = [0.0]
    dog = Watchdog(
        spool, beat_timeout=10.0, wall_timeout=100.0, clock=lambda: clock[0]
    )
    writer = heartbeat.HeartbeatWriter("sec", path=spool)
    writer("first")
    clock[0] = 8.0
    assert dog.check() is None  # beat seen, inside the window
    clock[0] = 17.0
    assert dog.check() is None  # 9s of silence < 10s window
    writer("progress")
    clock[0] = 26.0
    assert dog.check() is None  # the beat reset the silence clock
    clock[0] = 37.0
    reason = dog.check()
    assert reason is not None and "heartbeat silence" in reason
    assert "progress" in reason  # diagnostic carries the last beat line


def test_watchdog_startup_window_is_the_probe_budget(tmp_path):
    """A child that never produces its FIRST beat (wedged backend
    import) is held to the probe window, not the heartbeat window."""
    spool = str(tmp_path / "hb.spool")
    clock = [0.0]
    dog = Watchdog(
        spool,
        beat_timeout=300.0,
        wall_timeout=1000.0,
        startup_timeout=20.0,
        clock=lambda: clock[0],
    )
    clock[0] = 19.0
    assert dog.check() is None
    clock[0] = 21.0
    reason = dog.check()
    assert reason is not None and "probe window" in reason


def test_watchdog_wall_timeout_caps_a_dutiful_beater(tmp_path):
    spool = str(tmp_path / "hb.spool")
    clock = [0.0]
    dog = Watchdog(
        spool, beat_timeout=10.0, wall_timeout=50.0, clock=lambda: clock[0]
    )
    writer = heartbeat.HeartbeatWriter("sec", path=spool)
    for t in range(5, 56, 5):
        clock[0] = float(t)
        writer("tick %d" % t)
        verdict = dog.check()
        if t <= 50:
            assert verdict is None, t
    clock[0] = 51.0
    writer("tick")
    assert "wall timeout" in (dog.check() or "")


def test_heartbeat_writer_degrades_without_spool(monkeypatch):
    monkeypatch.delenv(heartbeat.HEARTBEAT_FILE_ENV, raising=False)
    writer = heartbeat.HeartbeatWriter("sec")
    writer("no spool configured")  # must not raise
    assert writer.beats == 1


# --- partial-result JSON ------------------------------------------------------


def test_partial_roundtrip_merge_and_exit_codes(tmp_path):
    path = str(tmp_path / "p.json")
    doc = results.new_partial("cpu")
    results.record_section(
        doc, path, "host_ref",
        results.section_block(
            results.OK, attempts=1, duration_s=1.0,
            result={"host_ref": {"sigs_per_s": 123.0}},
        ),
    )
    assert results.exit_code(doc) == 0
    results.record_section(
        doc, path, "throughput",
        results.section_block(
            results.TIMEOUT, attempts=2, duration_s=9.0, note="heartbeat silence",
        ),
    )
    loaded = results.load_partial(path)  # survives the round-trip
    assert loaded["sections"]["throughput"]["status"] == results.TIMEOUT
    merged = results.merge(loaded, list(sections.ORDER))
    assert merged["schema"] == results.MERGED_SCHEMA
    assert merged["host_ref"] == {"sigs_per_s": 123.0}
    assert merged["value"] == 0.0  # throughput died: headline honest zero
    assert merged["sections"]["throughput"]["note"] == "heartbeat silence"
    assert "result" not in merged["sections"]["host_ref"]
    assert results.exit_code(loaded) == 3  # partial evidence
    doc2 = results.new_partial("cpu")
    results.record_section(
        doc2, None, "throughput",
        results.section_block(results.CRASHED, attempts=3, duration_s=1.0),
    )
    assert results.exit_code(doc2) == 1  # nothing measured


def test_load_partial_rejects_foreign_json(tmp_path):
    path = tmp_path / "x.json"
    path.write_text(json.dumps({"metric": "x", "value": 1}))
    with pytest.raises(ValueError, match="schema"):
        results.load_partial(str(path))


# --- chaos: subprocess scenarios ---------------------------------------------


def _run(plan, **env):
    for k, v in env.items():
        os.environ[k] = v
    try:
        return runner.run(plan=plan)
    finally:
        for k in env:
            os.environ.pop(k, None)


def test_sigkilled_section_keeps_other_sections_evidence(bench_env):
    """SIGKILL one section child mid-run: the merged JSON still carries
    the completed section's real numbers and an honest ``crashed``
    status (attempt count included) for the dead one."""
    merged, code = _run(("host_ref", "_chaos"), BENCH_CHAOS="sigkill")
    assert merged["host_ref"]["sigs_per_s"] > 0  # real measurement survived
    chaos = merged["sections"]["_chaos"]
    assert chaos["status"] == "crashed"
    assert chaos["attempts"] == 1
    assert "-9" in chaos["note"]  # the SIGKILL is visible, not laundered
    assert merged["sections"]["host_ref"]["status"] == "ok"
    assert code == 3  # partial evidence, not rc=124-style total loss
    # the partial file on disk is schema-valid and carries the evidence
    doc = results.load_partial(bench_env["partial"])
    assert doc["sections"]["host_ref"]["result"]["host_ref"]["sigs_per_s"] > 0


def test_heartbeat_silence_triggers_watchdog_kill(bench_env, monkeypatch):
    """A section that goes silent (sleeping child) dies by heartbeat
    watchdog within the configured window — long before the 60s wall
    budget — and lands as ``timeout``."""
    monkeypatch.setenv("BENCH_HEARTBEAT_TIMEOUT", "2")
    t0 = time.monotonic()
    merged, code = _run(("host_ref", "_chaos"), BENCH_CHAOS="hang")
    elapsed = time.monotonic() - t0
    chaos = merged["sections"]["_chaos"]
    assert chaos["status"] == "timeout"
    assert "heartbeat silence" in chaos["note"]
    assert "mode=hang" in chaos["note"]  # last beat line = kill diagnostic
    assert elapsed < 30, "watchdog must kill well before the wall budget"
    assert merged["host_ref"]["sigs_per_s"] > 0
    assert code == 3


def test_resume_reruns_only_failed_sections(bench_env):
    """--resume on a partial with one dead section re-runs exactly that
    section; finished sections keep their original evidence untouched."""
    merged1, code1 = _run(("host_ref", "_chaos"), BENCH_CHAOS="sigkill")
    assert code1 == 3
    before = results.load_partial(bench_env["partial"])
    host_ref_block = dict(before["sections"]["host_ref"])

    os.environ["BENCH_CHAOS"] = "ok"
    try:
        merged2, code2 = runner.run(
            plan=("host_ref", "_chaos"), resume_path=bench_env["partial"]
        )
    finally:
        os.environ.pop("BENCH_CHAOS", None)
    assert code2 == 0
    assert merged2["sections"]["_chaos"]["status"] == "ok"
    assert merged2["chaos"] == {"mode": "ok"}
    # host_ref was NOT re-run: its block (timestamp included) is byte-identical
    after = results.load_partial(bench_env["partial"])
    assert after["sections"]["host_ref"] == host_ref_block


def test_resume_without_plan_finishes_the_recorded_round(bench_env):
    """A partial from a BENCH_SECTIONS subset run records its plan;
    resuming with NO explicit plan must finish that round, not widen to
    the full registry (which would probe jax sections never asked for)."""
    merged1, code1 = _run(("host_ref", "_chaos"), BENCH_CHAOS="crash")
    assert code1 == 3
    recorded = results.load_partial(bench_env["partial"])
    assert recorded["plan"] == ["host_ref", "_chaos"]

    os.environ["BENCH_CHAOS"] = "ok"
    try:
        merged2, code2 = runner.run(resume_path=bench_env["partial"])
    finally:
        os.environ.pop("BENCH_CHAOS", None)
    assert code2 == 0
    # only the recorded round's sections appear — no jax section was drafted
    assert set(merged2["sections"]) == {"host_ref", "_chaos"}
    assert merged2["sections"]["_chaos"]["status"] == "ok"


def test_crashing_section_retries_down_the_ladder(bench_env, monkeypatch):
    monkeypatch.setenv("BENCH_SECTION_ATTEMPTS", "2")
    merged, code = _run(("_chaos",), BENCH_CHAOS="crash")
    chaos = merged["sections"]["_chaos"]
    assert chaos["status"] == "crashed"
    assert chaos["attempts"] == 2  # the ladder actually re-attempted
    assert "injected chaos crash" in chaos["note"]
    assert code == 1  # nothing measured at all


def test_probe_log_gets_one_structured_line_per_section(bench_env):
    merged, _ = _run(("host_ref", "_chaos"), BENCH_CHAOS="sigkill")
    text = open(bench_env["probe_log"]).read()
    lines = [l for l in text.splitlines() if "— section " in l]
    assert len(lines) == 2
    ok_line = next(l for l in lines if "section host_ref" in l)
    assert "ok in" in ok_line and "attempts=1" in ok_line
    dead_line = next(l for l in lines if "section _chaos" in l)
    assert "crashed in" in dead_line
    # plus the whole-round summary line the old harness always wrote
    assert any("bench round on JAX_PLATFORMS" in l for l in text.splitlines())


def test_skipped_sections_get_honest_status(bench_env, monkeypatch):
    """Legacy BENCH_SKIP_* opt-outs surface as status=skipped blocks in
    the merged JSON rather than silently vanishing."""
    monkeypatch.setenv("BENCH_SKIP_COMMIT", "1")
    doc = results.new_partial("cpu")
    runner.mark_skipped(doc, None)
    assert doc["sections"]["verify_commit"]["status"] == "skipped"
    assert doc["sections"]["verify_commit"]["note"] == "BENCH_SKIP_COMMIT=1"
    assert "throughput" not in doc["sections"]  # not skipped, just not run yet
