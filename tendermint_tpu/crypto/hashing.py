"""Batched host-side hashing for the device verifier.

``sha512_batch`` hashes N variable-length messages through a small C
extension (``native/sha512_batch.c``, OpenMP-parallel, built lazily
with the system compiler and loaded via ctypes) with a pure-hashlib
fallback. ``sha512_batch_mod_l`` additionally reduces each 512-bit
digest mod the ed25519 group order L with a vectorized numpy Barrett
reduction — no per-signature Python arithmetic anywhere on the hot
path.

Reference analog: the challenge hashing inside curve25519-voi's batch
verifier (crypto/ed25519/ed25519.go:198-233).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import List, Optional, Sequence

import numpy as np

L = 2**252 + 27742317777372353535851937790883648493

_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    """Compile the C extension once per machine and load it."""
    src = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "sha512_batch.c")
    if not os.path.exists(src):
        return None
    build_dir = os.environ.get(
        "TENDERMINT_TPU_BUILD_DIR",
        os.path.join(tempfile.gettempdir(), "tendermint_tpu_native"),
    )
    os.makedirs(build_dir, exist_ok=True)
    lib_path = os.path.join(build_dir, "libsha512batch.so")
    if not os.path.exists(lib_path) or os.path.getmtime(lib_path) < os.path.getmtime(src):
        for cc in ("cc", "gcc", "g++"):
            try:
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-fopenmp", src, "-o", lib_path + ".tmp"],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(lib_path + ".tmp", lib_path)
                break
            except Exception:
                continue
        else:
            return None
    try:
        lib = ctypes.CDLL(lib_path)
        lib.sha512_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.sha512_batch.restype = None
        lib.sha512_batch_prefixed.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.sha512_batch_prefixed.restype = None
        return lib
    except Exception:
        return None


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB_TRIED = True
        _LIB = _build_and_load()
    return _LIB


def sha512_batch(msgs: Sequence[bytes]) -> np.ndarray:
    """N messages -> (N, 64) uint8 digests."""
    n = len(msgs)
    if n == 0:
        return np.zeros((0, 64), dtype=np.uint8)
    lib = _lib()
    if lib is None:
        out = np.empty((n, 64), dtype=np.uint8)
        for i, m in enumerate(msgs):
            out[i] = np.frombuffer(hashlib.sha512(m).digest(), dtype=np.uint8)
        return out
    offsets = np.zeros(n + 1, dtype=np.uint64)
    np.cumsum([len(m) for m in msgs], out=offsets[1:])
    buf = np.frombuffer(b"".join(msgs), dtype=np.uint8)
    if buf.size == 0:
        buf = np.zeros(1, dtype=np.uint8)
    out = np.empty((n, 64), dtype=np.uint8)
    lib.sha512_batch(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def sha512_batch_prefixed(prefix: np.ndarray, msgs: Sequence[bytes]) -> np.ndarray:
    """Hash prefix_i || msg_i for a (N, 64) uint8 prefix block -> (N, 64).

    The verifier's challenge is SHA-512(R || A || M); R and A already
    live in (N, 32) arrays, so the 64-byte prefix block costs one
    concatenate instead of N Python byte-string builds.
    """
    n = len(msgs)
    assert prefix.shape == (n, 64) and prefix.dtype == np.uint8
    if n == 0:
        return np.zeros((0, 64), dtype=np.uint8)
    lib = _lib()
    if lib is None:
        out = np.empty((n, 64), dtype=np.uint8)
        pb = np.ascontiguousarray(prefix)
        for i, m in enumerate(msgs):
            h = hashlib.sha512(pb[i].tobytes())
            h.update(m)
            out[i] = np.frombuffer(h.digest(), dtype=np.uint8)
        return out
    offsets = np.zeros(n + 1, dtype=np.uint64)
    np.cumsum([len(m) for m in msgs], out=offsets[1:])
    buf = np.frombuffer(b"".join(msgs), dtype=np.uint8)
    if buf.size == 0:
        buf = np.zeros(1, dtype=np.uint8)
    out = np.empty((n, 64), dtype=np.uint8)
    pb = np.ascontiguousarray(prefix)
    lib.sha512_batch_prefixed(
        pb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


# --- vectorized Barrett reduction mod L -------------------------------------
#
# Values are little-endian 16-bit limb vectors; all products accumulate
# in int64 (max column ~ 40 * 2^32 < 2^38, exact). Barrett with
# mu = floor(2^512 / L): q = floor(floor(x / 2^248) * mu / 2^264),
# r = x - q*L, then at most two conditional subtracts of L.

_NL16 = 16  # limbs of a 256-bit value
_L_LIMBS = np.array([(L >> (16 * i)) & 0xFFFF for i in range(16)], dtype=np.int64)
_MU = (1 << 512) // L
_MU_LIMBS = np.array([(_MU >> (16 * i)) & 0xFFFF for i in range((_MU.bit_length() + 15) // 16)], dtype=np.int64)


def _carry16(cols: np.ndarray, nlimbs: int) -> np.ndarray:
    """Carry-propagate int64 columns into nlimbs 16-bit limbs (drop overflow)."""
    out = np.zeros((cols.shape[0], nlimbs), dtype=np.int64)
    c = np.zeros(cols.shape[0], dtype=np.int64)
    for i in range(nlimbs):
        v = c + (cols[:, i] if i < cols.shape[1] else 0)
        out[:, i] = v & 0xFFFF
        c = v >> 16
    return out


def _mul_const(x: np.ndarray, const_limbs: np.ndarray) -> np.ndarray:
    """(N, a) 16-bit limbs times constant (b,) limbs -> (N, a+b) columns."""
    n, a = x.shape
    b = const_limbs.shape[0]
    cols = np.zeros((n, a + b), dtype=np.int64)
    for j in range(b):
        cols[:, j : j + a] += x * const_limbs[j]
    return cols


def _ge(x: np.ndarray, y_limbs: np.ndarray) -> np.ndarray:
    """(N, 16) >= const (16,) comparison, little-endian limbs."""
    diff = x - y_limbs[None, :]
    nz = diff != 0
    rev = nz[:, ::-1]
    first = np.argmax(rev, axis=1)
    rows = np.arange(x.shape[0])
    val = diff[:, ::-1][rows, first]
    any_nz = nz.any(axis=1)
    return np.where(any_nz, val > 0, True)


def reduce_mod_l(digests: np.ndarray) -> np.ndarray:
    """(N, 64) uint8 little-endian 512-bit values -> (N, 32) uint8 mod L."""
    n = digests.shape[0]
    x16 = (
        digests.reshape(n, 32, 2).astype(np.int64)[:, :, 0]
        + (digests.reshape(n, 32, 2).astype(np.int64)[:, :, 1] << 8)
    )  # (N, 32) 16-bit limbs, little-endian
    # q1 = floor(x / 2^248) -> drop 15.5 limbs; use 2^240 (15 limbs) for a
    # slightly larger q1*mu, then shift 2^272 total. Keep it simple and
    # exact: q = floor( floor(x/2^240) * mu / 2^272 ).
    q1 = x16[:, 15:]  # (N, 17) limbs: x >> 240
    q2 = _mul_const(q1, _MU_LIMBS)  # x/2^240 * mu, columns
    q2 = _carry16(q2, q2.shape[1])
    q = q2[:, 17:]  # >> 272
    # r = x - q*L (mod 2^256 is safe: r < 2L < 2^253)
    ql = _carry16(_mul_const(q, _L_LIMBS), 16)
    r = np.zeros((n, 16), dtype=np.int64)
    borrow = np.zeros(n, dtype=np.int64)
    for i in range(16):
        v = x16[:, i] - ql[:, i] - borrow
        borrow = (v < 0).astype(np.int64)
        r[:, i] = v + (borrow << 16)
    # Barrett error bound for this shift choice: r < 4L -> up to 3 subtracts.
    for _ in range(3):
        ge = _ge(r, _L_LIMBS)
        borrow = np.zeros(n, dtype=np.int64)
        sub = np.zeros_like(r)
        for i in range(16):
            v = r[:, i] - _L_LIMBS[i] - borrow
            borrow = (v < 0).astype(np.int64)
            sub[:, i] = v + (borrow << 16)
        r = np.where(ge[:, None], sub, r)
    out = np.zeros((n, 32), dtype=np.uint8)
    out[:, 0::2] = (r & 0xFF).astype(np.uint8)
    out[:, 1::2] = ((r >> 8) & 0xFF).astype(np.uint8)
    return out


def sha512_batch_mod_l(msgs: Sequence[bytes]) -> List[bytes]:
    """N messages -> N 32-byte little-endian scalars SHA-512(m) mod L."""
    if not msgs:
        return []
    digests = sha512_batch(msgs)
    reduced = reduce_mod_l(digests)
    return [reduced[i].tobytes() for i in range(reduced.shape[0])]
