"""Key interface tests: addresses, signing, verification, proto codec."""

import hashlib

from tendermint_tpu.crypto import (
    Ed25519PrivKey,
    Ed25519PubKey,
    Secp256k1PrivKey,
    create_batch_verifier,
    pubkey_from_proto,
    pubkey_to_proto,
    supports_batch_verifier,
)


def test_ed25519_address_is_sha256_prefix():
    priv = Ed25519PrivKey.from_seed(b"\x07" * 32)
    pub = priv.pub_key()
    assert pub.address() == hashlib.sha256(pub.bytes()).digest()[:20]
    assert len(pub.address()) == 20


def test_ed25519_sign_verify():
    priv = Ed25519PrivKey.generate()
    pub = priv.pub_key()
    sig = priv.sign(b"payload")
    assert pub.verify_signature(b"payload", sig)
    assert not pub.verify_signature(b"other", sig)
    assert not pub.verify_signature(b"payload", sig[:-1])


def test_secp256k1_sign_verify_and_address():
    priv = Secp256k1PrivKey.generate()
    pub = priv.pub_key()
    assert len(pub.bytes()) == 33
    assert pub.address() == hashlib.new(
        "ripemd160", hashlib.sha256(pub.bytes()).digest()
    ).digest()
    sig = priv.sign(b"tx bytes")
    assert len(sig) == 64
    assert pub.verify_signature(b"tx bytes", sig)
    assert not pub.verify_signature(b"bad", sig)
    # high-s malleated signature must be rejected (low-s rule)
    from tendermint_tpu.crypto.keys import SECP256K1_N

    r = sig[:32]
    s = int.from_bytes(sig[32:], "big")
    high = r + (SECP256K1_N - s).to_bytes(32, "big")
    assert not pub.verify_signature(b"tx bytes", high)


def test_pubkey_proto_roundtrip():
    priv = Ed25519PrivKey.from_seed(b"\x01" * 32)
    pub = priv.pub_key()
    enc = pubkey_to_proto(pub)
    assert enc[0] == 0x0A  # field 1, wire 2
    back = pubkey_from_proto(enc)
    assert back == pub and isinstance(back, Ed25519PubKey)

    spriv = Secp256k1PrivKey.generate()
    enc2 = pubkey_to_proto(spriv.pub_key())
    assert enc2[0] == 0x12  # field 2, wire 2
    assert pubkey_from_proto(enc2) == spriv.pub_key()


def test_batch_dispatch():
    ed = Ed25519PrivKey.generate()
    assert supports_batch_verifier(ed.pub_key())
    sec = Secp256k1PrivKey.generate()
    assert not supports_batch_verifier(sec.pub_key())

    bv = create_batch_verifier(ed.pub_key())
    msgs = [b"msg%d" % i for i in range(5)]
    for m in msgs:
        bv.add(ed.pub_key(), m, ed.sign(m))
    ok, oks = bv.verify()
    assert ok and all(oks) and len(oks) == 5

    bv2 = create_batch_verifier(ed.pub_key())
    for i, m in enumerate(msgs):
        sig = ed.sign(m)
        if i == 2:
            sig = sig[:32] + bytes(32)  # s = 0 is canonical but wrong
        bv2.add(ed.pub_key(), m, sig)
    ok, oks = bv2.verify()
    assert not ok
    assert oks == [True, True, False, True, True]
