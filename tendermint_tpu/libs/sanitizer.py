"""tpusan — runtime concurrency sanitizer for the serving stack.

Three modes, selected by ``TENDERMINT_TPU_SANITIZE`` (parsed by
``install()``, normally from tests/conftest.py BEFORE jax or the package
under test create any locks):

``=1`` — **lock-order mode** (the original sanitizer).
    ``threading.Lock``/``threading.RLock`` are replaced by a wrapper
    that keeps a per-thread stack of held locks and records, on every
    acquisition, an edge from each held lock to the new one in a
    process-wide acquisition-order graph. Nodes are lock *creation
    sites* (``file:line`` of the constructor call), so the thousands of
    per-metric lock instances collapse into one node per class of lock.
    A cycle in that graph is a potential deadlock even if no run ever
    deadlocked. Blocking IO under a lock is surfaced report-only.

``=hb`` — **happens-before race detection** (implies lock-order mode).
    Every thread carries a vector clock. Sync primitives thread the
    clocks through: a lock release publishes the holder's clock on the
    lock, an acquire joins it; ``Thread.start`` snapshots the parent
    clock as the child's birth clock; ``Thread.join`` joins the dead
    child's final clock. ``Event``, ``Condition`` and ``queue.Queue``
    ride the same machinery because their internal locks are created
    after install and are therefore sanitized (``queue.SimpleQueue`` is
    aliased to ``queue.Queue`` so executor hand-offs get edges too).
    Classes opted in with ``@instrument_attrs`` get per-attribute
    access tracking: two accesses to the same attribute, at least one a
    write, with no happens-before path between them is a **DATA RACE**,
    reported with both access stacks and the locks each side held (the
    sync evidence that failed to order them). ci_checks.sh greps for
    the ``DATA RACE`` marker.

``=explore:<seed>`` — **deterministic schedule exploration** (implies hb).
    Inside an ``explore_scope()`` (tests/conftest.py opens one per test
    in this mode), participating threads — the scope owner plus every
    thread it transitively starts — are serialized through a single
    run token. At each sync point (lock acquire/release, tracked
    attribute access) the token holder consults a PRNG seeded with
    ``<seed>`` to pick which runnable participant goes next; a thread
    about to truly block hands the token off first and re-queues after
    waking. The schedule is a pure function of the seed, so a race
    found in CI replays byte-identically from its seed on a laptop.

Overhead is a dict update (plus, under hb, a short stack walk) per
instrumented operation — fine for tests, not for production; this is a
test-harness tool, which is why it activates only via explicit
env/install and never by import side effect, and why bench/ strips the
env var from child processes.
"""

from __future__ import annotations

import _thread
import contextlib
import os
import queue as _queue_mod
import random
import re
import selectors
import socket
import sys
import threading
import time
import traceback
import weakref
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

ENV = "TENDERMINT_TPU_SANITIZE"

# internal bookkeeping uses raw OS locks so the sanitizer never records
# (or deadlocks on) itself
_state_mtx = _thread.allocate_lock()
_tls = threading.local()

_installed = False
_orig_lock = None
_orig_rlock = None
_orig_sleep = None
_orig_recv = None
_orig_accept = None
_orig_select = None

# hb-mode patch originals
_hb_on = False
_orig_thread_start = None
_orig_thread_join = None
_orig_cond_wait = None
_orig_cond_notify = None
_orig_simple_queue = None

_explore_seed: Optional[int] = None
_explorer: Optional["_Explorer"] = None

#: (from_site, to_site) -> example (thread name, to-site acquire stack)
_edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
#: (io kind, frozenset of held sites) -> example thread name
_io_violations: Dict[Tuple[str, Tuple[str, ...]], str] = {}
_known_sites: Set[str] = set()

# --- happens-before state ----------------------------------------------------
# Generation counter: reset() bumps it, lazily invalidating every
# per-thread and per-lock clock without having to reach into other
# threads' TLS.
_hb_gen = 0
# Dense tids: 0 is reserved for the main thread, children preassigned
# at start() draw 1, 2, ... in schedule order. Threads the sanitizer
# never saw start (leaked pools from earlier tests, foreign daemons)
# draw from a disjoint high range so their first-sync timing can never
# shift a participant's tid — replay reports stay byte-stable even in
# a full-suite process with stragglers.
_MAIN_TID = 0
_FOREIGN_TID_BASE = 10000
_next_tid = 1
_next_foreign_tid = _FOREIGN_TID_BASE
#: (id(obj), attr) -> {"cls", "attr", "w": (tid, clock, acc)|None,
#:                     "r": {tid: (clock, acc)}}
#: where acc = (op, thread-disp, stack, held-lock-sites)
_vars: Dict[Tuple[int, str], dict] = {}
#: dedup key -> race record
_races: Dict[Tuple, dict] = {}

_RAW_LOCK_TYPE = type(_thread.allocate_lock())
_DEFAULT_NAME_RE = re.compile(r"^(Thread-\d+|ThreadPoolExecutor-\d+_\d+)")

_HERE = os.path.abspath(__file__)
_rel_cache: Dict[str, str] = {}
_skip_cache: Dict[str, bool] = {}


def enabled_from_env() -> bool:
    return os.environ.get(ENV, "") not in ("", "0", "false", "no")


def _parse_mode(value: str) -> Tuple[bool, Optional[int]]:
    """``value`` -> (hb enabled, explore seed or None)."""
    v = (value or "").strip().lower()
    if v.startswith("explore"):
        seed = 0
        if ":" in v:
            try:
                seed = int(v.split(":", 1)[1])
            except ValueError:
                seed = 0
        return True, seed
    if v == "hb":
        return True, None
    return False, None


def active_mode() -> str:
    """One of ``off | lockorder | hb | explore``."""
    if not _installed:
        return "off"
    if _explore_seed is not None:
        return "explore"
    if _hb_on:
        return "hb"
    return "lockorder"


def hb_enabled() -> bool:
    return _hb_on


def explore_seed() -> Optional[int]:
    return _explore_seed


def _caller_site() -> str:
    """file:line of the lock constructor call, skipping sanitizer and
    threading internals (a Condition() allocates its RLock inside
    threading.py — the interesting site is Condition's caller)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if (
            os.path.abspath(fn) != os.path.abspath(__file__)
            and os.sep + "threading.py" not in fn
        ):
            try:
                rel = os.path.relpath(fn)
            except ValueError:
                rel = fn
            if not rel.startswith(".."):
                fn = rel
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _relfile(fn: str) -> str:
    r = _rel_cache.get(fn)
    if r is None:
        try:
            rel = os.path.relpath(fn)
        except ValueError:
            rel = fn
        r = fn if rel.startswith("..") else rel
        _rel_cache[fn] = r
    return r


def _short_stack(limit: int = 6) -> Tuple[Tuple[str, int, str], ...]:
    """Compact stack of the current access: (file, line, func) tuples,
    innermost first, sanitizer frames skipped. Cheap enough to capture
    on every tracked access; formatted only if a race is reported."""
    try:
        f = sys._getframe(2)
    except ValueError:
        return ()
    out: List[Tuple[str, int, str]] = []
    while f is not None and len(out) < limit:
        fn = f.f_code.co_filename
        skip = _skip_cache.get(fn)
        if skip is None:
            skip = os.path.abspath(fn) == _HERE
            _skip_cache[fn] = skip
        if not skip:
            out.append((_relfile(fn), f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return tuple(out)


def _held_stack() -> List["_SanitizedLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


# --- vector clocks -----------------------------------------------------------


class _HBThread:
    __slots__ = ("tid", "vc", "gen", "disp")


def _current_thread_obj() -> Optional[threading.Thread]:
    """The current Thread object WITHOUT threading.current_thread():
    during bootstrap a thread fires sync ops (``_started.set()``)
    before it lands in ``threading._active``, and current_thread()
    would then manufacture a _DummyThread whose Event recurses straight
    back into the sanitizer. Returns None for truly foreign threads."""
    ident = _thread.get_ident()
    t = threading._active.get(ident)
    if t is not None:
        return t
    try:
        for t in list(threading._limbo.values()):
            if t._ident == ident:
                return t
    except RuntimeError:
        pass  # _limbo mutated under us: treat as a foreign thread
    return None


def _alloc_tid() -> int:
    global _next_tid
    with _state_mtx:
        tid = _next_tid
        _next_tid += 1
    return tid


def _alloc_foreign_tid() -> int:
    global _next_foreign_tid
    with _state_mtx:
        tid = _next_foreign_tid
        _next_foreign_tid += 1
    return tid


def _hb_state() -> _HBThread:
    """Per-thread hb state, lazily (re)created per generation. Thread
    ids are dense ints preassigned by the parent at ``start()`` (so the
    numbering is schedule-determined under the explorer and reports are
    byte-stable for a given seed); threads the sanitizer never saw
    start (the main thread, foreign pools) allocate on first sync."""
    st = getattr(_tls, "hb", None)
    if st is not None and st.gen == _hb_gen:
        return st
    cur = _current_thread_obj()
    pre = getattr(cur, "_tpusan_tid", None) if cur is not None else None
    if pre is not None and pre[0] == _hb_gen:
        tid = pre[1]
    elif cur is not None and cur is threading.main_thread():
        tid = _MAIN_TID
    else:
        tid = _alloc_foreign_tid()
    st = _HBThread()
    st.tid = tid
    st.gen = _hb_gen
    st.vc = {tid: 1}
    name = cur.name if cur is not None else ""
    if not name or _DEFAULT_NAME_RE.match(name):
        # auto-numbered names drift with the process-global thread
        # counter; keep reports byte-stable across replays
        st.disp = "T%d" % tid
    else:
        st.disp = "T%d(%s)" % (tid, name)
    birth = getattr(cur, "_tpusan_birth", None) if cur is not None else None
    if birth is not None and birth[0] == _hb_gen:
        vc = st.vc
        for t, c in birth[1].items():
            if c > vc.get(t, 0):
                vc[t] = c
    _tls.hb = st
    if cur is not None:
        cur._tpusan_state = st
    return st


def _hb_lock_acquired(lock: Any) -> None:
    # only the holder touches lock._hb_vc, so no extra locking needed
    if getattr(lock, "_hb_gen", -1) != _hb_gen:
        return
    st = _hb_state()
    vc = st.vc
    for t, c in lock._hb_vc.items():
        if c > vc.get(t, 0):
            vc[t] = c


def _hb_lock_released(lock: Any) -> None:
    st = _hb_state()
    lock._hb_vc = dict(st.vc)
    lock._hb_gen = _hb_gen
    st.vc[st.tid] = st.vc.get(st.tid, 0) + 1


def _record_race_locked(v: dict, first: tuple, second: tuple) -> None:
    key = (
        v["cls"],
        v["attr"],
        first[0],
        first[2][0] if first[2] else None,
        second[0],
        second[2][0] if second[2] else None,
    )
    if key in _races:
        return
    _races[key] = {
        "cls": v["cls"],
        "attr": v["attr"],
        "first": first,
        "second": second,
    }


def _note_var_access(obj: Any, name: str, is_write: bool) -> None:
    if not _hb_on or getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        ex = _explorer
        if ex is not None:
            ex.maybe_switch()
        st = _hb_state()
        stack = _short_stack()
        held = tuple(sorted({l._site for l in _held_stack()}))
        acc = ("write" if is_write else "read", st.disp, stack, held)
        tid = st.tid
        vc = st.vc
        clk = vc[tid]
        key = (id(obj), name)
        try:
            ref = weakref.ref(obj)
        except TypeError:
            ref = None
        with _state_mtx:
            v = _vars.get(key)
            if v is not None and v["ref"] is not None and v["ref"]() is not obj:
                v = None  # id(obj) reuse: a dead object's record collided
            if v is None:
                v = _vars[key] = {
                    "cls": type(obj).__name__,
                    "attr": name,
                    "ref": ref,
                    "w": None,
                    "r": {},
                }
            w = v["w"]
            if w is not None and w[0] != tid and w[1] > vc.get(w[0], 0):
                _record_race_locked(v, w[2], acc)
            if is_write:
                for rt, (rc, racc) in v["r"].items():
                    if rt != tid and rc > vc.get(rt, 0):
                        _record_race_locked(v, racc, acc)
                v["w"] = (tid, clk, acc)
                v["r"] = {}
            else:
                v["r"][tid] = (clk, acc)
    finally:
        _tls.busy = False


# --- attribute instrumentation -----------------------------------------------

_ATTR_REGISTRY: List[type] = []
_WRAPPED: Dict[type, Tuple[Any, Any]] = {}

_sync_types_cache: Optional[tuple] = None


def _sync_types() -> tuple:
    global _sync_types_cache
    if _sync_types_cache is None:
        _sync_types_cache = (
            _SanitizedLock,
            _RAW_LOCK_TYPE,
            _thread.RLock,
            threading.Condition,
            threading.Event,
            threading.Thread,
            threading.Semaphore,
            threading.Barrier,
        )
    return _sync_types_cache


def instrument_attrs(cls=None, *, exclude: Tuple[str, ...] = ()):
    """Class decorator opting a class into tpusan attribute tracking.

    Free when the sanitizer is off: classes are only wrapped while hb
    mode is active (env-installed runs wrap at decoration time; test
    fixtures wrap retroactively via ``instrumented()``). ``exclude``
    names attributes that are racy by design (documented stats-grade
    reads) and must not be reported.
    """

    def deco(c: type) -> type:
        c._tpusan_exclude = frozenset(exclude) | getattr(
            c, "_tpusan_exclude", frozenset()
        )
        _ATTR_REGISTRY.append(c)
        if _hb_on:
            _wrap_class(c)
        return c

    if cls is None:
        return deco
    return deco(cls)


def _wrap_class(cls: type) -> bool:
    if cls in _WRAPPED:
        return False
    orig_ga = cls.__getattribute__
    orig_sa = cls.__setattr__
    exclude = getattr(cls, "_tpusan_exclude", frozenset())

    def __getattribute__(self, name):
        val = orig_ga(self, name)
        if (
            _hb_on
            and name[:2] != "__"
            and not name.startswith("_tpusan")
            and name not in exclude
        ):
            try:
                d = orig_ga(self, "__dict__")
            except AttributeError:
                return val
            if name in d and not isinstance(val, _sync_types()):
                _note_var_access(self, name, False)
        return val

    def __setattr__(self, name, value):
        if (
            _hb_on
            and name[:2] != "__"
            and not name.startswith("_tpusan")
            and name not in exclude
            and not isinstance(value, _sync_types())
        ):
            _note_var_access(self, name, True)
        orig_sa(self, name, value)

    cls.__getattribute__ = __getattribute__
    cls.__setattr__ = __setattr__
    _WRAPPED[cls] = (orig_ga, orig_sa)
    return True


def _unwrap_class(cls: type) -> None:
    pair = _WRAPPED.pop(cls, None)
    if pair is None:
        return
    cls.__getattribute__, cls.__setattr__ = pair


@contextlib.contextmanager
def instrumented(*classes: type) -> Iterator[None]:
    """Wrap the given classes (default: every registered class) for the
    duration — how tier-1 tests get attribute tracking without the env
    var being set at import time. Classes already wrapped by an
    env-mode install are left wrapped on exit."""
    targets = list(classes) if classes else list(_ATTR_REGISTRY)
    mine = [c for c in targets if _wrap_class(c)]
    try:
        yield
    finally:
        for c in mine:
            _unwrap_class(c)


# --- the lock wrapper --------------------------------------------------------


class _SanitizedLock:
    """Wraps a raw Lock/RLock; speaks both the lock protocol and the
    pieces of the RLock protocol that threading.Condition wants."""

    def __init__(self, inner: Any, site: str, reentrant: bool):
        self._inner = inner
        self._site = site
        self._reentrant = reentrant
        with _state_mtx:
            _known_sites.add(site)

    # --- bookkeeping ---------------------------------------------------------

    def _depth(self) -> int:
        return sum(1 for l in _held_stack() if l is self)

    def _note_acquired(self) -> None:
        stack = _held_stack()
        if self._reentrant and self._depth() > 0:
            stack.append(self)  # reentrant re-acquire: no new edges
            return
        if _hb_on:
            _hb_lock_acquired(self)
        held_sites = []
        for l in stack:
            if l._site != self._site and l._site not in held_sites:
                held_sites.append(l._site)
        if held_sites:
            cur = _current_thread_obj()
            who = cur.name if cur is not None else "<foreign>"
            try:
                frame = sys._getframe(3)
            except ValueError:
                frame = None
            where = "".join(traceback.format_stack(frame, limit=4))
            with _state_mtx:
                for s in held_sites:
                    _edges.setdefault((s, self._site), (who, where))
        stack.append(self)

    def _note_released(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        else:
            return
        # publish the clock BEFORE the raw release so the next holder
        # observes it (outermost release only, for RLocks)
        if _hb_on and not (self._reentrant and self._depth() > 0):
            _hb_lock_released(self)

    # --- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ex = _explorer
        if ex is not None and ex.active and ex.current_part() is not None:
            ex.maybe_switch()
            if blocking:
                ok = self._inner.acquire(False)
                if not ok:
                    # hand the run token off before truly blocking
                    ex.block_begin()
                    try:
                        ok = self._inner.acquire(True, timeout)
                    finally:
                        ex.block_end()
            else:
                ok = self._inner.acquire(False)
        else:
            ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    def release(self) -> None:
        self._note_released()
        self._inner.release()
        ex = _explorer
        if ex is not None and ex.active:
            ex.note_wake()
            ex.maybe_switch()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib modules (concurrent.futures.thread) register this with
        # os.register_at_fork at import time; held-state bookkeeping in
        # the child is stale anyway, so just reinit the raw lock.
        self._inner._at_fork_reinit()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "RLock" if self._reentrant else "Lock"
        return f"<sanitized {kind} from {self._site}>"

    # --- Condition protocol (used by threading.Condition) --------------------

    def _release_save(self):
        self._note_released()
        if self._reentrant:
            # fully release an N-deep RLock; Condition restores it after
            depth = self._depth() + 1  # +1: _note_released popped one
            while self._depth() > 0:
                self._note_released()
            if hasattr(self._inner, "_release_save"):
                state = (self._inner._release_save(), depth)
            else:
                self._inner.release()
                state = (None, depth)
        else:
            self._inner.release()
            state = None
        ex = _explorer
        if ex is not None and ex.active:
            ex.note_wake()
        return state

    def _acquire_restore(self, state) -> None:
        if self._reentrant:
            inner_state, depth = state
            if hasattr(self._inner, "_acquire_restore"):
                self._inner._acquire_restore(inner_state)
            else:
                self._inner.acquire()
            for _ in range(depth):
                self._note_acquired()
        else:
            self._inner.acquire()
            self._note_acquired()

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain Lock: same approximation threading.Condition uses
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def _make_lock():
    return _SanitizedLock(_orig_lock(), _caller_site(), reentrant=False)


def _make_rlock():
    return _SanitizedLock(_orig_rlock(), _caller_site(), reentrant=True)


# --- IO-under-lock probes -----------------------------------------------------


def _note_io(kind: str) -> None:
    stack = getattr(_tls, "held", None)
    if not stack:
        return
    sites = tuple(sorted({l._site for l in stack}))
    cur = _current_thread_obj()
    who = cur.name if cur is not None else "<foreign>"
    with _state_mtx:
        _io_violations.setdefault((kind, sites), who)


@contextlib.contextmanager
def _explorer_blocking() -> Iterator[None]:
    """Release the explorer run token around a truly blocking call."""
    ex = _explorer
    if ex is not None and ex.active and ex.current_part() is not None:
        ex.block_begin()
        try:
            yield
        finally:
            ex.block_end()
    else:
        yield


def _sleep(seconds: float) -> None:
    _note_io("time.sleep")
    with _explorer_blocking():
        _orig_sleep(seconds)


def _recv(self, *args, **kwargs):
    _note_io("socket.recv")
    with _explorer_blocking():
        return _orig_recv(self, *args, **kwargs)


def _accept(self, *args, **kwargs):
    _note_io("socket.accept")
    with _explorer_blocking():
        return _orig_accept(self, *args, **kwargs)


def _select(self, *args, **kwargs):
    # event loops park here with second-scale timeouts; without the
    # release an evloop participant would sit on the explore run token
    # for the whole select and every schedule decision would degrade
    # through the stall failsafe
    with _explorer_blocking():
        return _orig_select(self, *args, **kwargs)


# --- hb-mode thread / condition patches --------------------------------------


def _thread_start(self):
    if _hb_on:
        st = _hb_state()
        self._tpusan_birth = (_hb_gen, dict(st.vc))
        self._tpusan_tid = (_hb_gen, _alloc_tid())
        st.vc[st.tid] = st.vc.get(st.tid, 0) + 1
    ex = _explorer
    if ex is not None and ex.active and ex.current_part() is not None:
        ex.adopt_child(self)
        ex.note_wake()
    return _orig_thread_start(self)


def _thread_join(self, timeout=None):
    with _explorer_blocking():
        r = _orig_thread_join(self, timeout)
    if _hb_on and not self.is_alive():
        child = getattr(self, "_tpusan_state", None)
        if child is not None and child.gen == _hb_gen:
            st = _hb_state()
            vc = st.vc
            for t, c in child.vc.items():
                if c > vc.get(t, 0):
                    vc[t] = c
    return r


def _cond_wait(self, timeout=None):
    with _explorer_blocking():
        return _orig_cond_wait(self, timeout)


def _cond_notify(self, n=1):
    ex = _explorer
    if ex is not None and ex.active:
        ex.note_wake()
    return _orig_cond_notify(self, n)


def _enable_hb() -> None:
    global _hb_on, _orig_thread_start, _orig_thread_join
    global _orig_cond_wait, _orig_cond_notify, _orig_simple_queue
    if _hb_on:
        return
    _orig_thread_start = threading.Thread.start
    threading.Thread.start = _thread_start
    _orig_thread_join = threading.Thread.join
    threading.Thread.join = _thread_join
    _orig_cond_wait = threading.Condition.wait
    threading.Condition.wait = _cond_wait
    _orig_cond_notify = threading.Condition.notify
    threading.Condition.notify = _cond_notify
    # SimpleQueue is C-implemented and invisible to the clocks; Queue is
    # pure python over sanitized locks, so executor hand-offs get edges
    _orig_simple_queue = _queue_mod.SimpleQueue
    _queue_mod.SimpleQueue = _queue_mod.Queue
    _hb_on = True
    for c in list(_ATTR_REGISTRY):
        _wrap_class(c)


def _disable_hb() -> None:
    global _hb_on
    if not _hb_on:
        return
    threading.Thread.start = _orig_thread_start
    threading.Thread.join = _orig_thread_join
    threading.Condition.wait = _orig_cond_wait
    threading.Condition.notify = _orig_cond_notify
    _queue_mod.SimpleQueue = _orig_simple_queue
    _hb_on = False
    for c in list(_WRAPPED):
        _unwrap_class(c)


# --- deterministic schedule explorer -----------------------------------------


class _Gate:
    """One-shot token gate on a raw lock (never a sanitized primitive,
    so the explorer cannot record or schedule itself)."""

    __slots__ = ("_lk",)

    def __init__(self):
        self._lk = _thread.allocate_lock()
        self._lk.acquire()

    def wait(self, timeout: float) -> bool:
        return self._lk.acquire(True, timeout)

    def set(self) -> None:
        try:
            self._lk.release()
        except RuntimeError:
            pass  # already signalled: the gate is level, not a counter


class _Part:
    __slots__ = ("ex", "reg", "gate", "blocked", "ident")


class _Explorer:
    """Token-passing cooperative scheduler. Participants are the scope
    owner and threads transitively started by participants; everything
    else free-runs (its accesses are still race-checked by hb). Exactly
    one non-blocked participant runs at a time; every sync point is a
    PRNG-driven switch decision, so the interleaving is a deterministic
    function of the seed."""

    #: failsafe so a participant stuck behind an uninstrumented blocking
    #: call degrades exploration instead of deadlocking the test run
    STALL_TIMEOUT = 2.0
    #: settle window after a block-state change: a thread woken from a
    #: real block needs a moment of CPU to run block_end and re-park;
    #: deciding before it settles would make the candidate set (and so
    #: the rng stream) a function of OS wake latency, not the seed
    GRACE = 0.002

    def __init__(self, seed: int):
        self.seed = seed
        self.rng = random.Random(seed)
        self.mtx = _thread.allocate_lock()
        self.active = True
        self.parts: Dict[int, _Part] = {}  # ident -> part
        self.all: List[_Part] = []  # includes preregistered children
        self.holder: Optional[_Part] = None
        self.next_reg = 0
        self.switches = 0
        self.stalls = 0
        #: bumped on every wake-capable action (lock release, notify,
        #: thread start/death); arms one settle window before the next
        #: decision so a woken participant gets CPU to re-park
        self.wake_epoch = 0
        self.graced = -1

    # --- registration --------------------------------------------------------

    def _new_part_locked(self) -> _Part:
        p = _Part()
        p.ex = self
        p.reg = self.next_reg
        self.next_reg += 1
        p.gate = _Gate()
        p.blocked = 0
        p.ident = None
        self.all.append(p)
        return p

    def join_current(self) -> None:
        me = _thread.get_ident()
        with self.mtx:
            p = self._new_part_locked()
            p.ident = me
            self.parts[me] = p
            if self.holder is None:
                self.holder = p

    def adopt_child(self, thread: threading.Thread) -> None:
        """Preregister a thread at start() time (parent-side, so the
        candidate set is schedule-deterministic) and wrap its run() to
        deregister on exit."""
        with self.mtx:
            p = self._new_part_locked()
        thread._tpusan_part = p
        orig_run = thread.run
        ex = self

        def _run(*a, **k):
            try:
                return orig_run(*a, **k)
            finally:
                ex.deregister_current()

        thread.run = _run

    def current_part(self) -> Optional[_Part]:
        me = _thread.get_ident()
        p = self.parts.get(me)
        if p is not None:
            return p
        cur = _current_thread_obj()
        pre = getattr(cur, "_tpusan_part", None) if cur is not None else None
        if pre is not None and pre.ex is self:
            with self.mtx:
                cur = self.parts.get(me)
                if cur is None:
                    pre.ident = me
                    self.parts[me] = pre
                return self.parts[me]
        return None

    def deregister_current(self) -> None:
        me = _thread.get_ident()
        with self.mtx:
            p = self.parts.pop(me, None)
            if p is None:
                cur = _current_thread_obj()
                pre = (
                    getattr(cur, "_tpusan_part", None)
                    if cur is not None
                    else None
                )
                if pre is not None and pre.ex is self:
                    p = pre
            if p is None:
                return
            if p in self.all:
                self.all.remove(p)
            self.wake_epoch += 1  # death unblocks joiners
            if self.holder is p:
                self._pass_token_locked(p)

    # --- scheduling ----------------------------------------------------------

    def maybe_switch(self) -> None:
        if not self.active:
            return
        p = self.current_part()
        if p is None:
            return
        wait_needed = False
        for attempt in (0, 1):
            grace_epoch = None
            with self.mtx:
                if not self.active or p.blocked:
                    return
                if self.holder is None:
                    self.holder = p
                if self.holder is not p:
                    wait_needed = True
                    break
                if (
                    attempt == 0
                    and self.graced != self.wake_epoch
                    and any(q.blocked for q in self.all)
                ):
                    grace_epoch = self.wake_epoch
                else:
                    cands = [q for q in self.all if not q.blocked]
                    if len(cands) > 1:
                        cands.sort(key=lambda q: q.reg)
                        pick = self.rng.choice(cands)
                        if pick is not p:
                            self.holder = pick
                            pick.gate.set()
                            self.switches += 1
                            wait_needed = True
                    break
            # settle window (token retained; only real-block wakers and
            # free-runners can use it to reach their next sync point)
            (_orig_sleep or time.sleep)(self.GRACE)
            with self.mtx:
                self.graced = grace_epoch
        if wait_needed:
            self._wait_token(p)

    def note_wake(self) -> None:
        """Record a wake-capable action (lock release, notify, thread
        start/death). A participant blocked on the woken primitive needs
        GIL time to run block_end and re-park; without the settle window
        this re-arms, a holder in a tight loop would starve it and the
        candidate set would depend on OS scheduling, not the seed."""
        with self.mtx:
            self.wake_epoch += 1

    def block_begin(self) -> None:
        p = self.current_part()
        if p is None:
            return
        with self.mtx:
            p.blocked += 1
            if p.blocked == 1 and self.holder is p:
                self._pass_token_locked(p)

    def block_end(self) -> None:
        p = self.current_part()
        if p is None:
            return
        wait_needed = False
        with self.mtx:
            if p.blocked:
                p.blocked -= 1
            if not self.active:
                return
            if p.blocked == 0:
                if self.holder is None:
                    self.holder = p
                elif self.holder is not p:
                    wait_needed = True
        if wait_needed:
            self._wait_token(p)

    def _pass_token_locked(self, exclude: _Part) -> None:
        cands = [q for q in self.all if q is not exclude and not q.blocked]
        if not cands:
            self.holder = None
            return
        cands.sort(key=lambda q: q.reg)
        pick = self.rng.choice(cands)
        self.holder = pick
        pick.gate.set()
        self.switches += 1

    def _wait_token(self, p: _Part) -> None:
        while True:
            got = p.gate.wait(self.STALL_TIMEOUT)
            with self.mtx:
                if not self.active:
                    return
                if self.holder is p:
                    return
                if not got:
                    # failsafe: a participant wedged behind an
                    # uninstrumented blocking call degrades exploration
                    # instead of deadlocking the run
                    self.stalls += 1
                    self.holder = p
                    return
            # stale signal: the token was granted while this part was
            # still free-running (pre-first-sync) and has since moved
            # on; drain it and keep waiting

    def shutdown(self) -> None:
        with self.mtx:
            self.active = False
            self.holder = None
            for p in self.all:
                p.gate.set()
            self.all = []
            self.parts = {}


@contextlib.contextmanager
def explore_scope(seed: Optional[int] = None) -> Iterator[_Explorer]:
    """Serialize threads started under this scope through the seeded
    scheduler. Reentrant: a nested scope joins the active one."""
    global _explorer
    if _explorer is not None:
        yield _explorer
        return
    if seed is None:
        seed = _explore_seed if _explore_seed is not None else 0
    ex = _Explorer(seed)
    _explorer = ex
    ex.join_current()
    try:
        yield ex
    finally:
        _explorer = None
        ex.shutdown()


# --- install / report ---------------------------------------------------------


def install(mode: Optional[str] = None) -> None:
    """Patch the lock factories and IO probes; with mode ``hb`` or
    ``explore:<seed>`` also patch Thread.start/join, Condition.wait and
    queue.SimpleQueue and wrap registered classes. Idempotent and
    upgrade-only (install("hb") atop "1" adds hb; it never downgrades).
    Only locks created AFTER install are sanitized — install before
    importing the code under test (tests/conftest.py does)."""
    global _installed, _orig_lock, _orig_rlock
    global _orig_sleep, _orig_recv, _orig_accept, _orig_select
    global _explore_seed
    if mode is None:
        mode = os.environ.get(ENV, "") or "1"
    hb, seed = _parse_mode(mode)
    if not _installed:
        _orig_lock = threading.Lock
        _orig_rlock = threading.RLock
        threading.Lock = _make_lock
        threading.RLock = _make_rlock
        _orig_sleep = time.sleep
        time.sleep = _sleep
        _orig_recv = socket.socket.recv
        socket.socket.recv = _recv
        _orig_accept = socket.socket.accept
        socket.socket.accept = _accept
        _orig_select = selectors.DefaultSelector.select
        selectors.DefaultSelector.select = _select
        _installed = True
    if hb:
        _enable_hb()
    if seed is not None:
        _explore_seed = seed


def uninstall() -> None:
    global _installed, _explore_seed
    if not _installed:
        return
    _disable_hb()
    threading.Lock = _orig_lock
    threading.RLock = _orig_rlock
    time.sleep = _orig_sleep
    socket.socket.recv = _orig_recv
    socket.socket.accept = _orig_accept
    selectors.DefaultSelector.select = _orig_select
    _explore_seed = None
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    """Drop recorded edges/violations/races (test isolation). Bumping
    the generation lazily invalidates every thread and lock clock."""
    global _hb_gen, _next_tid, _next_foreign_tid
    with _state_mtx:
        _edges.clear()
        _io_violations.clear()
        _known_sites.clear()
        _vars.clear()
        _races.clear()
        _hb_gen += 1
        _next_tid = 1
        _next_foreign_tid = _FOREIGN_TID_BASE


def _find_cycles(
    edges: Dict[Tuple[str, str], Tuple[str, str]]
) -> List[List[str]]:
    """Elementary cycles in the site graph (one representative path per
    strongly-connected component with a cycle). Self-edges are excluded
    at record time, so every reported cycle spans >= 2 sites."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}

    def dfs(node: str, path: List[str]) -> None:
        color[node] = GREY
        path.append(node)
        for nxt in sorted(graph[node]):
            if color[nxt] == GREY:
                i = path.index(nxt)
                cyc = path[i:]
                canon = tuple(sorted(cyc))
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(cyc + [nxt])
            elif color[nxt] == WHITE:
                dfs(nxt, path)
        path.pop()
        color[node] = BLACK

    for node in sorted(graph):
        if color[node] == WHITE:
            dfs(node, [])
    return cycles


def _race_sort_key(r: dict):
    return (r["cls"], r["attr"], r["first"][:3], r["second"][:3])


def report() -> Dict[str, Any]:
    """Snapshot of findings: ``{"cycles": [...], "io_under_lock": [...],
    "races": [...], "edges": N, "sites": N, "tracked_vars": N}``."""
    with _state_mtx:
        edges = dict(_edges)
        io = dict(_io_violations)
        nsites = len(_known_sites)
        races = [dict(r) for r in _races.values()]
        nvars = len(_vars)
    cycles = _find_cycles(edges)
    races.sort(key=_race_sort_key)
    return {
        "cycles": cycles,
        "io_under_lock": [
            {"io": kind, "held": list(sites), "thread": who}
            for (kind, sites), who in sorted(io.items())
        ],
        "races": races,
        "edges": len(edges),
        "sites": nsites,
        "tracked_vars": nvars,
    }


def _format_race(r: dict) -> str:
    def top(acc):
        return "%s:%d" % (acc[2][0][0], acc[2][0][1]) if acc[2] else "<unknown>"

    def held(acc):
        return ", ".join(acc[3]) if acc[3] else "none"

    a, b = r["first"], r["second"]
    lines = [
        "DATA RACE: %s.%s: %s by %s at %s vs %s by %s at %s"
        % (r["cls"], r["attr"], a[0], a[1], top(a), b[0], b[1], top(b)),
        "  no happens-before path orders these accesses",
        "  locks held: first [%s]; second [%s]" % (held(a), held(b)),
    ]
    for label, acc in (("first (%s)" % a[0], a), ("second (%s)" % b[0], b)):
        lines.append("  %s stack:" % label)
        for fn, ln, func in acc[2]:
            lines.append("    %s:%d in %s" % (fn, ln, func))
    return "\n".join(lines) + "\n"


def race_report() -> str:
    """Just the DATA RACE blocks, byte-stable for a given schedule —
    what the same-seed replay test compares."""
    return "".join(_format_race(r) for r in report()["races"])


def print_report(stream=None) -> int:
    """Human report; returns cycles + races (CI fails on > 0 in the
    respective stage). ``LOCK-ORDER CYCLE`` and ``DATA RACE`` are the
    grep targets for CI."""
    out = stream if stream is not None else sys.stderr
    snap = report()
    for cyc in snap["cycles"]:
        out.write("LOCK-ORDER CYCLE: " + " -> ".join(cyc) + "\n")
    for r in snap["races"]:
        out.write(_format_race(r))
    for v in snap["io_under_lock"]:
        out.write(
            "IO-UNDER-LOCK (report-only): %s while holding [%s] in %s\n"
            % (v["io"], ", ".join(v["held"]), v["thread"])
        )
    if not snap["cycles"] and not snap["races"] and not snap["io_under_lock"]:
        out.write(
            "tpusan: no lock-order cycles, no data races "
            f"({snap['sites']} lock sites, {snap['edges']} order edges, "
            f"{snap['tracked_vars']} tracked vars)\n"
        )
    return len(snap["cycles"]) + len(snap["races"])
