"""Fault-injection hooks for the accelerator verification path.

Jepsen-style injected faults prove the device health state machine
(ops/device_policy.py) actually degrades and recovers: the signature
engines call :func:`fire` at each device dispatch site, and an
installed :class:`FaultPlan` decides — per call — whether to inject
latency, raise a transient error shape, or raise a permanent one.

Sites currently instrumented:

- ``ed25519.chunk``  — one CHUNK-size kernel dispatch in
  ops/ed25519_batch._run_chunk
- ``ed25519.collect`` — materialization of a dispatched chunk's result
- ``sr25519.chunk``  — one kernel dispatch in ops/sr25519_batch

When no plan is installed the hook is a single global read — zero
overhead on the hot path. Plans are process-global and thread-safe
(device dispatch happens from scheduler threads, the consensus state
loop, and tests concurrently).

Plans can be driven three ways:

- declaratively: ``FaultPlan(fail_from=3, fail_count=2)`` fails the 3rd
  and 4th matching calls (raise-on-Nth-call);
- imperatively: ``plan.kill()`` / ``plan.revive()`` flip a switch so a
  chaos driver can take the device down and bring it back mid-run;
- from the environment: ``TENDERMINT_TPU_FAULTS="site=ed25519;
  fail_from=1;fail_count=5;permanent=0;latency=0.01"`` installs a plan
  at import — the seam the e2e harness uses to inject faults into
  subprocess nodes.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterable, Optional, Set


class DeviceFault(RuntimeError):
    """Injected device error. ``permanent`` mirrors the shape of a
    backend-init failure vs a flaky launch; device_policy classifies on
    the attribute, so injected faults never depend on message text.
    ``device`` (an optional device id) mirrors a fault attributable to
    one chip of a mesh; parallel/mesh.attribute_device reads it."""

    def __init__(
        self,
        message: str = "injected device fault",
        permanent: bool = False,
        device: Optional[int] = None,
    ):
        super().__init__(message)
        self.permanent = permanent
        self.device = device


class FaultPlan:
    """One installed fault schedule.

    ``site`` is a prefix filter (``"ed25519"`` matches both the chunk
    and collect sites; None matches every site). Matching calls are
    counted; a call fails when its 1-based index is in ``fail_calls``,
    falls in [``fail_from``, ``fail_from + fail_count``), or the plan
    has been imperatively :meth:`kill`-ed. ``latency`` seconds are
    injected before every matching call, failing or not.
    """

    def __init__(
        self,
        site: Optional[str] = None,
        fail_calls: Iterable[int] = (),
        fail_from: Optional[int] = None,
        fail_count: int = 0,
        permanent: bool = False,
        latency: float = 0.0,
        error_factory: Optional[Callable[[], BaseException]] = None,
    ):
        self.site = site
        self.fail_calls: Set[int] = set(fail_calls)
        self.fail_from = fail_from
        self.fail_count = fail_count
        self.permanent = permanent
        self.latency = latency
        self.error_factory = error_factory
        self._mtx = threading.Lock()
        self._failing = False  # imperative kill/revive switch
        self.calls = 0
        self.faults_raised = 0

    # --- imperative chaos driver ---------------------------------------------

    def kill(self) -> None:
        """Every matching call fails until revive()."""
        with self._mtx:
            self._failing = True

    def revive(self) -> None:
        with self._mtx:
            self._failing = False

    @property
    def killed(self) -> bool:
        with self._mtx:
            return self._failing

    # --- hook ---------------------------------------------------------------

    def _matches(self, site: str) -> bool:
        return self.site is None or site.startswith(self.site)

    def on_call(self, site: str) -> None:
        if not self._matches(site):
            return
        with self._mtx:
            self.calls += 1
            idx = self.calls
            fail = self._failing or idx in self.fail_calls
            if (
                not fail
                and self.fail_from is not None
                and self.fail_from <= idx < self.fail_from + self.fail_count
            ):
                fail = True
            if fail:
                self.faults_raised += 1
        if self.latency > 0:
            time.sleep(self.latency)
        if fail:
            if self.error_factory is not None:
                raise self.error_factory()
            raise DeviceFault(
                f"injected {'permanent' if self.permanent else 'transient'} "
                f"fault at {site} call #{idx}",
                permanent=self.permanent,
            )


_PLAN: Optional[FaultPlan] = None
_PLAN_MTX = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    global _PLAN
    with _PLAN_MTX:
        _PLAN = plan
    return plan


def uninstall() -> None:
    global _PLAN
    with _PLAN_MTX:
        _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def fire(site: str) -> None:
    """The per-dispatch hook the engines call. No-op without a plan."""
    plan = _PLAN
    if plan is not None:
        plan.on_call(site)


@contextmanager
def inject(**plan_kwargs):
    """Scoped installation for tests::

        with fault_injection.inject(site="ed25519", fail_from=1,
                                    fail_count=2) as plan:
            ...
    """
    plan = install(FaultPlan(**plan_kwargs))
    try:
        yield plan
    finally:
        uninstall()


def _parse_env_plan(spec: str) -> FaultPlan:
    """``key=value`` pairs separated by ``;`` (see module docstring)."""
    kwargs: dict = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        value = value.strip()
        if key == "site":
            kwargs["site"] = value or None
        elif key == "fail_calls":
            kwargs["fail_calls"] = [int(v) for v in value.split(",") if v]
        elif key == "fail_from":
            kwargs["fail_from"] = int(value)
        elif key == "fail_count":
            kwargs["fail_count"] = int(value)
        elif key == "permanent":
            kwargs["permanent"] = value not in ("0", "false", "")
        elif key == "latency":
            kwargs["latency"] = float(value)
        else:
            raise ValueError(f"unknown fault-plan key {key!r}")
    return FaultPlan(**kwargs)


def install_from_env(env_var: str = "TENDERMINT_TPU_FAULTS") -> Optional[FaultPlan]:
    spec = os.environ.get(env_var, "")
    if not spec:
        return None
    return install(_parse_env_plan(spec))


install_from_env()
