"""RFC-6962 Merkle trees and proofs.

Mirrors the reference semantics (crypto/merkle/tree.go, hash.go,
proof.go): SHA-256, leaf prefix 0x00, inner prefix 0x01, split point =
largest power of two strictly less than n, empty tree = SHA256("").
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"
HASH_SIZE = 32


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def empty_hash() -> bytes:
    return _sha256(b"")


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def get_split_point(n: int) -> int:
    """Largest power of two strictly less than n (crypto/merkle/tree.go:94)."""
    if n < 1:
        raise ValueError("n must be >= 1")
    k = 1 << (n.bit_length() - 1)
    if k == n:
        k >>= 1
    return k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """crypto/merkle.HashFromByteSlices, iteratively."""
    n = len(items)
    if n == 0:
        return empty_hash()
    hashes = [leaf_hash(item) for item in items]
    # Bottom-up combine respecting the RFC-6962 split structure: combining
    # pairs left-to-right per level reproduces the recursive split because
    # the split point is the largest power of two < n.
    return _hash_level(hashes)


def _hash_level(hashes: List[bytes]) -> bytes:
    n = len(hashes)
    if n == 1:
        return hashes[0]
    k = get_split_point(n)
    return inner_hash(_hash_level(hashes[:k]), _hash_level(hashes[k:]))


def hash_from_map(m: dict) -> bytes:
    """Deterministic map hash: keys sorted, each leaf a length-delimited
    (key, value) pair so distinct maps cannot collide. Keys must be str or
    bytes; values bytes."""
    from tendermint_tpu.encoding.proto import length_delimited

    items = []
    for key in sorted(m, key=lambda k: k.encode() if isinstance(k, str) else k):
        if isinstance(key, str):
            kb = key.encode()
        elif isinstance(key, bytes):
            kb = key
        else:
            raise TypeError(f"map key must be str or bytes, got {type(key)}")
        items.append(length_delimited(kb) + length_delimited(m[key]))
    return hash_from_byte_slices(items)


@dataclass
class Proof:
    """Merkle inclusion proof (crypto/merkle/proof.go:22-103)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    MAX_AUNTS = 100

    def verify(self, root_hash: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or len(self.aunts) > self.MAX_AUNTS:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = self.compute_root_hash()
        return computed is not None and computed == root_hash

    def compute_root_hash(self) -> Optional[bytes]:
        return _compute_hash_from_aunts(
            self.index, self.total, self.leaf_hash, self.aunts
        )


def _compute_hash_from_aunts(
    index: int, total: int, leaf: bytes, aunts: List[bytes]
) -> Optional[bytes]:
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        if aunts:
            return None
        return leaf
    if not aunts:
        return None
    k = get_split_point(total)
    if index < k:
        left = _compute_hash_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_hash_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(
    items: Sequence[bytes],
) -> Tuple[bytes, List[Proof]]:
    """Root hash + proof per item (crypto/merkle/proof.go ProofsFromByteSlices)."""
    n = len(items)
    leaf_hashes = [leaf_hash(item) for item in items]
    if n == 0:
        return empty_hash(), []
    proofs = [Proof(total=n, index=i, leaf_hash=leaf_hashes[i]) for i in range(n)]

    def build(lo: int, hi: int) -> bytes:
        if hi - lo == 1:
            return leaf_hashes[lo]
        k = get_split_point(hi - lo)
        left = build(lo, lo + k)
        right = build(lo + k, hi)
        for i in range(lo, lo + k):
            proofs[i].aunts.append(right)
        for i in range(lo + k, hi):
            proofs[i].aunts.append(left)
        return inner_hash(left, right)

    root = build(0, n)
    return root, proofs


# --- proof operators (crypto/merkle/proof_op.go) ----------------------------


class ProofOperator:
    """One step in a chained proof: run(values) -> values for the next op."""

    def run(self, values: List[bytes]) -> List[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        raise NotImplementedError


class ValueOp(ProofOperator):
    """Leaf value inclusion op (crypto/merkle/proof_value.go): proves
    key=>value is in the tree with the given root."""

    TYPE = "simple:v"

    def __init__(self, key: bytes, proof: Proof):
        self.key = key
        self.proof = proof

    def run(self, values: List[bytes]) -> List[bytes]:
        if len(values) != 1:
            raise ValueError("ValueOp expects one value")
        vhash = _sha256(values[0])
        # leaf is the kv pair encoding: len-prefixed key + len-prefixed vhash
        from tendermint_tpu.encoding.proto import length_delimited

        kv = length_delimited(self.key) + length_delimited(vhash)
        if leaf_hash(kv) != self.proof.leaf_hash:
            raise ValueError("leaf hash mismatch")
        root = self.proof.compute_root_hash()
        if root is None:
            raise ValueError("bad proof")
        return [root]

    def get_key(self) -> bytes:
        return self.key


class ProofOperators:
    """Chain of operators verified outer-to-inner
    (crypto/merkle/proof_op.go:47-87)."""

    def __init__(self, ops: List[ProofOperator]):
        self.ops = ops

    def verify_value(self, root: bytes, keypath: List[bytes], value: bytes) -> None:
        self.verify(root, keypath, [value])

    def verify(self, root: bytes, keypath: List[bytes], args: List[bytes]) -> None:
        keys = list(keypath)
        for op in self.ops:
            key = op.get_key()
            if key:
                if not keys or keys[-1] != key:
                    raise ValueError(f"key mismatch on {key!r}")
                keys.pop()
            args = op.run(args)
        if args != [root]:
            raise ValueError("computed root does not match")
        if keys:
            raise ValueError("keypath not fully consumed")
