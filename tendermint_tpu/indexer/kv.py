"""KV event sink: tx + block indexing for /tx_search and /block_search.

The reference indexes txs and block events into a KV store behind the
``EventSink`` interface (internal/state/indexer/sink/kv/kv.go,
indexer/tx/kv/): tx results keyed by hash, plus composite-key event
index entries ``<key>/<value>/<height>/<index>`` enabling query-driven
search. This implementation keeps the same key discipline over the
storage/kv.py abstraction so any backend (MemDB or persistent) works.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs.pubsub import Query
from tendermint_tpu.storage.kv import KVStore

_TX_HASH_PREFIX = b"tx.hash/"
_TX_HEIGHT_PREFIX = b"tx.height/"
_TX_EVENT_PREFIX = b"txevt/"
_BLOCK_EVENT_PREFIX = b"blkevt/"
_BLOCK_HEIGHT_KEY = b"blk.height/"


@dataclass
class TxResult:
    """Indexed transaction (proto abci.TxResult analog)."""

    height: int
    index: int
    tx: bytes
    result: abci.ExecTxResult

    def hash(self) -> bytes:
        return hashlib.sha256(self.tx).digest()

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "height": self.height,
                "index": self.index,
                "tx": self.tx.hex(),
                "code": self.result.code,
                "data": self.result.data.hex(),
                "log": self.result.log,
                "gas_wanted": self.result.gas_wanted,
                "gas_used": self.result.gas_used,
                "events": [
                    {
                        "type": e.type,
                        "attributes": [
                            {"key": a.key, "value": a.value, "index": a.index}
                            for a in e.attributes
                        ],
                    }
                    for e in (self.result.events or [])
                ],
            }
        ).encode()

    @staticmethod
    def from_json(raw: bytes) -> "TxResult":
        d = json.loads(raw.decode())
        return TxResult(
            height=d["height"],
            index=d["index"],
            tx=bytes.fromhex(d["tx"]),
            result=abci.ExecTxResult(
                code=d["code"],
                data=bytes.fromhex(d["data"]),
                log=d["log"],
                gas_wanted=d["gas_wanted"],
                gas_used=d["gas_used"],
                events=[
                    abci.Event(
                        type=e["type"],
                        attributes=[
                            abci.EventAttribute(
                                key=a["key"], value=a["value"], index=a["index"]
                            )
                            for a in e["attributes"]
                        ],
                    )
                    for e in d["events"]
                ],
            ),
        )


def _evt_key(prefix: bytes, key: str, value: str, height: int, index: int) -> bytes:
    return prefix + (
        f"{key}/{value}/{height:020d}/{index:010d}".encode()
    )


def _prefix_end(prefix: bytes) -> bytes:
    """Smallest byte string greater than every key with this prefix."""
    p = bytearray(prefix)
    while p and p[-1] == 0xFF:
        p.pop()
    if not p:
        return b"\xff" * (len(prefix) + 1)
    p[-1] += 1
    return bytes(p)


def _iter_prefix(db: KVStore, prefix: bytes):
    return db.iterator(prefix, _prefix_end(prefix))


def _num_cond_matches(cond, val: float) -> bool:
    try:
        bound = float(cond.value)
    except ValueError:
        return False
    return (
        (cond.op == "=" and val == bound)
        or (cond.op == "<" and val < bound)
        or (cond.op == "<=" and val <= bound)
        or (cond.op == ">" and val > bound)
        or (cond.op == ">=" and val >= bound)
    )


class KVIndexer:
    """Tx + block event index over a KV store."""

    def __init__(self, db: KVStore):
        self.db = db

    # -- indexing -------------------------------------------------------------

    def _put_block_events(self, batch, height: int, events) -> None:
        batch.set(
            _BLOCK_HEIGHT_KEY + f"{height:020d}".encode(), str(height).encode()
        )
        for ev in events or []:
            if not ev.type:
                continue
            for attr in ev.attributes or []:
                if not attr.index:
                    continue
                batch.set(
                    _evt_key(
                        _BLOCK_EVENT_PREFIX,
                        f"{ev.type}.{attr.key}",
                        attr.value,
                        height,
                        0,
                    ),
                    str(height).encode(),
                )

    def _put_tx(self, batch, tr: "TxResult") -> None:
        h = tr.hash()
        batch.set(_TX_HASH_PREFIX + h, tr.to_json())
        batch.set(
            _evt_key(
                _TX_EVENT_PREFIX, "tx.height", str(tr.height), tr.height, tr.index
            ),
            h,
        )
        for ev in tr.result.events or []:
            if not ev.type:
                continue
            for attr in ev.attributes or []:
                if not attr.index:
                    continue
                batch.set(
                    _evt_key(
                        _TX_EVENT_PREFIX,
                        f"{ev.type}.{attr.key}",
                        attr.value,
                        tr.height,
                        tr.index,
                    ),
                    h,
                )

    def index_block_events(self, height: int, events: List[abci.Event]) -> None:
        batch = self.db.new_batch()
        self._put_block_events(batch, height, events)
        batch.write()

    def index_txs(self, results: Iterable[TxResult]) -> None:
        batch = self.db.new_batch()
        for tr in results:
            self._put_tx(batch, tr)
        batch.write()

    def index_finalized_block(self, height: int, txs, fres) -> None:
        """Index one decided block — block events plus per-tx results —
        in a SINGLE batch (one durable write per height). The one shared
        entry point for the live node (node._fire_events) and the
        offline reindex-event rebuild, so the two paths cannot diverge.
        ``fres`` is the ABCI ResponseFinalizeBlock."""
        txs = list(txs)
        batch = self.db.new_batch()
        self._put_block_events(batch, height, fres.events)
        for i, r in enumerate(fres.tx_results):
            if i >= len(txs):
                break
            self._put_tx(
                batch, TxResult(height=height, index=i, tx=txs[i], result=r)
            )
        batch.write()

    # -- queries --------------------------------------------------------------

    def get_tx(self, tx_hash: bytes) -> Optional[TxResult]:
        raw = self.db.get(_TX_HASH_PREFIX + tx_hash)
        return TxResult.from_json(raw) if raw is not None else None

    def search_txs(self, query: Query, limit: int = 100) -> List[TxResult]:
        """AND-of-conditions search; full records decoded only for the
        first ``limit`` matches (see search_tx_keys)."""
        keys = self.search_tx_keys(query)
        out = []
        for _, _, h in keys[:limit]:
            tr = self.get_tx(h)
            if tr is not None:
                out.append(tr)
        return out

    def search_tx_keys(self, query: Query) -> List[tuple]:
        """AND-of-conditions search mirroring tx/kv/kv.go: each condition
        produces a hash set from its index range; results are the
        intersection as sorted (height, index, hash) triples. (height,
        index) come from the index keys themselves, so paginating callers
        can count and order ALL matches without decoding any record —
        only the requested page pays get_tx (the reference pushes
        pagination into the kv sink the same way, tx/kv/kv.go)."""
        positions: dict = {}

        def _note(h: bytes, k: bytes) -> None:
            if h not in positions:
                tail = k.rsplit(b"/", 2)
                if len(tail) == 3:
                    try:
                        positions[h] = (int(tail[1]), int(tail[2]))
                        return
                    except ValueError:
                        pass
                positions[h] = None

        hash_sets: List[set] = []
        for cond in query.conditions:
            hashes = set()
            # tm.event is implicit in this index: every indexed entry IS
            # a Tx event (reference kv indexer special-cases it, tx/kv).
            if cond.key == "tm.event":
                if cond.op == "=" and cond.value != "Tx":
                    return []
                continue
            if cond.key == "tx.hash" and cond.op == "=":
                try:
                    h = bytes.fromhex(cond.value)
                except ValueError:
                    return []
                tr = self.get_tx(h)
                if tr is not None:
                    positions[h] = (tr.height, tr.index)
                    hash_sets.append({h})
                else:
                    hash_sets.append(set())
                continue
            if cond.op == "=":
                prefix = _TX_EVENT_PREFIX + f"{cond.key}/{cond.value}/".encode()
                for k, v in _iter_prefix(self.db, prefix):
                    h = bytes(v)
                    hashes.add(h)
                    _note(h, k)
            elif cond.op in ("<", "<=", ">", ">="):
                prefix = _TX_EVENT_PREFIX + f"{cond.key}/".encode()
                bound = float(cond.value)
                for k, v in _iter_prefix(self.db, prefix):
                    parts = k[len(prefix) :].rsplit(b"/", 2)
                    if len(parts) != 3:
                        continue
                    try:
                        val = float(parts[0])
                    except ValueError:
                        continue
                    if (
                        (cond.op == "<" and val < bound)
                        or (cond.op == "<=" and val <= bound)
                        or (cond.op == ">" and val > bound)
                        or (cond.op == ">=" and val >= bound)
                    ):
                        h = bytes(v)
                        hashes.add(h)
                        _note(h, k)
            elif cond.op == "CONTAINS":
                prefix = _TX_EVENT_PREFIX + f"{cond.key}/".encode()
                for k, v in _iter_prefix(self.db, prefix):
                    parts = k[len(prefix) :].rsplit(b"/", 2)
                    if len(parts) == 3 and cond.value.encode() in parts[0]:
                        h = bytes(v)
                        hashes.add(h)
                        _note(h, k)
            elif cond.op == "EXISTS":
                prefix = _TX_EVENT_PREFIX + f"{cond.key}/".encode()
                for k, v in _iter_prefix(self.db, prefix):
                    h = bytes(v)
                    hashes.add(h)
                    _note(h, k)
            hash_sets.append(hashes)
        if not hash_sets:
            # query was only tm.event = 'Tx': all indexed txs
            common = set()
            for k, v in _iter_prefix(self.db, _TX_EVENT_PREFIX + b"tx.height/"):
                h = bytes(v)
                common.add(h)
                _note(h, k)
        else:
            common = set.intersection(*hash_sets)
        triples = []
        for h in common:
            pos = positions.get(h)
            if pos is None:
                tr = self.get_tx(h)  # rare: unparseable key tail
                if tr is None:
                    continue
                pos = (tr.height, tr.index)
            triples.append((pos[0], pos[1], h))
        triples.sort()
        return triples

    def search_block_heights(self, query: Query, limit: int = 100) -> List[int]:
        height_sets: List[set] = []
        for cond in query.conditions:
            heights = set()
            if cond.key == "tm.event":
                if cond.op == "=" and cond.value != "NewBlock":
                    return []
                continue
            if cond.key == "block.height":
                prefix = _BLOCK_HEIGHT_KEY
                for _, v in _iter_prefix(self.db, prefix):
                    hv = int(v.decode())
                    if _num_cond_matches(cond, hv):
                        heights.add(hv)
                height_sets.append(heights)
                continue
            if cond.op == "=":
                prefix = _BLOCK_EVENT_PREFIX + f"{cond.key}/{cond.value}/".encode()
                for _, v in _iter_prefix(self.db, prefix):
                    heights.add(int(v.decode()))
            else:
                prefix = _BLOCK_EVENT_PREFIX + f"{cond.key}/".encode()
                for k, v in _iter_prefix(self.db, prefix):
                    parts = k[len(prefix) :].rsplit(b"/", 2)
                    if len(parts) != 3:
                        continue
                    sval = parts[0].decode()
                    if cond.op == "EXISTS":
                        heights.add(int(v.decode()))
                        continue
                    if cond.op == "CONTAINS":
                        if cond.value in sval:
                            heights.add(int(v.decode()))
                        continue
                    try:
                        val = float(sval)
                        bound = float(cond.value)
                    except ValueError:
                        continue
                    if (
                        (cond.op == "<" and val < bound)
                        or (cond.op == "<=" and val <= bound)
                        or (cond.op == ">" and val > bound)
                        or (cond.op == ">=" and val >= bound)
                    ):
                        heights.add(int(v.decode()))
            height_sets.append(heights)
        if not height_sets:
            # query was only tm.event = 'NewBlock': every stored height
            heights = set()
            for _, v in _iter_prefix(self.db, _BLOCK_HEIGHT_KEY):
                heights.add(int(v.decode()))
            return sorted(heights)[:limit]
        return sorted(set.intersection(*height_sets))[:limit]
