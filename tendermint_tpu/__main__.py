"""``python -m tendermint_tpu`` → operator CLI (cmd/tendermint/main.go)."""

from tendermint_tpu.cli import main

raise SystemExit(main())
