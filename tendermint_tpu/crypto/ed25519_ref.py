"""Pure-Python Ed25519 with ZIP-215 verification semantics.

This is the host-side reference implementation: the correctness oracle for
the TPU batch verifier in :mod:`tendermint_tpu.ops` and the fallback path
for sub-threshold batches.

Semantics mirror the reference framework's crypto layer, which verifies
with ZIP-215 rules (reference: crypto/ed25519/ed25519.go:24-29, using
curve25519-voi ``VerifyOptionsZIP_215``):

- ``s`` must be canonical (``s < L``); reject otherwise.
- ``A`` and ``R`` are decompressed *liberally*: the y-coordinate canonicity
  check of RFC 8032 section 5.1.3 is omitted (encodings with ``y >= p`` are
  accepted and reduced mod p). The ``x == 0 && sign == 1`` rejection of
  RFC 8032 decoding is kept. Small-order and mixed-order points are
  accepted.
- The *cofactored* verification equation is used:
  ``[8][s]B == [8]R + [8][k]A`` with ``k = SHA512(R || A || M) mod L``.

Signing / key generation follow RFC 8032 exactly (as the reference does:
its PrivKey.Sign defers to the standard Ed25519 signing flow).

A fast path uses the ``cryptography`` package when available: a signature
accepted by a strict cofactorless RFC 8032 verifier is always accepted by
the cofactored ZIP-215 verifier (multiply the cofactorless equation by 8),
so we only fall back to the slow pure-Python path on rejection, which for
honest traffic is the rare case.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

# --- curve constants -------------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

# Base point: y = 4/5, x recovered with even parity... sign bit 0 means even.
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> Optional[int]:
    """RFC 8032 5.1.3 x-recovery (y already reduced mod p). None if invalid."""
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # candidate root of u/v
    x = u * pow(v, 3, P) % P * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    vx2 = v * x * x % P
    if vx2 == u:
        pass
    elif vx2 == (-u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign == 1:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
assert _BX is not None

# --- extended twisted Edwards point arithmetic (python ints) ---------------
# Point = (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z.

IDENT = (0, 1, 1, 0)
B_POINT = (_BX, _BY, 1, _BX * _BY % P)
_2D = 2 * D % P


def pt_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    Bv = (Y1 + X1) * (Y2 + X2) % P
    C = T1 * _2D % P * T2 % P
    Dv = 2 * Z1 * Z2 % P
    E = Bv - A
    F = Dv - C
    G = Dv + C
    H = Bv + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_double(p):
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    Bv = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + Bv
    E = H - (X1 + Y1) * (X1 + Y1)
    G = A - Bv
    F = C + G
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_neg(p):
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def pt_mul(k: int, p) -> Tuple[int, int, int, int]:
    q = IDENT
    while k > 0:
        if k & 1:
            q = pt_add(q, p)
        p = pt_double(p)
        k >>= 1
    return q


def pt_equal(p, q) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def pt_is_identity(p) -> bool:
    X, Y, Z, _ = p
    return X % P == 0 and (Y - Z) % P == 0


def pt_compress(p) -> bytes:
    X, Y, Z, _ = p
    zinv = pow(Z, P - 2, P)
    x = X * zinv % P
    y = Y * zinv % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def pt_decompress_liberal(b: bytes):
    """ZIP-215 decompression: no y-canonicity check. None if not on curve."""
    if len(b) != 32:
        return None
    n = int.from_bytes(b, "little")
    sign = n >> 255
    y = (n & ((1 << 255) - 1)) % P
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def pt_decompress_canonical(b: bytes):
    """Strict RFC 8032 decompression (rejects y >= p)."""
    n = int.from_bytes(b, "little")
    if (n & ((1 << 255) - 1)) >= P:
        return None
    return pt_decompress_liberal(b)


# --- scalars ---------------------------------------------------------------


def sc_reduce(b: bytes) -> int:
    return int.from_bytes(b, "little") % L


def _sha512(*parts: bytes) -> bytes:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return h.digest()


def _clamp(h32: bytes) -> int:
    a = bytearray(h32)
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


# --- keygen / sign / verify ------------------------------------------------


def pubkey_from_seed(seed: bytes) -> bytes:
    a = _clamp(_sha512(seed)[:32])
    return pt_compress(pt_mul(a, B_POINT))


def keypair_from_seed(seed: bytes) -> Tuple[bytes, bytes]:
    """Returns (privkey64, pubkey32) in the reference's 64-byte privkey
    layout: seed || pubkey (reference: crypto/ed25519/ed25519.go:76-82)."""
    pub = pubkey_from_seed(seed)
    return seed + pub, pub


def generate_keypair() -> Tuple[bytes, bytes]:
    return keypair_from_seed(os.urandom(32))


def sign(privkey64: bytes, msg: bytes) -> bytes:
    seed, pub = privkey64[:32], privkey64[32:]
    h = _sha512(seed)
    a = _clamp(h[:32])
    prefix = h[32:]
    r = sc_reduce(_sha512(prefix, msg))
    r_point = pt_mul(r, B_POINT)
    r_bytes = pt_compress(r_point)
    k = sc_reduce(_sha512(r_bytes, pub, msg))
    s = (r + k * a) % L
    return r_bytes + int.to_bytes(s, 32, "little")


def verify_zip215_slow(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """Pure-Python ZIP-215 cofactored verification. The oracle."""
    if len(sig) != 64 or len(pubkey) != 32:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    a_point = pt_decompress_liberal(pubkey)
    if a_point is None:
        return False
    r_point = pt_decompress_liberal(sig[:32])
    if r_point is None:
        return False
    k = sc_reduce(_sha512(sig[:32], pubkey, msg))
    # [8]([s]B - R - [k]A) == identity
    diff = pt_add(pt_mul(s, B_POINT), pt_neg(pt_add(r_point, pt_mul(k, a_point))))
    for _ in range(3):
        diff = pt_double(diff)
    return pt_is_identity(diff)


try:  # fast cofactorless pre-check via the cryptography package
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey as _FastPub,
    )
    from cryptography.exceptions import InvalidSignature as _InvalidSig

    _HAVE_FAST = True
except Exception:  # pragma: no cover
    _HAVE_FAST = False


def verify_zip215(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 verification with a fast strict-verifier pre-pass.

    Strict cofactorless acceptance implies cofactored acceptance, so only
    rejections need the slow liberal re-check.
    """
    if _HAVE_FAST and len(pubkey) == 32 and len(sig) == 64:
        try:
            _FastPub.from_public_bytes(pubkey).verify(sig, msg)
            return True
        except (_InvalidSig, ValueError):
            pass
        except Exception:
            pass
    return verify_zip215_slow(pubkey, msg, sig)
