"""tpulint: the project-specific static-analysis suite.

Run as ``python -m scripts.analysis`` from the repo root. See
``scripts/analysis/README.md`` for the checker-code catalogue and
``core.py`` for the framework contract.
"""

from __future__ import annotations

from typing import Dict, List, Type

from scripts.analysis.core import (  # noqa: F401  (re-exported API)
    Checker,
    Finding,
    Module,
    Project,
    Runner,
    diff_baseline,
    load_baseline,
    load_modules,
    write_baseline,
)
from scripts.analysis.hygiene import HygieneChecker
from scripts.analysis.jaxpurity import JaxPurityChecker
from scripts.analysis.locks import LockDisciplineChecker
from scripts.analysis.metrics_checks import MetricsChecker
from scripts.analysis.taint import TaintChecker
from scripts.analysis.wire import WireCompatChecker

#: registration order is report order for equal path:line
CHECKERS: List[Type[Checker]] = [
    LockDisciplineChecker,
    JaxPurityChecker,
    WireCompatChecker,
    HygieneChecker,
    MetricsChecker,
    TaintChecker,
]


def checker_registry() -> Dict[str, Type[Checker]]:
    return {c.name: c for c in CHECKERS}
