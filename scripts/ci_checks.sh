#!/usr/bin/env bash
# Repo CI gate: byte-compile, static metrics audit, tier-1 tests.
#
# The tier-1 line is the ROADMAP.md "Tier-1 verify" command verbatim —
# keep the two in sync. DOTS_PASSED is the per-test pass count the
# driver compares against the seed.
set -u

rc_total=0

echo "== compileall =="
python -m compileall -q tendermint_tpu tests scripts bench.py || rc_total=1

echo "== check_metrics =="
python scripts/check_metrics.py || rc_total=1

echo "== tier-1 pytest =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && rc_total=1

exit $rc_total
