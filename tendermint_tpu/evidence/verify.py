"""Evidence verification (internal/evidence/verify.go).

Both checks end in signature verification against historical validator
sets — the third call site of the batch crypto boundary (SURVEY.md §2.1).
"""

from __future__ import annotations

from tendermint_tpu.light.verifier import DEFAULT_TRUST_LEVEL
from tendermint_tpu.types.evidence import (
    DuplicateVoteEvidence,
    LightClientAttackEvidence,
)
from tendermint_tpu.types.light import SignedHeader
from tendermint_tpu.types.validation import (
    verify_commit_light,
    verify_commit_light_trusting,
)
from tendermint_tpu.types.validator_set import ValidatorSet


class InvalidEvidenceError(ValueError):
    pass


def verify_duplicate_vote(
    e: DuplicateVoteEvidence, chain_id: str, val_set: ValidatorSet
) -> None:
    """internal/evidence/verify.go:203-256."""
    _, val = val_set.get_by_address(e.vote_a.validator_address)
    if val is None:
        raise InvalidEvidenceError(
            f"address {e.vote_a.validator_address.hex()} was not a validator "
            f"at height {e.height()}"
        )
    pub_key = val.pub_key
    if (
        e.vote_a.height != e.vote_b.height
        or e.vote_a.round != e.vote_b.round
        or e.vote_a.type != e.vote_b.type
    ):
        raise InvalidEvidenceError("h/r/s does not match")
    if e.vote_a.validator_address != e.vote_b.validator_address:
        raise InvalidEvidenceError("validator addresses do not match")
    if e.vote_a.block_id == e.vote_b.block_id:
        raise InvalidEvidenceError(
            "block IDs are the same - not a real duplicate vote"
        )
    if pub_key.address() != e.vote_a.validator_address:
        raise InvalidEvidenceError("address doesn't match pubkey")
    # Evidence arrives on concurrent paths (RPC handler threads,
    # per-peer reactor delivery): ed25519 verifies go through the shared
    # accumulate-with-deadline scheduler so simultaneous submissions
    # share one device batch (crypto/scheduler.py); other key types
    # verify inline.
    ok_a, ok_b = _verify_pair(
        pub_key,
        e.vote_a.sign_bytes(chain_id),
        e.vote_a.signature,
        e.vote_b.sign_bytes(chain_id),
        e.vote_b.signature,
    )
    if not ok_a:
        raise InvalidEvidenceError("verifying VoteA: invalid signature")
    if not ok_b:
        raise InvalidEvidenceError("verifying VoteB: invalid signature")


def _verify_pair(pub_key, msg_a, sig_a, msg_b, sig_b):
    from tendermint_tpu.crypto.keys import ED25519_KEY_TYPE

    if pub_key.type == ED25519_KEY_TYPE:
        try:
            from tendermint_tpu.crypto.batch import get_shared_scheduler

            sched = get_shared_scheduler()
            pk = pub_key.bytes()
            # submit both, then wait: one flush covers the pair
            ha = sched.submit(pk, msg_a, sig_a)
            hb = sched.submit(pk, msg_b, sig_b)
            return sched.wait(ha), sched.wait(hb)
        except RuntimeError:
            pass  # scheduler stopped: fall through to inline verify
    return (
        pub_key.verify_signature(msg_a, sig_a),
        pub_key.verify_signature(msg_b, sig_b),
    )


def verify_light_client_attack(
    e: LightClientAttackEvidence,
    common_header: SignedHeader,
    trusted_header: SignedHeader,
    common_vals: ValidatorSet,
) -> None:
    """internal/evidence/verify.go:160-196."""
    if common_header.height != e.conflicting_block.height:
        # Lunatic attack: single trusting jump from the common header.
        try:
            verify_commit_light_trusting(
                trusted_header.chain_id,
                common_vals,
                e.conflicting_block.signed_header.commit,
                DEFAULT_TRUST_LEVEL,
            )
        except ValueError as err:
            raise InvalidEvidenceError(
                f"skipping verification of conflicting block failed: {err}"
            ) from err
    elif e.conflicting_header_is_invalid(trusted_header.header):
        raise InvalidEvidenceError(
            "common height is the same as conflicting block height so expected "
            "the conflicting block to be correctly derived yet it wasn't"
        )
    try:
        verify_commit_light(
            trusted_header.chain_id,
            e.conflicting_block.validator_set,
            e.conflicting_block.signed_header.commit.block_id,
            e.conflicting_block.height,
            e.conflicting_block.signed_header.commit,
        )
    except ValueError as err:
        raise InvalidEvidenceError(
            f"invalid commit from conflicting block: {err}"
        ) from err
    if e.conflicting_block.height > trusted_header.height:
        if (
            e.conflicting_block.signed_header.header.time.to_unix_ns()
            > trusted_header.header.time.to_unix_ns()
        ):
            raise InvalidEvidenceError(
                "conflicting block doesn't violate monotonically increasing time"
            )
    elif trusted_header.hash() == e.conflicting_block.hash():
        raise InvalidEvidenceError(
            "trusted header hash matches the evidence's conflicting header hash"
        )
