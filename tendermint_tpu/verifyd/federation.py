"""Verifyd federation: digest-routed shards, client-side routing.

One verifyd per host was the ceiling: every co-located caller funneled
into a single process and resident precompute tables REPLICATED across
the mesh, so the aggregate device-table budget never grew with the
fleet. This module scales the verification tier out: N verifyd shards
(same host first; the addresses generalise to multi-host) with
**client-side consistent-hash routing keyed by validator-set digest**.

Routing key. ``note_validator_set`` (forwarded from
``crypto/batch.note_validator_set``) digests each activated committee
(sha256 over its sorted pubkeys) and remembers which digest owns each
key. A verify batch is partitioned by owning digest — every lane of a
committee rides to the SAME shard, so that shard's ``note_hot_keys``
pinning sees the committee repeatedly and pins exactly its slice of
resident tables. Keys never seen in a committee route by their own
pk digest. Partitioned, not replicated: each shard's resident tensor
holds a disjoint slice and the fleet's aggregate table budget grows
linearly with shard count (PR 18's introspect ledger shows it, owner
``resident_tables`` on device and ``resident_tables_host`` on CPU).

Failover ladder. On a shed (RESOURCE_EXHAUSTED after the shard
client's own shed-retry budget) or a dead shard (transport failure),
the group's keys re-route with jittered exponential backoff down the
ladder: next shard in the ring's preference order for that digest,
then the host oracle as the last rung — never a silent drop. A dead
shard is quarantined for ``dead_retry_s`` and re-probed; every
membership flip bumps ``route_epoch`` (protocol field 10) so servers
can count stale-map misroutes.

Transports. Each shard gets its own ``VerifydClient``; the existing
shm negotiation (PR 13) makes the LOCAL shard ride the slab ring and
remote shards ride TCP, with the 17-byte trace context (PR 15) on
every hop so ``scripts/trace_merge.py`` attributes cross-shard latency.

Health gossip. ``refresh()`` polls each shard's STATS_PATH snapshot
(brownout level, tenant SLO view, pinned slice) and ``stats()`` merges
the per-shard tenant views into ONE fleet view — a tenant's ``p99_ms``
is the fleet max and its ``slo_sheds`` the fleet sum, so an SLO budget
spans the fleet instead of resetting per shard.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
from bisect import bisect_right
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.sanitizer import instrument_attrs
from tendermint_tpu.verifyd.client import (
    VerifydClient,
    VerifydRejectedError,
    VerifydUnavailableError,
    _host_verify,
    current_class,
)
from tendermint_tpu.verifyd.protocol import (
    ALGO_ED25519,
    CLASS_RPC,
    DEFAULT_TENANT,
)

SHARDS_ENV = "TENDERMINT_TPU_VERIFY_SHARDS"

# virtual nodes per shard on the hash ring: enough that a 2-4 shard
# fleet splits key space near-evenly, cheap enough to rebuild on every
# membership change
DEFAULT_VNODES = 64

# quarantine after a transport failure before the shard is re-probed
DEFAULT_DEAD_RETRY_S = 2.0

# first-rung failover pause; doubles per rung, jittered, deadline-capped
DEFAULT_FAILOVER_BACKOFF_S = 0.02

# pk -> owning-digest index bound: a federation client tracking more
# distinct keys than this rebuilds from scratch (committees rotate;
# unbounded growth would be a leak, stale entries only cost locality)
_OWNER_INDEX_CAP = 16384

# gossip snapshot bounds: a misbehaving shard's STATS reply must not be
# able to balloon every peer's fleet view. Oversized snapshots are
# dropped whole (and counted in gossip_rejects) rather than truncated —
# a partial health view is worse than a missing one.
MAX_GOSSIP_TENANTS = 1024  # tenant entries per snapshot
MAX_GOSSIP_SNAPSHOT_BYTES = 256 * 1024  # JSON-encoded snapshot size


def _hash64(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest()[:8], "big")


def digest_validator_set(pubkeys: Sequence[bytes]) -> bytes:
    """The routing key of one committee: sha256 over its SORTED pubkeys
    (order-independent — the same set always yields the same digest, so
    the same shard, regardless of vote order)."""
    h = hashlib.sha256()
    for pk in sorted(bytes(p) for p in pubkeys):
        h.update(pk)
    return h.digest()


class HashRing:
    """Consistent-hash ring over shard ids with virtual nodes.

    ``preference(key)`` is the failover ladder order: the vnode walk
    from the key's ring position, deduplicated to distinct shards.
    Because a key's walk never changes, removing a shard moves ONLY
    that shard's keys (each to its next rung) — the minimal-remap
    property the federation tests pin.
    """

    def __init__(self, shard_ids: Sequence[int], vnodes: int = DEFAULT_VNODES):
        if not shard_ids:
            raise ValueError("hash ring needs at least one shard")
        self.shard_ids = tuple(sorted(set(int(s) for s in shard_ids)))
        self.vnodes = max(1, int(vnodes))
        points: List[Tuple[int, int]] = []
        for sid in self.shard_ids:
            for v in range(self.vnodes):
                points.append((_hash64(b"shard:%d:%d" % (sid, v)), sid))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]

    def preference(self, key: bytes) -> List[int]:
        """Distinct shard ids in ring-walk order from ``key``'s
        position — index 0 is the primary, the rest the failover order."""
        start = bisect_right(self._hashes, _hash64(key))
        seen: List[int] = []
        n = len(self._points)
        for i in range(n):
            sid = self._points[(start + i) % n][1]
            if sid not in seen:
                seen.append(sid)
                if len(seen) == len(self.shard_ids):
                    break
        return seen

    def route(self, key: bytes, dead: Optional[set] = None) -> int:
        """Primary shard for ``key`` among live shards: the first rung
        of ``preference`` not in ``dead``. With every shard dead the
        primary is returned anyway — the caller's ladder will fail it
        over to the host oracle."""
        pref = self.preference(key)
        if dead:
            for sid in pref:
                if sid not in dead:
                    return sid
        return pref[0]


@instrument_attrs
class FederationClient:
    """Client-side router over N verifyd shards.

    Call shape matches ``VerifydClient.verify`` — (pks, msgs, sigs) ->
    List[bool] — so it drops into every verify_fn seam. Lanes are
    partitioned by owning validator-set digest, each group rides its
    primary shard, and failures walk the ladder (next shard -> host
    oracle) with jittered backoff. Verdicts merge back in submission
    order; every lane gets a verdict or an explicit fallback — never a
    silent drop.
    """

    def __init__(
        self,
        shards: Sequence[str],
        tenant: str = DEFAULT_TENANT,
        slo_ms: int = 0,
        timeout: float = 10.0,
        shm: Optional[str] = None,
        vnodes: int = DEFAULT_VNODES,
        dead_retry_s: float = DEFAULT_DEAD_RETRY_S,
        failover_backoff_s: float = DEFAULT_FAILOVER_BACKOFF_S,
        shed_retries: int = 1,
    ):
        addrs = [a.strip() for a in shards if a and a.strip()]
        if not addrs:
            raise ValueError("federation needs at least one shard address")
        self.tenant = tenant or DEFAULT_TENANT
        self.dead_retry_s = dead_retry_s
        self.failover_backoff_s = failover_backoff_s
        self._clients: List[VerifydClient] = [
            VerifydClient(
                addr,
                timeout=timeout,
                # the federation owns the ladder: a shard client must
                # surface sheds/deaths instead of host-falling-back
                # itself, or keys would silently stop re-routing
                fallback=False,
                tenant=self.tenant,
                slo_ms=slo_ms,
                shm=shm,
                shard_id=i,
                # one in-place shed retry per shard; further patience is
                # the ladder's call (other shards may be idle)
                shed_retries=shed_retries,
            )
            for i, addr in enumerate(addrs)
        ]
        self.ring = HashRing(range(len(addrs)), vnodes=vnodes)
        self._mtx = threading.Lock()
        # shard id -> monotonic re-probe time; present = quarantined
        self._dead: Dict[int, float] = {}  # guarded-by: _mtx
        # pk -> owning validator-set digest (routing locality index)
        self._owner: Dict[bytes, bytes] = {}  # guarded-by: _mtx
        # bumped on every membership flip; rides protocol field 10
        self.route_epoch = 1  # guarded-by: _mtx
        # last refresh()'s per-shard gossip snapshots (health view)
        self._gossip: Dict[int, dict] = {}  # guarded-by: _mtx
        # counters (tests/bench introspection)
        self.routed_calls = 0  # guarded-by: _mtx
        self.failovers = 0  # guarded-by: _mtx
        self.rerouted_lanes = 0  # guarded-by: _mtx
        self.host_fallback_lanes = 0  # guarded-by: _mtx
        self.gossip_rejects = 0  # guarded-by: _mtx
        self._push_epoch(self.route_epoch)

    # --- membership ---------------------------------------------------------

    def _push_epoch(self, epoch: int) -> None:
        for c in self._clients:
            c.route_epoch = epoch

    def _bump_epoch_locked(self) -> None:
        self.route_epoch += 1
        self._push_epoch(self.route_epoch)

    def _mark_dead(self, sid: int) -> None:
        with self._mtx:
            if sid not in self._dead:
                self._bump_epoch_locked()
            self._dead[sid] = time.monotonic() + self.dead_retry_s
        tracing.instant("federation_shard_dead", shard=sid)

    def _mark_alive(self, sid: int) -> None:
        with self._mtx:
            if self._dead.pop(sid, None) is not None:
                self._bump_epoch_locked()
                tracing.instant("federation_shard_alive", shard=sid)

    def _dead_set(self) -> set:
        """Quarantined shards whose re-probe time has NOT passed; an
        expired quarantine lets the shard take primary traffic again
        (the probe — success revives it, failure re-quarantines)."""
        now = time.monotonic()
        with self._mtx:
            return {s for s, t in self._dead.items() if now < t}

    def alive_shards(self) -> List[int]:
        dead = self._dead_set()
        return [i for i in range(len(self._clients)) if i not in dead]

    # --- routing ------------------------------------------------------------

    def note_validator_set(self, pubkeys: Sequence[bytes]) -> bytes:
        """Register a committee: its digest becomes the routing key of
        every member, so a later mixed batch keeps whole committees on
        one shard. Returns the digest (tests pin determinism)."""
        keys = [bytes(p) for p in pubkeys]
        digest = digest_validator_set(keys)
        with self._mtx:
            if len(self._owner) + len(keys) > _OWNER_INDEX_CAP:
                # rotation churn outgrew the index: locality resets,
                # correctness doesn't (unknown keys route by pk digest)
                self._owner.clear()
            for pk in keys:
                self._owner[pk] = digest
        return digest

    def routing_key(self, pk: bytes) -> bytes:
        pk = bytes(pk)
        with self._mtx:
            return self._owner.get(pk, pk)

    def shard_for(self, pk: bytes) -> int:
        """Primary shard for one key right now (tests/bench)."""
        return self.ring.route(self.routing_key(pk), dead=self._dead_set())

    # --- the verify seam ----------------------------------------------------

    def verify(
        self,
        pks: Sequence[bytes],
        msgs: Sequence[bytes],
        sigs: Sequence[bytes],
        *,
        algo: int = ALGO_ED25519,
        klass: Optional[int] = None,
        kind: Optional[int] = None,
        deadline: Optional[float] = None,
    ) -> List[bool]:
        if not pks:
            return []
        if klass is None:
            klass = current_class()
            if klass is None:
                klass = CLASS_RPC
        # partition lanes by routing key digest, preserving submission
        # order inside each group so verdicts merge back positionally
        groups: Dict[bytes, List[int]] = {}
        for i, pk in enumerate(pks):
            groups.setdefault(self.routing_key(pk), []).append(i)
        verdicts: List[bool] = [False] * len(pks)

        def dispatch(key: bytes, idxs: List[int]) -> None:
            out = self._verify_group(
                key,
                [pks[i] for i in idxs],
                [msgs[i] for i in idxs],
                [sigs[i] for i in idxs],
                algo=algo,
                klass=klass,
                kind=kind,
                deadline=deadline,
            )
            # disjoint index slices per group: no write overlaps
            for i, v in zip(idxs, out):
                verdicts[i] = v

        items = list(groups.items())
        with tracing.span(
            "federation_verify", lanes=len(pks), groups=len(items)
        ):
            if len(items) > 1 and len(self._clients) > 1:
                # a mixed batch spans committees that live on DIFFERENT
                # shards: dispatching the groups concurrently is what
                # makes aggregate throughput scale with the fleet
                # instead of serializing on one client thread
                # (_verify_group never raises, so no cross-thread
                # error plumbing is needed)
                workers = [
                    threading.Thread(
                        target=dispatch, args=(k, ix), daemon=True
                    )
                    for k, ix in items
                ]
                for t in workers:
                    t.start()
                for t in workers:
                    t.join()
            else:
                for k, ix in items:
                    dispatch(k, ix)
        return verdicts

    def _verify_group(
        self,
        key: bytes,
        pks: List[bytes],
        msgs: List[bytes],
        sigs: List[bytes],
        *,
        algo: int,
        klass: int,
        kind: Optional[int],
        deadline: Optional[float],
    ) -> List[bool]:
        """One routing group down the ladder: preference-ordered shards
        (alive first, quarantined last-resort), jittered backoff between
        rungs, host oracle at the bottom. Raising is not an option —
        every lane leaves with a verdict."""
        t0 = time.monotonic()
        budget = deadline if deadline is not None else self._clients[0].timeout
        pref = self.ring.preference(key)
        dead = self._dead_set()
        # alive shards first in ring order, then quarantined ones as a
        # desperation rung before the host oracle (a stale quarantine
        # beats burning host CPU when the shard already recovered)
        ladder = [s for s in pref if s not in dead] + [
            s for s in pref if s in dead
        ]
        delay = self.failover_backoff_s
        for rung, sid in enumerate(ladder):
            remaining = budget - (time.monotonic() - t0)
            if remaining <= 0:
                break
            client = self._clients[sid]
            try:
                out = client.verify(
                    pks, msgs, sigs,
                    algo=algo, klass=klass, kind=kind, deadline=remaining,
                )
            except VerifydUnavailableError:
                self._mark_dead(sid)
            except VerifydRejectedError as exc:
                # a shed (or expired deadline) from a live shard: the
                # shard is up but browning out — walk the ladder
                tracing.instant(
                    "federation_reroute",
                    shard=sid,
                    status=exc.status,
                    lanes=len(pks),
                )
            else:
                self._mark_alive(sid)
                with self._mtx:
                    self.routed_calls += 1
                    if rung > 0:
                        self.failovers += 1
                        self.rerouted_lanes += len(pks)
                return out
            # jittered exponential backoff before the next rung,
            # bounded by the remaining budget
            remaining = budget - (time.monotonic() - t0)
            pause = min(
                delay * (0.5 + random.random() * 0.5), max(0.0, remaining)
            )
            delay *= 2
            if pause > 0:
                time.sleep(pause)
        # last rung: the host oracle — slower, sound, never sheds
        with self._mtx:
            self.host_fallback_lanes += len(pks)
        with tracing.span("federation_host_fallback", lanes=len(pks)):
            return _host_verify(algo, pks, msgs, sigs)

    @property
    def verify_fn(self) -> Callable[..., List[bool]]:
        return self.verify

    # --- gossip / fleet stats ----------------------------------------------

    def refresh(self, timeout: float = 2.0) -> Dict[int, dict]:
        """Poll every shard's STATS_PATH snapshot: health, brownout
        level, tenant SLO view, pinned slice. A shard that answers is
        revived; one that doesn't is quarantined. Returns the per-shard
        snapshots (shard id -> gossip dict, absent = unreachable)."""
        snaps: Dict[int, dict] = {}
        for sid, client in enumerate(self._clients):
            try:
                snap = client.server_stats(timeout=timeout)
            except VerifydUnavailableError:
                self._mark_dead(sid)
                continue
            # the shard answered, so it is alive either way; but an
            # oversized snapshot is dropped before it can reach the
            # merged fleet view
            self._mark_alive(sid)
            try:
                # tpuflow: sanitized=_sanitize_snapshot raises on
                # snapshots over MAX_GOSSIP_TENANTS entries or
                # MAX_GOSSIP_SNAPSHOT_BYTES encoded bytes
                snaps[sid] = self._sanitize_snapshot(snap)
            except ValueError:
                with self._mtx:
                    self.gossip_rejects += 1
        with self._mtx:
            self._gossip = dict(snaps)
        return snaps

    @staticmethod
    def _sanitize_snapshot(snap: dict) -> dict:
        """Bound one shard's gossip snapshot before it joins the fleet
        view; raises ValueError when any cap is exceeded."""
        if not isinstance(snap, dict):
            raise ValueError("gossip snapshot is not a dict")
        tenants = snap.get("tenants")
        if isinstance(tenants, dict) and len(tenants) > MAX_GOSSIP_TENANTS:
            raise ValueError(
                f"gossip snapshot lists {len(tenants)} tenants "
                f"> {MAX_GOSSIP_TENANTS}"
            )
        encoded = len(json.dumps(snap, default=str))
        if encoded > MAX_GOSSIP_SNAPSHOT_BYTES:
            raise ValueError(
                f"gossip snapshot {encoded}B > {MAX_GOSSIP_SNAPSHOT_BYTES}B"
            )
        return snap

    def fleet_tenants(self) -> Dict[str, Dict[str, float]]:
        """Merge the last refresh()'s per-shard tenant views into ONE
        fleet view: ``p99_ms`` is the fleet max (the budget verdict a
        tenant actually experiences), counters (``slo_sheds``, ``sheds``,
        ``lanes``, ``host_direct``) sum, ``slo_ms`` keeps the tightest
        declared target, and ``slo_shedding`` is true if ANY shard is
        currently shedding the tenant."""
        with self._mtx:
            gossip = dict(self._gossip)
        fleet: Dict[str, Dict[str, float]] = {}
        for snap in gossip.values():
            tenants = snap.get("tenants")
            if not isinstance(tenants, dict):
                continue
            for label, ts in tenants.items():
                if not isinstance(ts, dict):
                    continue
                agg = fleet.setdefault(
                    label,
                    {
                        "p99_ms": 0.0,
                        "slo_ms": 0,
                        "slo_sheds": 0,
                        "slo_shedding": 0,
                        "sheds": 0,
                        "lanes": 0,
                        "host_direct": 0,
                    },
                )
                agg["p99_ms"] = max(agg["p99_ms"], ts.get("p99_ms", 0.0))
                slo = int(ts.get("slo_ms", 0) or 0)
                if slo and (not agg["slo_ms"] or slo < agg["slo_ms"]):
                    agg["slo_ms"] = slo
                for k in ("slo_sheds", "sheds", "lanes", "host_direct"):
                    agg[k] += int(ts.get(k, 0) or 0)
                if ts.get("slo_shedding"):
                    agg["slo_shedding"] = 1
        return fleet

    def stats(self) -> dict:
        """Fleet snapshot: router counters + per-shard client stats +
        the merged tenant view (the closed rung of ROADMAP item 5 —
        a tenant's SLO accounting spans the fleet)."""
        with self._mtx:
            dead = set(self._dead)
            gossip = dict(self._gossip)
            out = {
                "shards": len(self._clients),
                "route_epoch": self.route_epoch,
                "routed_calls": self.routed_calls,
                "failovers": self.failovers,
                "rerouted_lanes": self.rerouted_lanes,
                "host_fallback_lanes": self.host_fallback_lanes,
                "gossip_rejects": self.gossip_rejects,
                "owner_index_keys": len(self._owner),
            }
        per_shard = []
        for sid, client in enumerate(self._clients):
            snap = gossip.get(sid) or {}
            per_shard.append(
                {
                    "shard_id": sid,
                    "addr": client.addr,
                    "alive": sid not in dead,
                    "transport": client.transport,
                    "client": client.stats(),
                    "brownout": snap.get("brownout"),
                }
            )
        out["per_shard"] = per_shard
        out["fleet_tenants"] = self.fleet_tenants()
        return out

    def memstats_rows(self, timeout: float = 2.0) -> Dict[str, dict]:
        """Fleet roll-up rows for ``ops.introspect.set_fleet_provider``:
        one row per reachable shard, carrying the shard's device-byte
        ledger under the SAME owner labels as the local ledger plus its
        pinned-slice summary — so ``/debug/memstats`` and ``verifyd
        stats`` show partitioned vs replicated placement at a glance."""
        rows: Dict[str, dict] = {}
        for sid, snap in self.refresh(timeout=timeout).items():
            stats = snap.get("stats") if isinstance(snap, dict) else None
            stats = stats if isinstance(stats, dict) else {}
            resident = snap.get("resident") if isinstance(snap, dict) else None
            resident = resident if isinstance(resident, dict) else {}
            rows["shard%d" % sid] = {
                "addr": self._clients[sid].addr,
                "device_bytes": stats.get("device_bytes") or {},
                "pinned_keys": resident.get("pinned_keys", 0),
                "host_staged_bytes": resident.get("host_staged_bytes", 0),
                "requests_served": stats.get("requests_served", 0),
                "misroutes": stats.get("misroutes", 0),
            }
        return rows

    def close(self) -> None:
        for client in self._clients:
            client.close()


# --- process-wide federation backend ----------------------------------------

_fed_mtx = threading.Lock()
_fed_shards: Tuple[str, ...] = ()  # config override; env consulted when empty
_fed_client: Optional[FederationClient] = None
_fed_client_key: Tuple[str, ...] = ()


def _parse_shards(spec: str) -> Tuple[str, ...]:
    return tuple(a.strip() for a in spec.split(",") if a.strip())


def set_federation(shards) -> None:
    """Config-driven shard list (node assembly / tests). Accepts a
    comma-separated string or a sequence of ``host:port``; empty
    clears the override (the env var still applies)."""
    global _fed_shards
    if isinstance(shards, str):
        parsed = _parse_shards(shards)
    else:
        parsed = tuple(a.strip() for a in (shards or ()) if a and a.strip())
    with _fed_mtx:
        _fed_shards = parsed


def reset_federation() -> None:
    """Drop the override AND the cached client (tests)."""
    global _fed_shards, _fed_client, _fed_client_key
    with _fed_mtx:
        _fed_shards = ()
        if _fed_client is not None:
            _fed_client.close()
        _fed_client = None
        _fed_client_key = ()


def federation_backend() -> Optional[Callable[..., List[bool]]]:
    """The configured federation's verify_fn, or None when fewer than
    two shards are configured (a single address is the plain remote
    client's job — ``client.remote_backend``)."""
    client = federation_client()
    return client.verify if client is not None else None


def federation_client() -> Optional[FederationClient]:
    """The process-wide FederationClient, cached and rebuilt when the
    shard list changes; None when unconfigured (< 2 shards)."""
    global _fed_client, _fed_client_key
    with _fed_mtx:
        shards = _fed_shards or _parse_shards(
            os.environ.get(SHARDS_ENV, "")
        )
        if len(shards) < 2:
            return None
        if _fed_client is None or _fed_client_key != shards:
            if _fed_client is not None:
                _fed_client.close()
            _fed_client = FederationClient(shards)
            _fed_client_key = shards
        return _fed_client


def note_validator_set(pubkeys: Sequence[bytes]) -> None:
    """Routing hook for ``crypto/batch.note_validator_set``: keep the
    committee's keys on one shard. No-op when unfederated."""
    client = federation_client()
    if client is not None:
        client.note_validator_set(pubkeys)
