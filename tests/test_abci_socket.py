"""ABCI socket transport: out-of-process apps (socket client/server).

The reference's socket transport tier (abci/client/socket_client.go,
abci/server/socket_server.go): the kvstore app runs as a SEPARATE OS
PROCESS; the node drives it over TCP. The crash-restart case kills the
app process and restarts it empty — the handshake must replay the chain
back into it (replay.go:204-550).
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.socket_client import SocketClient
from tendermint_tpu.abci.socket_server import SocketServer
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.node import Node, NodeConfig
from tendermint_tpu.privval import FilePV

from tests.test_node import fast_genesis, wait_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_app_process(tmp_path, db=""):
    """Run the kvstore ABCI server as a real OS process."""
    cmd = [
        sys.executable,
        "-m",
        "tendermint_tpu.abci.socket_server",
        "--addr",
        "127.0.0.1:0",
        "--app",
        "kvstore",
    ]
    if db:
        cmd += ["--db", db]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env
    )
    line = proc.stdout.readline()
    m = re.search(r"listening on ([\d.]+):(\d+)", line)
    assert m, f"no listen line: {line!r}"
    return proc, (m.group(1), int(m.group(2)))


class TestSocketTransport:
    def test_roundtrip_all_methods_in_process(self):
        server = SocketServer(KVStoreApplication(snapshot_interval=1))
        server.start()
        try:
            host, port = server.address
            client = SocketClient(host, port)
            client.start()
            assert client.echo("ping") == "ping"
            info = client.info(abci.RequestInfo())
            assert info.last_block_height == 0
            fres = client.finalize_block(
                abci.RequestFinalizeBlock(height=1, txs=[b"a=1", b"b=2"])
            )
            assert [r.code for r in fres.tx_results] == [0, 0]
            assert fres.app_hash
            client.commit()
            info = client.info(abci.RequestInfo())
            assert info.last_block_height == 1
            assert info.last_block_app_hash == fres.app_hash
            q = client.query(abci.RequestQuery(path="/key", data=b"a"))
            assert q.value == b"1"
            snaps = client.list_snapshots(abci.RequestListSnapshots())
            assert [s.height for s in snaps.snapshots] == [1]
            chunk = client.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(height=1, format=1, chunk=0)
            )
            assert chunk.chunk
            client.stop()
        finally:
            server.stop()

    def test_app_error_surfaces_not_kills_connection(self):
        class Exploding(KVStoreApplication):
            def query(self, req):
                raise RuntimeError("boom")

        server = SocketServer(Exploding())
        server.start()
        try:
            host, port = server.address
            client = SocketClient(host, port)
            client.start()
            with pytest.raises(RuntimeError, match="boom"):
                client.query(abci.RequestQuery(path="/key", data=b"x"))
            assert client.echo("still-alive") == "still-alive"
            client.stop()
        finally:
            server.stop()


class TestOutOfProcessNode:
    def _make_node(self, home, privs, client):
        os.makedirs(home, exist_ok=True)
        cfg = NodeConfig(
            chain_id="node-chain",
            home=home,
            blocksync=False,
            wal_enabled=True,
            db_backend="filedb",
        )
        return Node(cfg, fast_genesis(privs), client, priv_validator=privs[0])

    def test_node_commits_against_external_app_and_replays_after_kill(
        self, tmp_path
    ):
        home = str(tmp_path / "home")
        os.makedirs(home)
        privs = [FilePV.generate(home + "/pk.json", home + "/ps.json")]

        proc, (host, port) = spawn_app_process(tmp_path)
        node = None
        try:
            client = SocketClient(host, port)
            node = self._make_node(home, privs, client)
            node.start()
            node.submit_tx(b"color=red")
            assert wait_for(lambda: node.height >= 3, timeout=60), node.height
            h1 = node.height
            node.consensus.priv_validator = None
            node.stop()
            client.stop()
        finally:
            if node is not None and node._started:
                node.stop()
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)

        # App process is dead and its state is gone. A fresh app process
        # starts at height 0; the node handshake must replay it forward.
        proc2, (host2, port2) = spawn_app_process(tmp_path)
        node2 = None
        try:
            client2 = SocketClient(host2, port2)
            node2 = self._make_node(home, privs, client2)
            info = client2.info(abci.RequestInfo())
            assert info.last_block_height == node2.sm_state.last_block_height >= h1
            q = client2.query(abci.RequestQuery(path="/key", data=b"color"))
            assert q.value == b"red", "replayed app lost the committed tx"
            node2.start()
            assert wait_for(lambda: node2.height >= h1 + 2, timeout=60), node2.height
        finally:
            if node2 is not None:
                node2.consensus.priv_validator = None
                node2.stop()
            proc2.send_signal(signal.SIGKILL)
            proc2.wait(timeout=10)
